"""ZeRO-3 parameter NVMe swap (the ZeRO-Infinity param path).

Analog of the reference swap_tensor param machinery:
``AsyncPartitionedParameterSwapper`` (partitioned_param_swapper.py:36 —
per-param NVMe files, aligned pinned buffer pool, swap_in/swap_out with
async handles), ``AsyncTensorSwapper`` (async_swapper.py:19), and the
prefetch driven by the ZeRO-3 coordinator
(partitioned_param_coordinator.py:514 ``__prefetch_nvme_param_partitions``).

TPU-native shape: the engine's compiled ZeRO-3 path gathers per-layer params
inside one XLA program, which requires all shards resident in HBM.  When even
the shards don't fit (offload_param: nvme), the layer loop must leave the
compiled program: ``SwappedLayerTrainer`` streams one layer at a time —
NVMe -> host buffer (async, double-buffered) -> device -> compute -> drop —
with the backward pass re-fetching layers in reverse (ZeRO-Infinity
re-gathers params for backward rather than caching them).  Device memory is
bounded by ONE layer's params + activations of the micro-batch, regardless
of model depth.
"""

import os
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...ops.aio import build_aio_handle
from ...utils.logging import log_dist


class AsyncPartitionedParameterSwapper:
    """NVMe backing store for named param groups with a reusable host
    buffer pool and async prefetch.

    Protocol per key: ``swap_out(key, arrays)`` persists; ``swap_in_async(key)``
    starts reads into pool buffers; ``wait_in(key)`` joins and returns the
    arrays (buffers on loan); ``release(key)`` returns buffers to the pool.
    ``buffer_count`` bounds host memory exactly like the reference's
    aio buffer pool (swap_tensor/utils.py:37 MIN_AIO_BYTES pools).
    """

    def __init__(self, nvme_path: str, buffer_count: int = 4, aio_threads: int = 4,
                 use_odirect: bool = True):
        # O_DIRECT by default, like the reference's libaio queues
        # (deepspeed_aio_common.cpp): page-cache writeback throttling caps
        # buffered writes at ~100 MB/s on typical cloud VMs while direct IO
        # sustains the device's ~800 MB/s; tmpfs and other O_DIRECT-refusing
        # filesystems fall back per-file inside the library.
        self.dir = os.path.join(nvme_path, "dstpu_param_swap")
        os.makedirs(self.dir, exist_ok=True)
        self.aio = build_aio_handle(aio_threads, use_odirect=use_odirect)
        self.buffer_count = buffer_count
        self._free: List[np.ndarray] = []
        self._allocated = 0
        self._buf_bytes = 0
        self._manifest: Dict[str, List[tuple]] = {}   # key -> [(shape, dtype), ...]
        self._inflight: Dict[str, List[tuple]] = {}   # key -> [(rid, buffer, shape, dtype)]
        self._loaned: Dict[str, List[np.ndarray]] = {}

    # ------------------------------------------------------------ buffers
    # Accounting invariant: _allocated == loaned + in-flight + len(_free); it
    # only passes buffer_count via the warned growth path, so host memory is
    # bounded at ~buffer_count * max-leaf-bytes (the reference's pinned pool
    # contract, swap_tensor/utils.py:37).
    def _take_buffer(self, nbytes: int) -> np.ndarray:
        self._buf_bytes = max(self._buf_bytes, nbytes)
        for i in range(len(self._free) - 1, -1, -1):  # pool may hold mixed sizes
            if self._free[i].nbytes >= nbytes:
                return self._free.pop(i)
        if self._allocated >= self.buffer_count and self._free:
            # replace an undersized free buffer instead of growing the pool
            self._free.sort(key=lambda b: b.nbytes)
            self._free.pop(0)
            self._allocated -= 1
        if self._allocated >= self.buffer_count:
            # working set exceeded the configured pool: grow with a warning
            # rather than deadlocking the layer stream (reference asserts)
            from ...utils.logging import logger
            logger.warning(f"param swap pool grew beyond buffer_count={self.buffer_count}; "
                           f"consider raising offload_param.buffer_count")
        self._allocated += 1
        return np.empty(self._buf_bytes, np.uint8)

    # ------------------------------------------------------------ file ops
    def _file(self, key: str, i: int) -> str:
        return os.path.join(self.dir, f"{key.replace('/', '_')}.{i}.bin")

    def swap_out(self, key: str, arrays: Sequence[np.ndarray], wait: bool = True):
        """Persist a param group (async unless ``wait``)."""
        rids = []
        manifest = []
        for i, a in enumerate(arrays):
            a = np.asarray(a)
            manifest.append((a.shape, a.dtype))
            rids.append(self.aio.pwrite(self._file(key, i), a))
        self._manifest[key] = manifest
        if wait:
            for r in rids:
                self.aio.wait(r)
        return rids

    def swap_in_async(self, key: str):
        """Begin reading a group into pool buffers (the prefetch step)."""
        if key in self._inflight or key in self._loaned:
            return  # already prefetched / resident
        entries = []
        for i, (shape, dtype) in enumerate(self._manifest[key]):
            nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
            buf = self._take_buffer(nbytes)
            view = buf[:nbytes].view(dtype).reshape(shape)
            rid = self.aio.pread(self._file(key, i), view)
            entries.append((rid, buf, view))
        self._inflight[key] = entries

    def wait_in(self, key: str) -> List[np.ndarray]:
        """Join the prefetch (issuing it now if it wasn't) and loan the arrays."""
        if key not in self._inflight and key not in self._loaned:
            self.swap_in_async(key)
        if key in self._inflight:
            views = []
            for rid, buf, view in self._inflight.pop(key):
                self.aio.wait(rid)
                views.append((buf, view))
            self._loaned[key] = views
        return [view for _, view in self._loaned[key]]

    def release(self, key: str):
        """Return a group's buffers to the pool (reference
        remove_partition_and_release_buffers)."""
        for buf, _view in self._loaned.pop(key, []):
            self._free.append(buf)

    def available_swap_in_buffers(self) -> int:
        return len(self._free)


class SwappedLayerTrainer:
    """Layer-streamed training with NVMe-resident params (ZeRO-Infinity).

    ``layer_fn(params_l, x) -> x`` over ``num_layers`` homogeneous layers whose
    params live on NVMe; ``head_fn(head_params, x, batch) -> loss`` stays
    resident (embeddings/head are the reference's persistent params —
    persistence_threshold analog).  Forward streams layers 0..L-1 saving each
    layer's INPUT on host; backward streams L-1..0 re-fetching params,
    recomputing the layer forward under ``jax.vjp``, and stepping that layer's
    AdamW immediately (fp32 master + moments also NVMe-resident via the
    optimizer swapper pattern) so no full gradient tree ever materializes.
    """

    def __init__(self, layer_fn: Callable, num_layers: int, head_fn: Callable,
                 swapper: AsyncPartitionedParameterSwapper,
                 lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, compute_dtype=jnp.bfloat16,
                 stem_fn: Optional[Callable] = None,
                 optimizer_device: str = "nvme",
                 offload_activations: bool = False):
        """``stem_fn(stem_params, x) -> hidden`` is the optional trainable input
        transform (token embedding) ahead of the layer stack; its params stay
        DEVICE-resident like the head's, with a jitted AdamW (the reference
        keeps embeddings persistent via param_persistence_threshold).
        ``optimizer_device``: "nvme" streams Adam moments per layer alongside
        the params; "cpu" pins them in host RAM (the reference's
        offload_optimizer: cpu + offload_param: nvme combo — ZeRO-Infinity with
        moments one tier up, halving per-step disk traffic).
        ``offload_activations``: keep layer-input checkpoints on host instead of
        HBM (the reference's cpu_checkpointing; costs 2x activations over the
        host link per step — leave off unless HBM is the binding constraint)."""
        assert optimizer_device in ("nvme", "cpu")
        self.layer_fn = layer_fn
        self.num_layers = num_layers
        self.head_fn = head_fn
        self.stem_fn = stem_fn
        self.swapper = swapper
        self.compute_dtype = compute_dtype
        self._np_compute = np.dtype(compute_dtype)  # ml_dtypes-backed (bf16 ok)
        self.optimizer_device = optimizer_device
        self.offload_activations = offload_activations
        from ...ops.adam.cpu_adam import DeepSpeedCPUAdam
        self.opt = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay)
        self._default_lr = lr
        self.step_count = 0
        self._layer_treedef = None
        self._cpu_m: Optional[List[List[np.ndarray]]] = None  # [layer][leaf]
        self._cpu_v: Optional[List[List[np.ndarray]]] = None
        self._fwd_jit = jax.jit(lambda p, x: self.layer_fn(p, x))
        # backward recompute, compiled: (params, x, cotangent) -> (dparams, dx)
        self._bwd_jit = jax.jit(lambda p, x, ct: jax.vjp(self.layer_fn, p, x)[1](ct))

        def cast16(tree):
            return jax.tree_util.tree_map(lambda a: a.astype(compute_dtype), tree)

        # head loss+grads, compiled: the fp32 master head lives ON DEVICE and
        # casts to compute dtype INSIDE the jit (mixed-precision grads come
        # back fp32), so the 2 x vocab x hidden head tensors never cross the
        # host<->device link per step — that link is PCIe on real hardware but
        # a ~20 MB/s network relay under the axon tunnel
        self._head_jit = jax.jit(
            lambda h32, x, y: jax.value_and_grad(
                lambda hh, xx: self.head_fn(cast16(hh), xx, y), argnums=(0, 1))(h32, x))
        if stem_fn is not None:
            self._stem_jit = jax.jit(lambda sp32, x: stem_fn(cast16(sp32), x))
            self._stem_bwd_jit = jax.jit(
                lambda sp32, x, ct: jax.vjp(lambda sp: stem_fn(cast16(sp), x), sp32)[1](ct)[0])

        # device-resident AdamW for the persistent (head/stem) groups — same
        # decoupled-decay math as the host cpu_adam stepping the streamed layers
        b1, b2 = betas

        def persist_step(params, m, v, grads, lr_t, step_t):
            flat_p, tdef = jax.tree_util.tree_flatten(params)
            flat_m = jax.tree_util.tree_leaves(m)
            flat_v = jax.tree_util.tree_leaves(v)
            flat_g = jax.tree_util.tree_leaves(grads)
            new_p, new_m, new_v = [], [], []
            for p, mm, vv, g in zip(flat_p, flat_m, flat_v, flat_g):
                g = g.astype(jnp.float32)
                mm = b1 * mm + (1 - b1) * g
                vv = b2 * vv + (1 - b2) * g * g
                mhat = mm / (1 - jnp.power(b1, step_t))
                vhat = vv / (1 - jnp.power(b2, step_t))
                new_p.append(p - lr_t * (mhat / (jnp.sqrt(vhat) + eps) + weight_decay * p))
                new_m.append(mm)
                new_v.append(vv)
            unf = lambda leaves: jax.tree_util.tree_unflatten(tdef, leaves)
            return unf(new_p), unf(new_m), unf(new_v)

        self._persist_opt = jax.jit(persist_step, donate_argnums=(0, 1, 2))
        self._head_m = self._head_v = None
        self._stem_m = self._stem_v = None

    # ---------------------------------------------------------- initialize
    def init_from_stacked(self, stacked_params: Any, head_params: Any,
                          stem_params: Any = None):
        """Shard a [L, ...] stacked layer pytree onto NVMe (fp32 master +
        zero moments per layer) and keep head/stem params host-resident.
        One layer's worth of host copies at a time — broadcast-stacked or
        memmap'd leaves never materialize in full."""
        leaves, self._layer_treedef = jax.tree_util.tree_flatten(stacked_params)
        if self.optimizer_device == "cpu":
            self._cpu_m = [None] * self.num_layers
            self._cpu_v = [None] * self.num_layers
        for l in range(self.num_layers):
            layer = [np.asarray(leaf[l], np.float32) for leaf in leaves]
            rids = self.swapper.swap_out(self._pkey(l), layer, wait=False)
            if self.optimizer_device == "cpu":
                self._cpu_m[l] = [np.zeros_like(a) for a in layer]
                self._cpu_v[l] = [np.zeros_like(a) for a in layer]
            else:
                zeros = [np.zeros_like(a) for a in layer]
                rids += self.swapper.swap_out(self._mkey(l), zeros, wait=False)
                rids += self.swapper.swap_out(self._vkey(l), zeros, wait=False)
            # join per layer: unbounded in-flight writes would buffer every
            # layer's source arrays (they're host views into the stacked tree)
            for r in rids:
                self.swapper.aio.wait(r)
        # persistent groups: fp32 master ON DEVICE (uploaded once, not per step)
        self.head = jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float32), head_params)
        self.stem = (None if stem_params is None else
                     jax.tree_util.tree_map(lambda a: jnp.asarray(a, jnp.float32), stem_params))
        n = sum(int(np.prod(np.shape(x))) for x in leaves)
        log_dist(f"param nvme swap: {self.num_layers} layers, {n/1e6:.2f}M stacked elems "
                 f"on {self.swapper.dir} (moments: {self.optimizer_device})", ranks=[0])

    def _pkey(self, l):
        return f"layer{l}.p"

    def _mkey(self, l):
        return f"layer{l}.m"

    def _vkey(self, l):
        return f"layer{l}.v"

    def _device_params(self, host_leaves):
        """Upload one layer in COMPUTE dtype: the fp32->bf16 cast runs on host
        so half the bytes cross the host->device link (PCIe on real hardware;
        a ~20 MB/s network relay under the axon tunnel, where this halves the
        per-layer stream time)."""
        tree = jax.tree_util.tree_unflatten(self._layer_treedef, host_leaves)
        # astype always copies (even same-dtype): the source is a POOLED buffer
        # that recycles as soon as we release it — an uploaded view would race
        # the async transfer
        return jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a).astype(self._np_compute)), tree)

    def _zeros_like_tree(self, tree):
        return jax.tree_util.tree_map(lambda a: jnp.zeros_like(a), tree)

    # ---------------------------------------------------------- train step
    def train_step(self, batch: Dict[str, np.ndarray], lr: Optional[float] = None):
        """One full fwd+bwd+update with layer streaming.  Returns the loss."""
        lr_f = float(lr) if lr is not None else self._default_lr  # dslint: disable=host-sync-in-hot-path  # lr arrives as a host scalar (engine._host_lr); this float() is a no-op coercion, not a device fetch
        if self.stem_fn is not None:
            x_tokens = jnp.asarray(batch["x"])
            x = self._stem_jit(self.stem, x_tokens)
        else:
            x = jnp.asarray(batch["x"], self.compute_dtype)
        saved_inputs: List = [None] * self.num_layers

        # ---- forward: stream 0..L-1, double-buffered prefetch
        self.swapper.swap_in_async(self._pkey(0))
        for l in range(self.num_layers):
            # wait FIRST so layer l's buffer is the one recycled; prefetch l+1
            # unconditionally — it overlaps this layer's compute, and gating on
            # free buffers made layer 1's read synchronous every step
            host = self.swapper.wait_in(self._pkey(l))
            if l + 1 < self.num_layers:
                self.swapper.swap_in_async(self._pkey(l + 1))
            # activation checkpoint: HBM by default (L x micro x seq x hidden
            # bf16 — ~0.5 GB at 7B/seq2048/micro1); host when requested
            saved_inputs[l] = np.asarray(x) if self.offload_activations else x  # dslint: disable=host-sync-in-hot-path  # opt-in cpu_checkpointing: offloading the activation to host RAM is the feature
            x = self._fwd_jit(self._device_params(host), x)
            self.swapper.release(self._pkey(l))

        # ---- head loss + grads; head master/moments stay on device
        (loss, dhead, dx) = self._head_grads(self.head, x, batch)
        self.step_count += 1
        step = self.step_count
        if self._head_m is None:
            self._head_m = self._zeros_like_tree(self.head)
            self._head_v = self._zeros_like_tree(self.head)
        self.head, self._head_m, self._head_v = self._persist_opt(
            self.head, self._head_m, self._head_v, dhead,
            jnp.float32(lr_f), jnp.int32(step))

        # ---- backward: stream L-1..0, recompute layer fwd, step immediately
        for l in reversed(range(self.num_layers)):
            host = self.swapper.wait_in(self._pkey(l))
            if self.optimizer_device == "nvme":
                # moments overlap this layer's recompute (prefetch now, join
                # after the bwd_jit below)
                self.swapper.swap_in_async(self._mkey(l))
                self.swapper.swap_in_async(self._vkey(l))
            if l - 1 >= 0:
                self.swapper.swap_in_async(self._pkey(l - 1))
            params_dev = self._device_params(host)
            x_in = jnp.asarray(saved_inputs[l], self.compute_dtype)
            dparams, dx = self._bwd_jit(params_dev, x_in, dx.astype(self.compute_dtype))
            # this layer's optimizer state: RAM-resident (cpu) or streamed (nvme)
            if self.optimizer_device == "cpu":
                m_host, v_host = self._cpu_m[l], self._cpu_v[l]
            else:
                m_host = self.swapper.wait_in(self._mkey(l))
                v_host = self.swapper.wait_in(self._vkey(l))
            grads = [np.asarray(g, np.float32) for g in jax.tree_util.tree_leaves(dparams)]  # dslint: disable=host-sync-in-hot-path  # ZeRO-Infinity by design: the host CPU-Adam steps each streamed layer, so its grads must land on host
            for p, m, v, g in zip(host, m_host, v_host, grads):
                self.opt.step(p.ravel(), m.ravel(), v.ravel(), g.ravel(), lr=lr_f, step=step)
            # join THIS layer's writes (by rid — wait_all would orphan the
            # in-flight prefetch of layer l-1) before its buffers recycle: a
            # pooled buffer must not be overwritten mid-write, and the next
            # step's forward re-reads these files
            rids = self.swapper.swap_out(self._pkey(l), host, wait=False)
            if self.optimizer_device == "nvme":
                rids += self.swapper.swap_out(self._mkey(l), m_host, wait=False)
                rids += self.swapper.swap_out(self._vkey(l), v_host, wait=False)
            for r in rids:
                self.swapper.aio.wait(r)
            self.swapper.release(self._pkey(l))
            if self.optimizer_device == "nvme":
                self.swapper.release(self._mkey(l))
                self.swapper.release(self._vkey(l))

        # ---- stem (embedding) grads from the dx that reached layer 0's input
        if self.stem_fn is not None:
            dstem = self._stem_bwd_jit(self.stem, x_tokens, dx.astype(self.compute_dtype))
            if self._stem_m is None:
                self._stem_m = self._zeros_like_tree(self.stem)
                self._stem_v = self._zeros_like_tree(self.stem)
            self.stem, self._stem_m, self._stem_v = self._persist_opt(
                self.stem, self._stem_m, self._stem_v, dstem,
                jnp.float32(lr_f), jnp.int32(step))
        return float(loss)  # dslint: disable=host-sync-in-hot-path  # the step's one deliberate sync: the backward walk above already joined, and callers (engine nvme path) need the host loss

    def _head_grads(self, head32, x, batch):
        loss, grads = self._head_jit(head32, x, jnp.asarray(batch["y"]))
        return loss, grads[0], grads[1]

    # ------------------------------------------------------------- export
    def gather_stacked_params(self):
        """Re-stack the NVMe-resident fp32 master params into the [L, ...]
        host pytree they were initialized from — the zero_to_fp32 analog for
        the streamed path (reference utils/zero_to_fp32.py consolidates
        partitioned masters the same way, one shard at a time)."""
        per_layer = []
        for l in range(self.num_layers):
            host = self.swapper.wait_in(self._pkey(l))
            per_layer.append([np.array(a, np.float32) for a in host])
            self.swapper.release(self._pkey(l))
        stacked = [np.stack([per_layer[l][i] for l in range(self.num_layers)])
                   for i in range(len(per_layer[0]))]
        return jax.tree_util.tree_unflatten(self._layer_treedef, stacked)

    # ---------------------------------------------------------- inference
    def forward(self, x: np.ndarray):
        if self.stem_fn is not None:
            x = self._stem_jit(self.stem, jnp.asarray(x))
        else:
            x = jnp.asarray(x, self.compute_dtype)
        self.swapper.swap_in_async(self._pkey(0))
        for l in range(self.num_layers):
            host = self.swapper.wait_in(self._pkey(l))
            if l + 1 < self.num_layers:
                self.swapper.swap_in_async(self._pkey(l + 1))
            x = self._fwd_jit(self._device_params(host), x)
            self.swapper.release(self._pkey(l))
        return x
