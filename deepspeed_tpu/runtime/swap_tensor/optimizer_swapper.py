"""Optimizer-state offload: host RAM (cpu) or disk (nvme) via the aio library.

Analog of the reference swap_tensor stack (partitioned_optimizer_swapper.py:29,
async_swapper.py:19, aio buffer pools): fp32 master params + Adam moments live
OFF-device; the TPU holds only the bf16 compute copy.  The step pipeline is

  device grads -> host  ->  (nvme: swap-in moments)  ->  C++ cpu_adam step
  -> (nvme: async swap-out moments)  ->  updated master -> device bf16

For nvme, moments are written with the threaded aio handle while the next
leaf's compute proceeds — the reference's overlapped double-buffering
(pipelined_optimizer_swapper.py:51) expressed per-leaf.
"""

import os
from typing import Dict, List, Optional

import numpy as np

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam
from ...ops.aio import build_aio_handle
from ...utils.logging import log_dist


class OffloadedAdamState:
    """Flat host-side Adam state for one pytree of params."""

    def __init__(self, flat_params: Dict[str, np.ndarray], device: str = "cpu",
                 nvme_path: Optional[str] = None, aio_threads: int = 4,
                 lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0):
        assert device in ("cpu", "nvme")
        self.device = device
        self.opt = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay)
        # force writable owned copies (np views of jax arrays are read-only)
        self.params: Dict[str, np.ndarray] = {
            k: np.array(v, dtype=np.float32, copy=True) for k, v in flat_params.items()
        }
        self.step_count = 0
        if device == "cpu":
            self._m = {k: np.zeros_like(v) for k, v in self.params.items()}
            self._v = {k: np.zeros_like(v) for k, v in self.params.items()}
            self._aio = None
        else:
            if not nvme_path:
                raise ValueError("nvme offload needs offload_optimizer.nvme_path")
            self.nvme_dir = os.path.join(nvme_path, "dstpu_opt_swap")
            os.makedirs(self.nvme_dir, exist_ok=True)
            # O_DIRECT like the reference's libaio queues: buffered writes hit
            # page-cache writeback throttling (~100 MB/s on cloud VMs) while
            # direct IO sustains the device rate; non-supporting filesystems
            # fall back per-file inside the library
            self._aio = build_aio_handle(aio_threads, use_odirect=True)
            # initialize moment files to zero
            for k, v in self.params.items():
                zeros = np.zeros_like(v)
                self._aio.pwrite(self._file(k, "m"), zeros)
                self._aio.pwrite(self._file(k, "v"), zeros)
            self._aio.wait_all()
        log_dist(f"optimizer offload: device={device} "
                 f"({sum(v.size for v in self.params.values())/1e6:.2f}M elems)", ranks=[0])

    def _file(self, key: str, kind: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.nvme_dir, f"{safe}.{kind}.bin")

    def step(self, grads: Dict[str, np.ndarray], lr: Optional[float] = None) -> Dict[str, np.ndarray]:
        """Apply one AdamW step; returns the updated fp32 params per key."""
        self.step_count += 1
        if self.device == "cpu":
            for k, g in grads.items():
                self.opt.step(self.params[k], self._m[k], self._v[k], g,
                              lr=lr, step=self.step_count)
            return self.params
        # nvme: per-leaf swap-in -> step -> async swap-out (overlaps next swap-in)
        pending: List[int] = []
        for k, g in grads.items():
            m = np.empty_like(self.params[k])
            v = np.empty_like(self.params[k])
            rid_m = self._aio.pread(self._file(k, "m"), m)
            rid_v = self._aio.pread(self._file(k, "v"), v)
            self._aio.wait(rid_m)
            self._aio.wait(rid_v)
            self.opt.step(self.params[k], m, v, g, lr=lr, step=self.step_count)
            pending.append(self._aio.pwrite(self._file(k, "m"), m))
            pending.append(self._aio.pwrite(self._file(k, "v"), v))
        for rid in pending:
            self._aio.wait(rid)
        return self.params

    def state_dict(self) -> Dict[str, Dict[str, np.ndarray]]:
        if self.device == "cpu":
            return {"m": self._m, "v": self._v, "step": self.step_count}
        out_m, out_v = {}, {}
        for k, p in self.params.items():
            m = np.empty_like(p)
            v = np.empty_like(p)
            self._aio.wait(self._aio.pread(self._file(k, "m"), m))
            self._aio.wait(self._aio.pread(self._file(k, "v"), v))
            out_m[k], out_v[k] = m, v
        return {"m": out_m, "v": out_v, "step": self.step_count}

    def load_state_dict(self, sd) -> None:
        self.step_count = int(sd.get("step", 0))
        if self.device == "cpu":
            for k in self._m:
                self._m[k][...] = sd["m"][k]
                self._v[k][...] = sd["v"][k]
            return
        for k in self.params:
            self._aio.pwrite(self._file(k, "m"), np.ascontiguousarray(sd["m"][k]))
            self._aio.pwrite(self._file(k, "v"), np.ascontiguousarray(sd["v"][k]))
        self._aio.wait_all()
