"""Tensor offload/swap machinery (reference runtime/swap_tensor/)."""
from .optimizer_swapper import OffloadedAdamState
from .partitioned_param_swapper import (AsyncPartitionedParameterSwapper,
                                        SwappedLayerTrainer)
