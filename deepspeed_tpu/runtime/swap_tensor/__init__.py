"""Tensor offload/swap machinery (reference runtime/swap_tensor/)."""
from .optimizer_swapper import OffloadedAdamState
