"""Checkpoint save/load.

Analog of the reference checkpoint layer (engine.save_checkpoint:3052 /
load_checkpoint:2688, checkpoint_engine/).  Layout mirrors the reference's
directory scheme (``<dir>/<tag>/`` + a ``latest`` tag file, engine.py:2632), but
the payload is **topology-free**: every leaf of the train state is written as a
full (unsharded) ``.npy`` keyed by its pytree path.  On load, leaves are placed
with the *current* plan's shardings — so resuming on a different dp world size /
zero stage works by construction (the reference needs ``zero_elastic_checkpoint``
and the universal-checkpoint converter for this; here reshape-on-load is the
native behavior, and the universal format in deepspeed_tpu/checkpoint/ adds
tp/pp-aware merging on top).

Sharded leaves stream device shard -> memmap'd .npy directly (host RAM peaks
at one SHARD, not one leaf); loads mmap the file so each target shard reads
only its pages.  The rank-0 full-gather spike the reference's universal
checkpoint works around never happens.

Durability protocol (reference checkpoint_engine tag-commit semantics +
the Orbax/CheckFreq temp-dir-then-rename shape): a save stages everything
under ``<dir>/.tmp_<tag>/``, fsyncs each leaf, records per-leaf CRC32 +
byte size in ``metadata.json``, atomically renames the staging dir to
``<dir>/<tag>/``, calls ``engine.commit(tag)``, and only then flips
``latest``.  A preemption at ANY point leaves ``latest`` pointing at the
previous complete checkpoint; stale staging dirs are swept on the next
save.  Loads validate manifest completeness + sizes (checksums with
``verify_integrity``) and can fall back to the newest valid prior tag.
"""

import json
import os
import re
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..utils.logging import log_dist, logger
from ..utils.wal import atomic_write_text, file_crc32, fsync_dir, fsync_file
from .checkpoint_engine import CheckpointEngine, NativeCheckpointEngine

LATEST_FILE = "latest"
INDEX_FILE = "checkpoint_index.json"
METADATA_FILE = "metadata.json"
TMP_PREFIX = ".tmp_"
FORMAT_VERSION = 1


class CheckpointError(RuntimeError):
    """A checkpoint is missing, incomplete, or corrupt.

    Raised instead of the raw ``FileNotFoundError`` / ``JSONDecodeError`` soup a
    half-written directory produces, always naming the dir, the tag, and a
    remedy (``fallback_to_valid=True`` walks back to the newest valid tag)."""


def _leaf_key(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    key = ".".join(parts)
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", key)


def _is_rank0() -> bool:
    try:
        return jax.process_index() == 0
    except Exception:
        return True


# ------------------------------------------------------------ durable-IO utils
# One implementation shared with the serving request journal (PR 8): the
# fsync/CRC/atomic-write idioms live in utils/wal.py; the private names are
# kept as aliases because this module grew them first and tests/forks import
# them from here.
_fsync_file = fsync_file
_fsync_dir = fsync_dir
_atomic_write_text = atomic_write_text
_file_crc32 = file_crc32


# staging dirs of saves currently in flight in THIS process: a reentrant save
# (the SIGTERM preemption handler interrupting a regular save) must not sweep
# the dir the interrupted save is still writing into
_ACTIVE_STAGING: set = set()


def _sweep_stale_tmp(save_dir: str) -> List[str]:
    """Remove ``.tmp_*`` staging dirs left by crashed saves (safe: a staging
    dir is only ever renamed away on success, so any survivor not registered
    as in-flight is garbage)."""
    swept = []
    try:
        entries = os.listdir(save_dir)
    except OSError:
        return swept
    for name in entries:
        path = os.path.join(save_dir, name)
        if (name.startswith(TMP_PREFIX) and os.path.isdir(path)
                and path not in _ACTIVE_STAGING):
            shutil.rmtree(path, ignore_errors=True)
            swept.append(name)
    if swept:
        logger.warning(f"swept {len(swept)} stale checkpoint staging dir(s) in "
                       f"{save_dir}: {swept} (crashed earlier save)")
    return swept


# -------------------------------------------------------------- tag bookkeeping
def _read_index(save_dir: str) -> List[str]:
    path = os.path.join(save_dir, INDEX_FILE)
    try:
        with open(path) as fh:
            data = json.load(fh)
        tags = data.get("tags", [])
        return [t for t in tags if isinstance(t, str)]
    except (OSError, ValueError):
        return []


def _write_index(save_dir: str, tags: List[str]) -> None:
    _atomic_write_text(os.path.join(save_dir, INDEX_FILE),
                       json.dumps({"tags": tags}, indent=1))


def _append_index(save_dir: str, tag: str) -> None:
    tags = [t for t in _read_index(save_dir) if t != tag]
    tags.append(tag)
    _write_index(save_dir, tags)


def list_tags(load_dir: str) -> List[str]:
    """All checkpoint tags under ``load_dir``, oldest -> newest.  Ordered by
    ``checkpoint_index.json`` (append-per-save), with any on-disk tags the
    index missed (e.g. hand-copied) appended in mtime order."""
    try:
        on_disk = {d for d in os.listdir(load_dir)
                   if os.path.isdir(os.path.join(load_dir, d)) and not d.startswith(TMP_PREFIX)}
    except OSError:
        return []
    tags = [t for t in _read_index(load_dir) if t in on_disk]
    extra = sorted(on_disk - set(tags),
                   key=lambda t: os.path.getmtime(os.path.join(load_dir, t)))
    return tags + extra


def get_latest_tag(load_dir: str) -> Optional[str]:
    """The tag named by the ``latest`` file; None when no ``latest`` exists.
    An empty/whitespace ``latest`` (torn write on a non-atomic fs, or manual
    truncation) raises :class:`CheckpointError` instead of surfacing later as
    a confusing missing-dir error."""
    path = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        tag = fh.read().strip()
    if not tag:
        raise CheckpointError(
            f"checkpoint dir {load_dir!r}: the '{LATEST_FILE}' file is empty/whitespace "
            f"(torn write?) — delete it and pass an explicit tag, or use "
            f"load_checkpoint(..., fallback_to_valid=True) to walk back to the newest "
            f"valid checkpoint")
    return tag


def read_metadata(ckpt_dir: str) -> Dict[str, Any]:
    """Parse ``<ckpt_dir>/metadata.json``; missing/corrupt JSON raises a
    :class:`CheckpointError` naming the dir and the remedy."""
    path = os.path.join(ckpt_dir, METADATA_FILE)
    if not os.path.exists(path):
        raise CheckpointError(
            f"checkpoint {ckpt_dir!r} has no {METADATA_FILE} — incomplete or corrupted "
            f"save; pick another tag or use fallback_to_valid=True")
    try:
        with open(path) as fh:
            return json.load(fh)
    except ValueError as exc:
        raise CheckpointError(
            f"checkpoint {ckpt_dir!r}: {METADATA_FILE} is not valid JSON ({exc}) — "
            f"corrupted save; pick another tag or use fallback_to_valid=True") from exc


def check_checkpoint_tag(load_dir: str, tag: str, verify_integrity: bool = False) -> List[str]:
    """Integrity problems for ``<load_dir>/<tag>/`` (empty list == valid).

    Always checks: tag dir exists, metadata parses, every manifest leaf file
    exists with the recorded byte size.  With ``verify_integrity`` also
    re-computes each leaf's CRC32 against the manifest (full read)."""
    ckpt_dir = os.path.join(load_dir, tag)
    if not os.path.isdir(ckpt_dir):
        return [f"tag dir {ckpt_dir} does not exist"]
    try:
        meta = read_metadata(ckpt_dir)
    except CheckpointError as exc:
        return [str(exc)]
    problems = []
    manifest = meta.get("manifest", [])
    if not isinstance(manifest, list):
        return [f"metadata manifest is {type(manifest).__name__}, not a list"]
    for i, entry in enumerate(manifest):
        key = entry.get("key") if isinstance(entry, dict) else None
        if not isinstance(key, str):
            # still-valid-JSON damage must read as "tag invalid", not KeyError
            # out of the fallback walk this check protects
            problems.append(f"manifest entry {i} is malformed (no 'key')")
            continue
        path = os.path.join(ckpt_dir, key + ".npy")
        if not os.path.exists(path):
            problems.append(f"leaf {key}: file missing")
            continue
        want_bytes = entry.get("nbytes")
        if want_bytes is not None and os.path.getsize(path) != want_bytes:
            problems.append(f"leaf {key}: size {os.path.getsize(path)} != manifest {want_bytes}")
            continue
        if verify_integrity and entry.get("crc32") is not None:
            got = _file_crc32(path)
            if got != entry["crc32"]:
                problems.append(f"leaf {key}: crc32 {got:#010x} != manifest {entry['crc32']:#010x}")
    return problems


def validate_checkpoint_tag(load_dir: str, tag: str, verify_integrity: bool = False) -> None:
    problems = check_checkpoint_tag(load_dir, tag, verify_integrity=verify_integrity)
    if problems:
        raise CheckpointError(
            f"checkpoint {load_dir!r} tag {tag!r} failed validation: "
            + "; ".join(problems)
            + " — pass another tag or load_checkpoint(..., fallback_to_valid=True)")


def is_valid_tag(load_dir: str, tag: str, verify_integrity: bool = False) -> bool:
    return not check_checkpoint_tag(load_dir, tag, verify_integrity=verify_integrity)


def find_latest_valid_tag(load_dir: str, verify_integrity: bool = False,
                          exclude: Tuple[str, ...] = ()) -> Optional[str]:
    """Newest tag (per the checkpoint index / mtime order) that passes
    validation; the resume-from-latest-valid walk."""
    for tag in reversed(list_tags(load_dir)):
        if tag in exclude:
            continue
        if is_valid_tag(load_dir, tag, verify_integrity=verify_integrity):
            return tag
    return None


# -------------------------------------------------------------------- retention
def sweep_retention(save_dir: str, keep_last_n: Optional[int],
                    verify_integrity: bool = False) -> List[str]:
    """Delete tags older than the newest ``keep_last_n`` (checkpoint GC,
    reference Nebula ``num_of_version_in_retention``).  Never deletes the tag
    ``latest`` points at, and never deletes the only VALID checkpoint: when
    everything inside the retention window is corrupt, the newest valid tag
    outside it is retained so a fallback load always has somewhere to land."""
    if not keep_last_n or keep_last_n < 1:
        return []
    tags = list_tags(save_dir)
    if len(tags) <= keep_last_n:
        return []
    keep = set(tags[-keep_last_n:])
    try:
        latest = get_latest_tag(save_dir)
    except CheckpointError:
        latest = None
    if latest is not None:
        keep.add(latest)
    if not any(is_valid_tag(save_dir, t, verify_integrity) for t in keep):
        newest_valid = find_latest_valid_tag(save_dir, verify_integrity)
        if newest_valid is not None:
            keep.add(newest_valid)
    deleted = []
    for tag in tags:
        if tag in keep:
            continue
        shutil.rmtree(os.path.join(save_dir, tag), ignore_errors=True)
        deleted.append(tag)
    if deleted:
        _write_index(save_dir, [t for t in tags if t not in set(deleted)])
        log_dist(f"checkpoint retention: deleted {deleted} (keep_last_n={keep_last_n})",
                 ranks=[0])
    return deleted


# ------------------------------------------------------------------------- save
def save_checkpoint_dir(save_dir: str, tag: str, state, client_state: Dict, config=None,
                        engine: Optional[CheckpointEngine] = None):
    """Write the full state under ``save_dir/tag/`` and update ``latest``.

    Crash-safe ordering: stage under ``.tmp_<tag>/`` -> fsync leaves -> write
    manifest (per-leaf CRC32 + nbytes) -> fsync staging dir -> atomic rename to
    ``<tag>/`` -> ``engine.commit(tag)`` -> flip ``latest``.  Dying at any
    point leaves ``latest`` on the previous complete checkpoint; the partial
    staging dir is swept on the next save."""
    engine = engine or NativeCheckpointEngine()
    rank0 = _is_rank0()
    final_dir = os.path.join(save_dir, tag)
    tmp_dir = os.path.join(save_dir, TMP_PREFIX + tag)
    _ACTIVE_STAGING.add(tmp_dir)
    try:
        if rank0:
            _sweep_stale_tmp(save_dir)
            if os.path.isdir(tmp_dir):  # earlier attempt of THIS save (retry)
                shutil.rmtree(tmp_dir)
            engine.makedirs(tmp_dir)
        leaves_with_path = jax.tree_util.tree_flatten_with_path(state)[0]
        manifest = []
        for path, leaf in leaves_with_path:
            key = _leaf_key(path)
            target = os.path.join(tmp_dir, key + ".npy")
            if rank0 and _write_leaf_streaming(leaf, target, engine):
                pass  # shard-streamed straight into the .npy (no full-leaf host copy)
            else:
                arr = _gather_to_host(leaf)
                if rank0:
                    engine.save(arr, target)
            dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
            manifest.append({"key": key, "shape": list(np.shape(leaf)), "dtype": str(dtype)})
        # all leaf bytes must be durable BEFORE the manifest describes them and
        # the rename publishes them (async engines drain their writer queue here)
        engine.flush()
        replaced = None
        if rank0:
            for entry in manifest:
                leaf_path = os.path.join(tmp_dir, entry["key"] + ".npy")
                _fsync_file(leaf_path)
                entry["nbytes"] = os.path.getsize(leaf_path)
                # CRC of the file as it landed on disk: the read-back costs one
                # extra pass over hot page cache, and is both engine-agnostic
                # (plug-ins may write any format) and an immediate write
                # verification — a torn/bitflipped write is caught NOW, not at
                # the next resume
                entry["crc32"] = _file_crc32(leaf_path)
            meta = {"format_version": FORMAT_VERSION, "tag": tag,
                    "manifest": manifest, "client_state": _jsonable(client_state)}
            _atomic_write_text(os.path.join(tmp_dir, METADATA_FILE), json.dumps(meta, indent=1))
            _fsync_dir(tmp_dir)
            if os.path.isdir(final_dir):
                # re-saving an existing tag: park the old copy under a VALID tag
                # name (not .tmp_ — it must stay loadable) so a crash between
                # the two renames still leaves a complete checkpoint for the
                # fallback walk; removed only after `latest` flips
                replaced = final_dir + ".prev"
                if os.path.isdir(replaced):
                    shutil.rmtree(replaced)
                os.rename(final_dir, replaced)
            os.rename(tmp_dir, final_dir)
            _fsync_dir(save_dir)
        # commit AFTER the rename: the tag a plug-in engine marks durable now
        # names a complete, manifest-bearing directory (the old ordering
        # committed a tag whose metadata.json did not exist yet)
        engine.commit(tag)
        if rank0:
            _append_index(save_dir, tag)
            _atomic_write_text(os.path.join(save_dir, LATEST_FILE), tag)
            if replaced is not None:
                shutil.rmtree(replaced, ignore_errors=True)
        log_dist(f"saved checkpoint {tag} -> {final_dir} ({len(manifest)} leaves)", ranks=[0])
    finally:
        _ACTIVE_STAGING.discard(tmp_dir)


def save_checkpoint_with_retries(save_dir: str, tag: str, state, client_state: Dict,
                                 config=None, engine: Optional[CheckpointEngine] = None,
                                 retries: int = 0, backoff_secs: float = 0.5,
                                 on_retry=None):
    """``save_checkpoint_dir`` wrapped in bounded exponential-backoff retries
    over transient ``OSError`` (flaky NFS/GCS fuse mounts).  Non-OSError
    failures — including a simulated crash from the fault harness — propagate
    immediately: retrying a logic error never helps."""
    attempts = max(int(retries), 0) + 1
    for attempt in range(attempts):
        try:
            return save_checkpoint_dir(save_dir, tag, state, client_state,
                                       config=config, engine=engine)
        except OSError as exc:
            if attempt + 1 >= attempts:
                raise
            delay = backoff_secs * (2 ** attempt)
            logger.warning(f"checkpoint save {tag} attempt {attempt + 1}/{attempts} "
                           f"failed ({exc!r}); retrying in {delay:.2f}s")
            if on_retry is not None:
                on_retry(attempt + 1, exc)
            if delay > 0:
                time.sleep(delay)


def _gather_to_host(leaf) -> np.ndarray:
    if isinstance(leaf, jax.Array) and len(leaf.sharding.device_set) > 1:
        rep = NamedSharding(leaf.sharding.mesh, PartitionSpec())
        leaf = jax.device_put(leaf, rep)
    return np.asarray(leaf)  # dslint: disable=sharding-dropped-at-boundary  # deliberate collapse: checkpoint save replicates then serializes host bytes — the sharding ends here by design


def _leaf_fully_addressable(leaf) -> bool:
    """Seam for the multi-host tests: this process can see every shard."""
    return leaf.is_fully_addressable


def _shard_index_key(index) -> tuple:
    """Hashable form of ``shard.index`` (a tuple of slices — unhashable before
    Python 3.12, which made the dedup set below throw and silently demote
    EVERY streaming save to the full-gather path)."""
    return tuple((s.start, s.stop, s.step) if isinstance(s, slice) else s
                 for s in index)


def _write_leaf_streaming(leaf, target: str, engine) -> bool:
    """Stream a sharded leaf's device shards straight into one ``.npy`` via a
    memmap — host RAM stays at one SHARD, not one leaf (the reference's
    universal checkpoint exists to avoid exactly this rank-0 gather spike;
    here the per-shard write is the fix at the source).  Returns False when
    the leaf isn't a multi-device jax.Array or the engine isn't file-backed
    (fallback: gather + engine.save)."""
    if not isinstance(leaf, jax.Array) or len(leaf.sharding.device_set) <= 1:
        return False
    if not _leaf_fully_addressable(leaf):
        # multi-host: this process can't see every shard — writing only local
        # shards would persist zeros for the rest, and skipping the gather on
        # rank 0 while others enter it would desync the collective.  All ranks
        # take the gather path together.
        return False
    if not getattr(engine, "supports_streaming_save", False):
        return False  # plug-in engines define their own persistence
    try:
        out = np.lib.format.open_memmap(target, mode="w+", dtype=np.dtype(leaf.dtype),
                                        shape=leaf.shape)
        seen = set()
        for shard in leaf.addressable_shards:
            key = _shard_index_key(shard.index)
            if key in seen:  # replicated-over-axis shards write once
                continue
            seen.add(key)
            out[shard.index] = np.asarray(shard.data)
        out.flush()
        del out
        return True
    except Exception as exc:  # exotic dtype/fs: fall back to the gather path
        logger.warning(f"streaming shard write failed for {target} ({exc}); "
                       f"falling back to gathered save")
        return False


def _jsonable(obj):
    """JSON-safe deep copy of client_state: numpy/jax leaves become lists or
    Python scalars (an ``np.bool_`` or a device array in client_state used to
    raise TypeError deep inside json.dump, torching the whole save)."""
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.ndarray, jax.Array)):
        return np.asarray(obj).tolist()
    if isinstance(obj, np.generic):  # np.bool_ / np.integer / np.floating / ...
        return obj.item()
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    logger.warning(f"client_state value of type {type(obj).__name__} is not "
                   f"JSON-serializable; storing str() representation")
    return str(obj)


# ------------------------------------------------------------------------- load
def load_checkpoint_dir(load_dir: str,
                        tag: Optional[str],
                        state_template,
                        target_shardings,
                        load_optimizer_states: bool = True,
                        verify_integrity: bool = False,
                        validate: bool = True) -> Tuple[Any, Dict]:
    """Rebuild a train state from disk, placing each leaf with the current plan's
    sharding (elastic/reshaping load).  ``state_template`` supplies the pytree
    structure; ``load_optimizer_states=False`` keeps the template's optimizer
    state/loss scale and loads only params+step (reference load_checkpoint:2688
    ``load_optimizer_states`` arg).

    The tag is validated first (manifest completeness + byte sizes, CRC32s too
    with ``verify_integrity``); an incomplete/corrupt tag raises
    :class:`CheckpointError` before any leaf is touched.  Callers that already
    validated (the engine's fallback-tag resolution) pass ``validate=False`` so
    a CRC pass over a multi-GB checkpoint isn't paid twice per resume."""
    tag = tag or get_latest_tag(load_dir)
    if tag is None:
        raise CheckpointError(
            f"checkpoint dir {load_dir!r} has no '{LATEST_FILE}' file and no tag was "
            f"given — nothing to resume from; pass an explicit tag or save first")
    if validate:
        validate_checkpoint_tag(load_dir, tag, verify_integrity=verify_integrity)
    ckpt_dir = os.path.join(load_dir, tag)
    meta = read_metadata(ckpt_dir)
    available = {m["key"] for m in meta["manifest"]}

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    shard_leaves = jax.tree_util.tree_leaves(target_shardings)
    assert len(shard_leaves) == len(leaves_with_path), \
        f"sharding tree ({len(shard_leaves)}) != state tree ({len(leaves_with_path)})"

    new_leaves = []
    for (path, cur_leaf), sharding in zip(leaves_with_path, shard_leaves):
        key = _leaf_key(path)
        top = key.split(".")[0]
        skip = (not load_optimizer_states) and top in ("opt_state", "loss_scale")
        if skip or key not in available:
            if key not in available and not skip:
                logger.warning(f"checkpoint missing leaf {key}; keeping current value")
            new_leaves.append(cur_leaf)
            continue
        # mmap: device_put below slices per target shard, so only the pages a
        # shard needs are ever read into host RAM
        arr = np.load(os.path.join(ckpt_dir, key + ".npy"), mmap_mode="r")
        expected = tuple(np.shape(cur_leaf))
        if tuple(arr.shape) != expected:
            raise ValueError(f"checkpoint leaf {key} shape {arr.shape} != model shape {expected}")
        want = getattr(cur_leaf, "dtype", None)
        if want is not None and arr.dtype != want:
            arr = arr.astype(want)  # materializes; same-dtype mmap stays lazy
        if jax.process_count() > 1:
            # multi-controller: eager device_put rejects shardings spanning
            # non-addressable devices; build from per-shard callbacks instead
            # (each process materializes only its addressable shards' pages)
            new_leaves.append(jax.make_array_from_callback(
                tuple(arr.shape), sharding, lambda idx, a=arr: np.asarray(a[idx])))
        else:
            new_leaves.append(jax.device_put(arr, sharding))
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    log_dist(f"loaded checkpoint {tag} from {ckpt_dir}", ranks=[0])
    return state, meta.get("client_state", {})
