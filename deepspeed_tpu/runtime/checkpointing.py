"""Checkpoint save/load.

Analog of the reference checkpoint layer (engine.save_checkpoint:3052 /
load_checkpoint:2688, checkpoint_engine/).  Layout mirrors the reference's
directory scheme (``<dir>/<tag>/`` + a ``latest`` tag file, engine.py:2632), but
the payload is **topology-free**: every leaf of the train state is written as a
full (unsharded) ``.npy`` keyed by its pytree path.  On load, leaves are placed
with the *current* plan's shardings — so resuming on a different dp world size /
zero stage works by construction (the reference needs ``zero_elastic_checkpoint``
and the universal-checkpoint converter for this; here reshape-on-load is the
native behavior, and the universal format in deepspeed_tpu/checkpoint/ adds
tp/pp-aware merging on top).

Sharded leaves stream device shard -> memmap'd .npy directly (host RAM peaks
at one SHARD, not one leaf); loads mmap the file so each target shard reads
only its pages.  The rank-0 full-gather spike the reference's universal
checkpoint works around never happens.
"""

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..utils.logging import log_dist, logger
from .checkpoint_engine import CheckpointEngine, NativeCheckpointEngine

LATEST_FILE = "latest"


def _leaf_key(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    key = ".".join(parts)
    return re.sub(r"[^A-Za-z0-9_.\-]", "_", key)


def _is_rank0() -> bool:
    try:
        return jax.process_index() == 0
    except Exception:
        return True


def save_checkpoint_dir(save_dir: str, tag: str, state, client_state: Dict, config=None,
                        engine: Optional[CheckpointEngine] = None):
    """Write the full state under ``save_dir/tag/`` and update ``latest``."""
    engine = engine or NativeCheckpointEngine()
    ckpt_dir = os.path.join(save_dir, tag)
    if _is_rank0():
        engine.makedirs(ckpt_dir)
    leaves_with_path = jax.tree_util.tree_flatten_with_path(state)[0]
    manifest = []
    for path, leaf in leaves_with_path:
        key = _leaf_key(path)
        target = os.path.join(ckpt_dir, key + ".npy")
        if _is_rank0() and _write_leaf_streaming(leaf, target, engine):
            pass  # shard-streamed straight into the .npy (no full-leaf host copy)
        else:
            arr = _gather_to_host(leaf)
            if _is_rank0():
                engine.save(arr, target)
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        manifest.append({"key": key, "shape": list(np.shape(leaf)), "dtype": str(dtype)})
    engine.commit(tag)
    if _is_rank0():
        meta = {"manifest": manifest, "client_state": _jsonable(client_state)}
        with open(os.path.join(ckpt_dir, "metadata.json"), "w") as fh:
            json.dump(meta, fh, indent=1)
        with open(os.path.join(save_dir, LATEST_FILE), "w") as fh:
            fh.write(tag)
    log_dist(f"saved checkpoint {tag} -> {ckpt_dir} ({len(manifest)} leaves)", ranks=[0])


def _gather_to_host(leaf) -> np.ndarray:
    if isinstance(leaf, jax.Array) and len(leaf.sharding.device_set) > 1:
        rep = NamedSharding(leaf.sharding.mesh, PartitionSpec())
        leaf = jax.device_put(leaf, rep)
    return np.asarray(leaf)


def _write_leaf_streaming(leaf, target: str, engine) -> bool:
    """Stream a sharded leaf's device shards straight into one ``.npy`` via a
    memmap — host RAM stays at one SHARD, not one leaf (the reference's
    universal checkpoint exists to avoid exactly this rank-0 gather spike;
    here the per-shard write is the fix at the source).  Returns False when
    the leaf isn't a multi-device jax.Array or the engine isn't file-backed
    (fallback: gather + engine.save)."""
    if not isinstance(leaf, jax.Array) or len(leaf.sharding.device_set) <= 1:
        return False
    if not leaf.is_fully_addressable:
        # multi-host: this process can't see every shard — writing only local
        # shards would persist zeros for the rest, and skipping the gather on
        # rank 0 while others enter it would desync the collective.  All ranks
        # take the gather path together.
        return False
    if not getattr(engine, "supports_streaming_save", False):
        return False  # plug-in engines define their own persistence
    try:
        out = np.lib.format.open_memmap(target, mode="w+", dtype=np.dtype(leaf.dtype),
                                        shape=leaf.shape)
        seen = set()
        for shard in leaf.addressable_shards:
            if shard.index in seen:  # replicated-over-axis shards write once
                continue
            seen.add(shard.index)
            out[shard.index] = np.asarray(shard.data)
        out.flush()
        del out
        return True
    except Exception as exc:  # exotic dtype/fs: fall back to the gather path
        logger.warning(f"streaming shard write failed for {target} ({exc}); "
                       f"falling back to gathered save")
        return False


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer, np.floating)):
        return obj.item()
    return obj


def get_latest_tag(load_dir: str) -> Optional[str]:
    path = os.path.join(load_dir, LATEST_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        return fh.read().strip()


def load_checkpoint_dir(load_dir: str,
                        tag: Optional[str],
                        state_template,
                        target_shardings,
                        load_optimizer_states: bool = True) -> Tuple[Any, Dict]:
    """Rebuild a train state from disk, placing each leaf with the current plan's
    sharding (elastic/reshaping load).  ``state_template`` supplies the pytree
    structure; ``load_optimizer_states=False`` keeps the template's optimizer
    state/loss scale and loads only params+step (reference load_checkpoint:2688
    ``load_optimizer_states`` arg)."""
    tag = tag or get_latest_tag(load_dir)
    if tag is None:
        raise FileNotFoundError(f"no 'latest' file in {load_dir} and no tag given")
    ckpt_dir = os.path.join(load_dir, tag)
    with open(os.path.join(ckpt_dir, "metadata.json")) as fh:
        meta = json.load(fh)
    available = {m["key"] for m in meta["manifest"]}

    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(state_template)
    shard_leaves = jax.tree_util.tree_leaves(target_shardings)
    assert len(shard_leaves) == len(leaves_with_path), \
        f"sharding tree ({len(shard_leaves)}) != state tree ({len(leaves_with_path)})"

    new_leaves = []
    for (path, cur_leaf), sharding in zip(leaves_with_path, shard_leaves):
        key = _leaf_key(path)
        top = key.split(".")[0]
        skip = (not load_optimizer_states) and top in ("opt_state", "loss_scale")
        if skip or key not in available:
            if key not in available and not skip:
                logger.warning(f"checkpoint missing leaf {key}; keeping current value")
            new_leaves.append(cur_leaf)
            continue
        # mmap: device_put below slices per target shard, so only the pages a
        # shard needs are ever read into host RAM
        arr = np.load(os.path.join(ckpt_dir, key + ".npy"), mmap_mode="r")
        expected = tuple(np.shape(cur_leaf))
        if tuple(arr.shape) != expected:
            raise ValueError(f"checkpoint leaf {key} shape {arr.shape} != model shape {expected}")
        want = getattr(cur_leaf, "dtype", None)
        if want is not None and arr.dtype != want:
            arr = arr.astype(want)  # materializes; same-dtype mmap stays lazy
        if jax.process_count() > 1:
            # multi-controller: eager device_put rejects shardings spanning
            # non-addressable devices; build from per-shard callbacks instead
            # (each process materializes only its addressable shards' pages)
            new_leaves.append(jax.make_array_from_callback(
                tuple(arr.shape), sharding, lambda idx, a=arr: np.asarray(a[idx])))
        else:
            new_leaves.append(jax.device_put(arr, sharding))
    state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    log_dist(f"loaded checkpoint {tag} from {ckpt_dir}", ranks=[0])
    return state, meta.get("client_state", {})
