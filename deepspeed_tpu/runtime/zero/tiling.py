"""Tiled linear layers — bound the live activation/weight footprint of huge
projections.

Analog of the reference ``TiledLinear`` (runtime/zero/tiling.py:21): the
reference splits one nn.Linear into a grid of sub-Linears so ZeRO-3 fetches
one tile's weights at a time.  Under XLA the concern is the peak ACTIVATION
of giant projections (a [tokens, vocab] unembed logit block can dwarf the
model): ``tiled_matmul`` runs the output dimension in ``lax.map`` chunks so
at most one [tokens, tile] block plus the running consumer is live, and under
ZeRO-3 each tile's weight columns gather per chunk instead of all at once.

``TiledLinear`` mirrors the reference's param-splitting form: weights stored
pre-split [T, in, out/T], applied tile-by-tile — composes with zero.Init
(each tile is an independently sharded leaf).
"""

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


def tiled_matmul(x: jnp.ndarray, w: jnp.ndarray, num_tiles: int,
                 reduce_fn: Optional[Callable] = None):
    """``x @ w`` with the output dim computed in ``num_tiles`` sequential
    chunks.  With ``reduce_fn`` (e.g. a per-chunk logsumexp/top-k consumer)
    the full product never materializes — the softmax-over-vocab trick;
    without it, chunks concatenate to the ordinary result."""
    out_dim = w.shape[-1]
    if out_dim % num_tiles != 0:
        raise ValueError(f"output dim {out_dim} not divisible by {num_tiles} tiles")
    tile = out_dim // num_tiles
    wt = w.reshape(*w.shape[:-1], num_tiles, tile)
    wt = jnp.moveaxis(wt, -2, 0)  # [T, in, tile]

    if reduce_fn is None:
        chunks = lax.map(lambda wi: x @ wi, wt)          # [T, ..., tile]
        return _merge_tiles(chunks)
    return lax.map(lambda wi: reduce_fn(x @ wi), wt)


def _merge_tiles(chunks: jnp.ndarray) -> jnp.ndarray:
    """[T, ..., tile] -> [..., T*tile] preserving tile order."""
    moved = jnp.moveaxis(chunks, 0, -2)
    return moved.reshape(*moved.shape[:-2], moved.shape[-2] * moved.shape[-1])


class TiledLinear:
    """Pre-split linear: params {'w_tiles': [T, in, out/T], 'b_tiles': [T, out/T]}.

    ``init(key, in_dim, out_dim, num_tiles)`` builds the split params;
    ``apply(params, x)`` is the tiled forward.  Reference parity: in_splits
    are unnecessary under XLA (input-dim tiling is a plain reduction the
    compiler already schedules); out_splits are the memory lever.
    """

    @staticmethod
    def init(key, in_dim: int, out_dim: int, num_tiles: int, scale: Optional[float] = None,
             bias: bool = True, dtype=jnp.float32):
        if out_dim % num_tiles != 0:
            raise ValueError(f"out_dim {out_dim} not divisible by {num_tiles}")
        scale = scale if scale is not None else in_dim ** -0.5
        w = jax.random.normal(key, (num_tiles, in_dim, out_dim // num_tiles), dtype) * scale
        params = {"w_tiles": w}
        if bias:
            params["b_tiles"] = jnp.zeros((num_tiles, out_dim // num_tiles), dtype)
        return params

    @staticmethod
    def apply(params, x):
        def one(args):
            if len(args) == 2:
                w, b = args
                return x @ w + b
            (w,) = args
            return x @ w

        if "b_tiles" in params:
            chunks = lax.map(one, (params["w_tiles"], params["b_tiles"]))
        else:
            chunks = lax.map(one, (params["w_tiles"],))
        return _merge_tiles(chunks)

    @staticmethod
    def from_dense(w: jnp.ndarray, num_tiles: int, b: Optional[jnp.ndarray] = None):
        """Split an existing [in, out] weight (reference copy_params_from)."""
        in_dim, out_dim = w.shape
        if out_dim % num_tiles != 0:
            raise ValueError(f"out_dim {out_dim} not divisible by {num_tiles}")
        tile = out_dim // num_tiles
        params = {"w_tiles": jnp.moveaxis(w.reshape(in_dim, num_tiles, tile), 1, 0)}
        if b is not None:
            params["b_tiles"] = b.reshape(num_tiles, tile)
        return params
