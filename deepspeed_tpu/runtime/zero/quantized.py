"""ZeRO++-style quantized communication (qwZ / qgZ analogs).

Reference: ZeRO++ (runtime/zero/config.py:264-280, csrc/quantization/,
runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce):
  - qwZ: int8-quantized weight allgather (4x wire traffic cut)
  - qgZ: hierarchical int4 all-to-all gradient reduction (4x cut)
  - hpZ: secondary intra-node param shard (handled as a sharding-plan layout
    choice in sharding.py — gathers ride the fast 'fsdp' axis only)

Under GSPMD the dp reduction/gather collectives are implicit, so the quantized
variants take explicit control of the wire format with ``jax.shard_map`` over
the dp axes: gradients are accumulated per-shard, all-to-all'd as packed int4
(+fp32 group scales), summed locally, and re-gathered in bf16; the updated
master shards are quantized to int8 before the compute-copy allgather.

Total qgZ traffic per element: 0.5B (int4 a2a) + 2B (bf16 gather) = 2.5B vs
8B for an fp32 allreduce ring (2x4B) — and the a2a rides ICI.
"""

import functools
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec

from ...ops.quantizer.quantize import (quantized_allgather_int8, quantized_psum_scatter_int4)
from ..grad_accum import accumulate_micro_grads

# Leaves smaller than this reduce in fp32 (quantization overhead not worth it —
# the analog of the reference's persistence thresholds for small tensors).
MIN_QUANT_SIZE = 2048


def qgz_allreduce(g, axis_name, group_size: int = 2048):
    """All-reduce one gradient leaf with int4 all-to-all + bf16 allgather.

    Runs INSIDE shard_map with ``axis_name`` bound.  Each rank contributes its
    local gradient; returns the replicated mean.
    """
    world = jax.lax.axis_size(axis_name)
    n = int(np.prod(g.shape))
    if n < MIN_QUANT_SIZE or n < world * 2:
        return jax.lax.pmean(g, axis_name)
    flat = g.reshape(-1).astype(jnp.float32)
    pad = (-n) % (world * 2)
    flat = jnp.pad(flat, (0, pad))
    # int4 all-to-all reduce-scatter: rank i ends with the summed shard i
    shard_sum = quantized_psum_scatter_int4(flat, axis_name, group_size=group_size)
    shard_mean = (shard_sum / world).astype(jnp.bfloat16)
    full = jax.lax.all_gather(shard_mean, axis_name, axis=0).reshape(-1)
    return full[:n].astype(g.dtype).reshape(g.shape)


def make_qgz_grad_fn(loss_fn, mesh, dp_axes: Sequence[str], gas: int, group_size: int = 2048):
    """Build grads_fn(params16, batch, micro_rngs, scale) -> (grads, loss_sum)
    with explicit int4-quantized dp gradient reduction.

    params16 replicated; batch leaves [gas, micro*dp, ...] sharded on dim 1 over
    the dp axes.  Returns replicated (mean) grads and the summed (over gas,
    mean over dp) loss.
    """
    axes = tuple(dp_axes)
    axis_name = axes if len(axes) > 1 else axes[0]

    def local(params16, batch, micro_rngs, scale):
        grads, loss_sum = accumulate_micro_grads(loss_fn, params16, batch, micro_rngs, scale)
        grads = jax.tree_util.tree_map(functools.partial(qgz_allreduce, axis_name=axis_name,
                                                         group_size=group_size), grads)
        loss_sum = jax.lax.pmean(loss_sum, axis_name)
        return grads, loss_sum

    def batch_spec(x):
        return PartitionSpec(None, axes if len(axes) > 1 else axes[0])

    def wrapped(params16, batch, micro_rngs, scale):
        in_specs = (
            jax.tree_util.tree_map(lambda _: PartitionSpec(), params16),
            jax.tree_util.tree_map(batch_spec, batch),
            PartitionSpec(),
            PartitionSpec(),
        )
        out_specs = (jax.tree_util.tree_map(lambda _: PartitionSpec(), params16), PartitionSpec())
        return shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)(params16, batch, micro_rngs, scale)

    return wrapped


def qwz_cast_gather(master, mesh, dp_axes: Sequence[str], compute_dtype, group_size: int = 2048,
                    plan=None):
    """qwZ analog: int8-quantize the local master shard, allgather int8 + scales,
    dequantize to the compute dtype — halving the updated-weight gather traffic
    vs a bf16 gather (reference partition_parameters.py:1171 quantized gather).

    ``master`` leaves are dp-sharded on some dim; output is replicated compute-
    dtype params.  Leaves too small to shard arrive replicated and just cast.
    """
    axes = tuple(dp_axes)
    axis_name = axes if len(axes) > 1 else axes[0]
    world = 1
    for a in axes:
        world *= mesh.shape[a]

    def gather_leaf(x):
        n = int(np.prod(x.shape))
        if n < MIN_QUANT_SIZE or n % world != 0:
            return x.astype(compute_dtype)

        def local(shard):
            gathered = quantized_allgather_int8(shard.reshape(-1).astype(compute_dtype),
                                                axis_name, group_size)
            return gathered.reshape(-1)

        # ask the sharding plan which dim the master leaf is actually sharded on
        # so the explicit gather matches the stored layout (no extra reshard)
        shard_dim = None
        if plan is not None:
            spec = plan._spec_for_shape(x.shape, sharded=True)
            for d, s in enumerate(spec):
                if s is not None:
                    shard_dim = d
                    break
        if shard_dim is None:
            shard_dim = _sharded_dim(x.shape, world)
        if shard_dim is None:
            return x.astype(compute_dtype)
        perm = (shard_dim, ) + tuple(d for d in range(x.ndim) if d != shard_dim)
        xt = x.transpose(perm)
        flatv = shard_map(local, mesh=mesh,
                          in_specs=PartitionSpec(axes if len(axes) > 1 else axes[0]),
                          out_specs=PartitionSpec(), check_vma=False)(xt.reshape(xt.shape[0], -1))
        back = flatv.reshape(xt.shape).transpose(tuple(np.argsort(perm)))
        return back

    return jax.tree_util.tree_map(gather_leaf, master)


def _sharded_dim(shape, world):
    candidates = [(d, s) for d, s in enumerate(shape) if s % world == 0]
    if not candidates:
        return None
    return max(candidates, key=lambda t: t[1])[0]
