"""ZeRO++-style quantized communication (qwZ / qgZ analogs).

Reference: ZeRO++ (runtime/zero/config.py:264-280, csrc/quantization/,
runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce):
  - qwZ: int8-quantized weight allgather (4x wire traffic cut)
  - qgZ: hierarchical int4 all-to-all gradient reduction (4x cut)
  - hpZ: secondary intra-node param shard (handled as a sharding-plan layout
    choice in sharding.py — gathers ride the fast 'fsdp' axis only)

Under GSPMD the dp reduction/gather collectives are implicit, so the quantized
variants take explicit control of the wire format with ``compat.shard_map`` over
the dp axes: gradients are accumulated per-shard, all-to-all'd as packed int4
(+fp32 group scales), summed locally, and re-gathered in bf16; the updated
master shards are quantized to int8 before the compute-copy allgather.

Total qgZ traffic per element: 0.5B (int4 a2a) + 2B (bf16 gather) = 2.5B vs
8B for an fp32 allreduce ring (2x4B) — and the a2a rides ICI.
"""

import functools
from typing import Any, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...compat import axis_size, shard_map

from ...ops.quantizer.quantize import (quantized_allgather_int8, quantized_psum_scatter_int4)
from ..grad_accum import accumulate_micro_grads

# Leaves smaller than this reduce in fp32 (quantization overhead not worth it —
# the analog of the reference's persistence thresholds for small tensors).
MIN_QUANT_SIZE = 2048


def qgz_allreduce(g, axis_name, group_size: int = 2048):
    """All-reduce one gradient leaf with int4 all-to-all + bf16 allgather.

    Runs INSIDE shard_map with ``axis_name`` bound.  Each rank contributes its
    local gradient; returns the replicated mean.
    """
    world = axis_size(axis_name)
    n = int(np.prod(g.shape))
    if n < MIN_QUANT_SIZE or n < world * 2:
        return jax.lax.pmean(g, axis_name)
    flat = g.reshape(-1).astype(jnp.float32)
    pad = (-n) % (world * 2)
    flat = jnp.pad(flat, (0, pad))
    # int4 all-to-all reduce-scatter: rank i ends with the summed shard i
    shard_sum = quantized_psum_scatter_int4(flat, axis_name, group_size=group_size)
    shard_mean = (shard_sum / world).astype(jnp.bfloat16)
    full = jax.lax.all_gather(shard_mean, axis_name, axis=0).reshape(-1)
    return full[:n].astype(g.dtype).reshape(g.shape)


def make_qgz_grad_fn(loss_fn, mesh, dp_axes: Sequence[str], gas: int, group_size: int = 2048):
    """Build grads_fn(params16, batch, micro_rngs, scale) -> (grads, loss_sum)
    with explicit int4-quantized dp gradient reduction.

    params16 replicated; batch leaves [gas, micro*dp, ...] sharded on dim 1 over
    the dp axes.  Returns replicated (mean) grads and the summed (over gas,
    mean over dp) loss.
    """
    axes = tuple(dp_axes)
    axis_name = axes if len(axes) > 1 else axes[0]

    def local(params16, batch, micro_rngs, scale):
        grads, loss_sum = accumulate_micro_grads(loss_fn, params16, batch, micro_rngs, scale)
        grads = jax.tree_util.tree_map(functools.partial(qgz_allreduce, axis_name=axis_name,
                                                         group_size=group_size), grads)
        loss_sum = jax.lax.pmean(loss_sum, axis_name)
        return grads, loss_sum

    def batch_spec(x):
        return PartitionSpec(None, axes if len(axes) > 1 else axes[0])

    def wrapped(params16, batch, micro_rngs, scale):
        in_specs = (
            jax.tree_util.tree_map(lambda _: PartitionSpec(), params16),
            jax.tree_util.tree_map(batch_spec, batch),
            PartitionSpec(),
            PartitionSpec(),
        )
        out_specs = (jax.tree_util.tree_map(lambda _: PartitionSpec(), params16), PartitionSpec())
        return shard_map(local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)(params16, batch, micro_rngs, scale)

    return wrapped


def qwz_cast_gather(master, mesh, dp_axes: Sequence[str], compute_dtype, group_size: int = 2048,
                    plan=None):
    """qwZ analog: int8-quantize the local master shard, allgather int8 + scales,
    dequantize to the compute dtype — halving the updated-weight gather traffic
    vs a bf16 gather (reference partition_parameters.py:1171 quantized gather).

    ``master`` leaves are dp-sharded on some dim; output is replicated compute-
    dtype params.  Leaves too small to shard arrive replicated and just cast.
    """
    axes = tuple(dp_axes)
    axis_name = axes if len(axes) > 1 else axes[0]
    world = 1
    for a in axes:
        world *= mesh.shape[a]

    def gather_leaf(x):
        n = int(np.prod(x.shape))
        if n < MIN_QUANT_SIZE or n % world != 0:
            return x.astype(compute_dtype)

        def local(shard):
            gathered = quantized_allgather_int8(shard.reshape(-1).astype(compute_dtype),
                                                axis_name, group_size)
            return gathered.reshape(-1)

        # ask the sharding plan which dim the master leaf is actually sharded on
        # so the explicit gather matches the stored layout (no extra reshard)
        shard_dim = _data_dim(plan, x.shape, axes) if plan is not None else None
        if shard_dim is None:
            shard_dim = _sharded_dim(x.shape, world)
        if shard_dim is None:
            return x.astype(compute_dtype)
        perm = (shard_dim, ) + tuple(d for d in range(x.ndim) if d != shard_dim)
        xt = x.transpose(perm)
        flatv = shard_map(local, mesh=mesh,
                          in_specs=PartitionSpec(axes if len(axes) > 1 else axes[0]),
                          out_specs=PartitionSpec(), check_vma=False)(xt.reshape(xt.shape[0], -1))
        back = flatv.reshape(xt.shape).transpose(tuple(np.argsort(perm)))
        return back

    return jax.tree_util.tree_map(gather_leaf, master)


def _sharded_dim(shape, world):
    candidates = [(d, s) for d, s in enumerate(shape) if s % world == 0]
    if not candidates:
        return None
    return max(candidates, key=lambda t: t[1])[0]


# ---------------------------------------------------------------------------
# ZeRO++ for stage 3: hierarchical over ('data' = slow/inter-slice, 'fsdp' =
# fast/ICI).  The reference factors qgZ over local/global node groups
# (coalesced_collectives.py:31 via groups.py:356 _get_local_all_to_all_group)
# and gathers qwZ int8 across nodes with the hpZ secondary shard served
# intra-node (partition_parameters.py:1171-1243).  TPU mapping: shard_map with
# axis_names={'data'} takes MANUAL control of the slow hop (int8 param gather,
# int4 grad reduce-scatter) while 'fsdp' stays on GSPMD auto — per-layer bf16
# gathers inside the model's scan ride ICI, exactly the hpZ secondary layout.
# ---------------------------------------------------------------------------


def _data_dim(plan, shape, axes):
    """Dim of ``shape`` the plan shards over any of the given mesh ``axes``
    (str or tuple) — the single source of truth for 'which dim carries the
    ZeRO shard' used by qwZ gathers and the stage-3 hierarchical paths."""
    want = (axes, ) if isinstance(axes, str) else tuple(axes)
    spec = plan._spec_for_shape(tuple(shape), sharded=True)
    for d, s in enumerate(spec):
        entries = s if isinstance(s, tuple) else (s, )
        if s is not None and any(a in entries for a in want):
            return d
    return None


def _manual_data_spec(plan, tree, data_axis):
    """in/out specs for shard_map(axis_names={'data'}): only the manual axis is
    named; fsdp stays auto and rides the arrays' existing shardings."""

    def leaf_spec(leaf):
        dim = _data_dim(plan, np.shape(leaf), data_axis)
        if dim is None:
            return PartitionSpec()
        spec = [None] * len(np.shape(leaf))
        spec[dim] = data_axis
        return PartitionSpec(*spec)

    return jax.tree_util.tree_map(leaf_spec, tree)


def _qwz_gather_dim(x, dim, axis_name, compute_dtype, group_size, quantize):
    """All-gather a master shard over the slow axis along ``dim`` (tiled),
    int8-quantized when ``quantize`` — the stage-3 qwZ gather into the hpZ
    secondary copy."""
    if not quantize or int(np.prod(x.shape)) < MIN_QUANT_SIZE:
        return jax.lax.all_gather(x.astype(compute_dtype), axis_name, axis=dim, tiled=True)
    stacked = quantized_allgather_int8(x.astype(compute_dtype), axis_name, group_size)
    # [W, ...] -> tiled concat on dim
    moved = jnp.moveaxis(stacked, 0, dim)
    shape = list(x.shape)
    shape[dim] = x.shape[dim] * stacked.shape[0]
    return moved.reshape(shape)


def _qgz_scatter_dim(g, dim, axis_name, group_size, quantize):
    """Reduce-scatter a gradient leaf over the slow axis along ``dim``,
    int4-quantized when ``quantize`` — the stage-3 qgZ hierarchical reduction
    (the fsdp part of the reduction stays on GSPMD auto)."""
    world = axis_size(axis_name)
    perm = (dim, ) + tuple(d for d in range(g.ndim) if d != dim)
    gt = g.transpose(perm)
    lead = gt.shape[0]
    flat = gt.reshape(-1)
    if quantize and flat.shape[0] >= MIN_QUANT_SIZE and flat.shape[0] % (world * 2) == 0:
        shard = quantized_psum_scatter_int4(flat, axis_name, group_size=group_size)
    else:
        shard = jax.lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True)
    out_shape = (lead // world, ) + gt.shape[1:]
    back = shard.reshape(out_shape).transpose(tuple(np.argsort(perm)))
    return back / world  # mean over the data replicas


def make_zpp3_grad_fn(loss_fn, mesh, plan, gas: int, *, qwz: bool, qgz: bool,
                      compute_dtype, data_axis: str = "data", group_size: int = 2048):
    """Build grads_fn(master, batch, micro_rngs, scale) -> (grads, loss_sum) for
    ZeRO-3 with ZeRO++ quantized communication on the slow axis.

    master: fp32, sharded over ('data','fsdp') per the plan.  Inside the manual
    'data' context: qwZ int8 gather -> fsdp-sharded bf16 secondary copy (hpZ);
    GSPMD per-layer gathers over fsdp during loss; qgZ int4 reduce-scatter of
    grads back to the ('data','fsdp') master layout.  Returned grads are the dp
    MEAN (divide only by gas*scale afterwards, matching the GSPMD path).
    """

    def wrapped(master, batch, micro_rngs, scale):
        dims = jax.tree_util.tree_map(lambda x: _data_dim(plan, np.shape(x), data_axis), master)
        master_specs = _manual_data_spec(plan, master, data_axis)
        batch_specs = jax.tree_util.tree_map(lambda _: PartitionSpec(None, data_axis), batch)
        in_specs = (master_specs, batch_specs, PartitionSpec(), PartitionSpec())
        out_specs = (master_specs, PartitionSpec())

        def body(master, batch, micro_rngs, scale):
            params16 = jax.tree_util.tree_map(
                lambda x, d: x.astype(compute_dtype) if d is None else _qwz_gather_dim(
                    x, d, data_axis, compute_dtype, group_size, qwz), master, dims)
            grads, loss_sum = accumulate_micro_grads(loss_fn, params16, batch, micro_rngs, scale)
            grads = jax.tree_util.tree_map(
                lambda g, d: jax.lax.pmean(g, data_axis) if d is None else _qgz_scatter_dim(
                    g, d, data_axis, group_size, qgz), grads, dims)
            return grads, jax.lax.pmean(loss_sum, data_axis)

        return shard_map(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         axis_names={data_axis}, check_vma=False)(master, batch, micro_rngs, scale)

    return wrapped
