"""zero.Init analog — construct params **born sharded**, never materialized whole.

Reference semantics (runtime/zero/partition_parameters.py:786 ``Init``): modules
built under the context get their parameters partitioned at construction time, so
a 7B model never exists unsharded on any rank; ``GatheredParameters``
(partition_parameters.py:2044) temporarily reassembles them for
inspection/surgery; ``OnDevice`` (utils/init_on_device.py:12) builds on the meta
device for deferred materialization.

The TPU-native mapping is functional rather than hook-based:

- ``Init.materialize(init_fn, *args)`` jits the param constructor with
  ``out_shardings`` from the ZeRO plan — XLA partitions the RNG work and each
  device computes and stores ONLY its shard.  Peak per-host memory is the shard
  bytes, not the model bytes (no torch-style "build then scatter").
- ``Init.abstract(init_fn, *args)`` is the OnDevice/meta analog:
  ``jax.eval_shape`` gives the params skeleton with zero allocation.
- ``Init.materialize_from_loader(abstract_params, get_leaf)`` streams an external
  checkpoint leaf-by-leaf through ``jax.make_array_from_callback``: the loader
  is asked for one leaf (or one leaf-slice) at a time, so peak host RSS is
  O(largest leaf + one device shard), ≪ total param bytes — the analog of
  shard-by-shard HF checkpoint streaming into ZeRO-3
  (module_inject/load_checkpoint.py + partition_parameters hooks).
- ``GatheredParameters(params)`` yields the full (host, numpy) tree for
  debugging/surgery and re-scatters mutations on exit.
"""

import contextlib
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import MeshTopology, get_topology
from .sharding import ShardingPlan, _path_str, build_sharding_plan

# telemetry for tests/diagnostics: the high-water mark of host bytes the
# streaming loader held at once (one leaf at a time if the loader is honest)
_max_loader_bytes = 0


def max_loader_bytes() -> int:
    return _max_loader_bytes


def reset_loader_stats() -> None:
    global _max_loader_bytes
    _max_loader_bytes = 0


class Init:
    """Sharded-at-construction parameter factory (zero.Init analog).

    Usage::

        ini = zero.Init(topology=topo, zero_config=cfg.zero_optimization,
                        tp_rules=llama.tp_rules)
        params = ini.materialize(llama.init_params, llama_cfg, jax.random.PRNGKey(0))

    ``params`` leaves come out sharded per the ZeRO plan's **master** role
    (sharded over dp/fsdp from stage 1 up, plus any tensor-parallel rules), so a
    subsequent ``deepspeed_tpu.initialize()`` reuses them without resharding.
    """

    def __init__(self,
                 topology: Optional[MeshTopology] = None,
                 zero_config=None,
                 tp_rules=None,
                 plan: Optional[ShardingPlan] = None,
                 dtype=None):
        self.topology = topology or get_topology()
        if plan is None:
            if zero_config is None:
                from ..config import ZeroConfig
                zero_config = ZeroConfig(stage=3)
            plan = build_sharding_plan(zero_config, self.topology, tp_rules=tp_rules)
        self.plan = plan
        self.dtype = dtype

    # ------------------------------------------------------------- abstract
    def abstract(self, init_fn: Callable, *args, **kwargs):
        """Meta-device analog (utils/init_on_device.py:12): shapes/dtypes only,
        zero bytes allocated.  args are closed over (configs etc. need not be
        jax types)."""
        return jax.eval_shape(lambda: init_fn(*args, **kwargs))

    # ---------------------------------------------------------- materialize
    def shardings(self, tree):
        """Master-role shardings for an (abstract or concrete) params tree."""
        return self.plan.master_shardings(tree)

    def materialize(self, init_fn: Callable, *args, **kwargs):
        """Run ``init_fn`` jitted with sharded outputs: every leaf is computed
        and stored partitioned; no host or single-device full copy ever exists
        (the anti-pattern this replaces: init on host -> device_put -> shard)."""
        abstract = self.abstract(init_fn, *args, **kwargs)
        shardings = self.shardings(abstract)
        cast = self.dtype

        def build():
            tree = init_fn(*args, **kwargs)
            if cast is not None:
                tree = jax.tree_util.tree_map(lambda x: x.astype(cast), tree)
            return tree

        return jax.jit(build, out_shardings=shardings)()

    def materialize_from_loader(self, abstract_params, get_leaf: Callable[[str, Any], np.ndarray]):
        """Stream external weights in shard-by-shard.

        ``get_leaf(path, abstract_leaf)`` returns either

        - the FULL numpy value for one leaf (called once per leaf, sequentially —
          peak host memory is one leaf), or
        - a **callable** ``slice_cb(index) -> np.ndarray`` producing just the
          requested shard (for big stacked leaves the loader then reads only the
          layers/rows a device actually owns — peak host memory is one shard).

        Each device materializes only its shard via
        ``jax.make_array_from_callback``.  Returns the sharded params tree.
        """
        global _max_loader_bytes
        flat, treedef = jax.tree_util.tree_flatten_with_path(abstract_params)
        shard_tree = self.shardings(abstract_params)
        shard_flat = jax.tree_util.tree_leaves(shard_tree)
        out = []
        for (path, leaf), sharding in zip(flat, shard_flat):
            pstr = _path_str(path)
            val = get_leaf(pstr, leaf)
            shape, dtype = tuple(leaf.shape), leaf.dtype
            if callable(val):
                def cb(idx, f=val, dt=dtype):
                    global _max_loader_bytes
                    part = np.asarray(f(idx)).astype(dt, copy=False)
                    _max_loader_bytes = max(_max_loader_bytes, part.nbytes)
                    return part

                arr = jax.make_array_from_callback(shape, sharding, cb)
            else:
                host = np.asarray(val)
                if host.shape != shape:
                    raise ValueError(f"loader returned shape {host.shape} for {pstr}, "
                                     f"expected {shape}")
                host = host.astype(dtype, copy=False)
                _max_loader_bytes = max(_max_loader_bytes, host.nbytes)
                arr = jax.make_array_from_callback(shape, sharding,
                                                   lambda idx, h=host: h[idx])
                del host
            out.append(arr)
        return jax.tree_util.tree_unflatten(treedef, out)


def init(init_fn: Callable, *args, topology=None, zero_config=None, tp_rules=None,
         dtype=None, **kwargs):
    """Functional one-shot: ``zero.init(llama.init_params, cfg, key, ...)``."""
    return Init(topology=topology, zero_config=zero_config, tp_rules=tp_rules,
                dtype=dtype).materialize(init_fn, *args, **kwargs)


class GatheredParameters:
    """Temporarily reassemble sharded params on host (partition_parameters.py:2044).

    ::

        gp = GatheredParameters(params, modifier_rank=0)
        with gp as host:                           # host: mutable numpy tree
            host["embed"][0] = 0.0                 # optional surgery
        params = gp.updated                        # re-scattered tree

    Matching the reference default, ``modifier_rank=None`` means **inspection
    only** — no re-scatter happens on exit (a 7B read-only peek costs one gather,
    not a round-trip).  Pass ``modifier_rank=0`` (any int — under a
    single-controller JAX mesh every host sees the same copy) to write
    mutations back.
    """

    def __init__(self, params, modifier_rank: Optional[int] = None, writeback: bool = True):
        self.params = params
        self.writeback = writeback and modifier_rank is not None
        self.updated = params
        self._host = None

    def __enter__(self):
        self._host = jax.tree_util.tree_map(lambda x: np.array(x), self.params)
        return self._host

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self.writeback:
            shardings = jax.tree_util.tree_map(
                lambda x: x.sharding if hasattr(x, "sharding") else None, self.params)
            self.updated = jax.tree_util.tree_map(
                lambda h, s: jax.make_array_from_callback(h.shape, s, lambda idx, hh=h: hh[idx])
                if isinstance(s, jax.sharding.Sharding) and h.ndim > 0 else jnp.asarray(h),
                self._host, shardings)
        self._host = None
        return False
