"""ZeRO subsystem — sharding plans, quantized collectives, sharded init.

The public surface mirrors ``deepspeed.zero``: ``Init`` / ``GatheredParameters``
(reference partition_parameters.py:786,2044) re-exported here and at
``deepspeed_tpu.zero``.
"""

from .init import GatheredParameters, Init, init, max_loader_bytes, reset_loader_stats
from .tiling import TiledLinear, tiled_matmul
from .sharding import ShardingPlan, build_sharding_plan

__all__ = [
    "GatheredParameters", "Init", "init", "max_loader_bytes", "reset_loader_stats",
    "ShardingPlan", "build_sharding_plan",
    "TiledLinear", "tiled_matmul",
]
