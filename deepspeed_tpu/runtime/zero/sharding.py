"""ZeRO sharding rules — the TPU-native core of stages 0-3.

Reference mechanics (runtime/zero/stage_1_and_2.py, stage3.py) exist because
PyTorch is eager: grad hooks, IPG buckets, flat fp32 partitions, explicit
allgather of updated shards.  Under XLA/GSPMD the same memory states are
expressed as sharding annotations on the train-state pytree and the compiler
inserts the matching collectives:

  stage 0 (DDP):     params/opt replicated over dp; grads psum'd          -> allreduce
  stage 1:           optimizer state + fp32 master sharded over dp        -> step shards
                     params stay replicated                               -> allgather of
                                                                             updated shards
  stage 2:           + gradients sharded over dp (annotated inside the    -> reduce-scatter
                     step via with_sharding_constraint)                      instead of
                                                                             allreduce
  stage 3 (FSDP):    + compute params sharded over dp; each layer's use   -> per-layer
                     forces a just-in-time allgather, freed after use        allgather,
                     (scan-over-layers bounds live memory like the           like the
                     reference's coordinator's gather/release)               coordinator

The per-leaf rule: shard the largest dimension divisible by the dp shard world
on the ('data','fsdp') mesh axes; leaves with no divisible dim (scalars, small
vectors) stay replicated — the analog of the reference's persistence thresholds
(param_persistence_threshold, zero/config.py:194) under which params are kept
whole.
"""

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, MeshTopology


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Per-role sharding functions: each maps a pytree (by leaf shape) to a
    matching tree of NamedShardings."""
    topo: MeshTopology
    stage: int
    shard_axes: Tuple[str, ...]
    persistence_threshold: int = 0

    def _spec_for_shape(self, shape, sharded: bool) -> PartitionSpec:
        if not sharded or len(shape) == 0:
            return PartitionSpec()
        world = 1
        for a in self.shard_axes:
            world *= self.topo.axis_size(a)
        if world == 1:
            return PartitionSpec()
        if int(np.prod(shape)) <= self.persistence_threshold:
            return PartitionSpec()  # small params stay whole (persistence analog)
        # largest dim divisible by the shard world
        candidates = [(d, s) for d, s in enumerate(shape) if s % world == 0]
        if not candidates:
            return PartitionSpec()
        dim = max(candidates, key=lambda t: t[1])[0]
        spec = [None] * len(shape)
        spec[dim] = self.shard_axes if len(self.shard_axes) > 1 else self.shard_axes[0]
        return PartitionSpec(*spec)

    def _tree_shardings(self, tree, sharded: bool):
        return jax.tree_util.tree_map(
            lambda leaf: NamedSharding(self.topo.mesh, self._spec_for_shape(np.shape(leaf), sharded)), tree)

    # -- roles ---------------------------------------------------------------
    def param_shardings(self, params):
        """Compute (bit16) params: sharded only at stage 3."""
        return self._tree_shardings(params, sharded=self.stage >= 3)

    def master_shardings(self, master_params):
        """FP32 master copy: sharded from stage 1 up."""
        return self._tree_shardings(master_params, sharded=self.stage >= 1)

    def opt_state_shardings(self, opt_state):
        """Optimizer moments: sharded from stage 1 up (scalars replicated)."""
        return self._tree_shardings(opt_state, sharded=self.stage >= 1)

    def grad_shardings(self, grads):
        """Gradients: sharded from stage 2 up (reduce-scatter instead of allreduce)."""
        return self._tree_shardings(grads, sharded=self.stage >= 2)

    def constrain_grads(self, grads):
        """Annotate gradients inside the jitted step so XLA lowers the dp reduction
        to reduce-scatter (stage>=2) rather than allreduce — the analog of
        average_tensor's rank-sliced reduce (stage_1_and_2.py:1020)."""
        if self.stage < 2:
            return grads
        return jax.tree_util.tree_map(
            lambda g: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.topo.mesh, self._spec_for_shape(np.shape(g), True))), grads)


def build_sharding_plan(zero_config, topo: MeshTopology) -> ShardingPlan:
    axes = tuple(a for a in (DATA_AXIS, FSDP_AXIS) if topo.axis_size(a) > 1) or (DATA_AXIS, )
    threshold = zero_config.param_persistence_threshold if zero_config.stage >= 3 else 0
    return ShardingPlan(topo=topo,
                        stage=zero_config.stage,
                        shard_axes=axes,
                        persistence_threshold=threshold)
