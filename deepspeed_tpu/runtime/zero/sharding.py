"""ZeRO sharding rules — the TPU-native core of stages 0-3.

Reference mechanics (runtime/zero/stage_1_and_2.py, stage3.py) exist because
PyTorch is eager: grad hooks, IPG buckets, flat fp32 partitions, explicit
allgather of updated shards.  Under XLA/GSPMD the same memory states are
expressed as sharding annotations on the train-state pytree and the compiler
inserts the matching collectives:

  stage 0 (DDP):     params/opt replicated over dp; grads psum'd          -> allreduce
  stage 1:           optimizer state + fp32 master sharded over dp        -> step shards
                     params stay replicated                               -> allgather of
                                                                             updated shards
  stage 2:           + gradients sharded over dp (annotated inside the    -> reduce-scatter
                     step via with_sharding_constraint)                      instead of
                                                                             allreduce
  stage 3 (FSDP):    + compute params sharded over dp; each layer's use   -> per-layer
                     forces a just-in-time allgather, freed after use        allgather,
                     (scan-over-layers bounds live memory like the           like the
                     reference's coordinator's gather/release)               coordinator

The per-leaf rule: shard the largest dimension divisible by the dp shard world
on the ('data','fsdp') mesh axes; leaves with no divisible dim (scalars, small
vectors) stay replicated — the analog of the reference's persistence thresholds
(param_persistence_threshold, zero/config.py:194) under which params are kept
whole.
"""

import dataclasses
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ...parallel.mesh import (DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, SEQUENCE_AXIS,
                              TENSOR_AXIS, MeshTopology)

# A model-parallel rule maps (dotted param path, shape) to one of:
#   None                      — no model-parallel sharding for this leaf
#   int d                     — shard dim d over the 'tensor' axis
#   (d, axis_name)            — shard dim d over the named mesh axis
#   [(d1, a1), (d2, a2), ...] — multiple pinned dims (e.g. pipe dim 0 + tp dim 2)
# Models export one (e.g. models.llama.tp_rules) — the built-in analog of
# Megatron's mpu column/row-parallel layout the reference consumes externally
# (deepspeed/__init__.py:95 mpu contract) and AutoTP infers for inference
# (module_inject/auto_tp.py:188); pipeline stages pin dim 0 over 'pipe'
# (runtime/pipe/module.py pipe_rules).
TpRuleFn = Callable[[str, Tuple[int, ...]], Any]


def _normalize_rule(out) -> list:
    if out is None:
        return []
    if isinstance(out, int):
        return [(out, TENSOR_AXIS)]
    if isinstance(out, tuple) and len(out) == 2 and isinstance(out[0], int):
        return [out]
    return list(out)


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return ".".join(parts)


@dataclasses.dataclass(frozen=True)
class ShardingPlan:
    """Per-role sharding functions: each maps a pytree (by leaf shape + path) to
    a matching tree of NamedShardings, merging ZeRO dp/fsdp sharding with
    tensor-parallel rules."""
    topo: MeshTopology
    stage: int
    shard_axes: Tuple[str, ...]
    persistence_threshold: int = 0
    tp_rules: Optional[TpRuleFn] = None

    def _spec_for_shape(self, shape, sharded: bool, path: str = "", axes=None,
                        respect_persistence: bool = False) -> PartitionSpec:
        shard_axes = tuple(axes) if axes is not None else self.shard_axes
        if len(shape) == 0:
            return PartitionSpec()
        spec = [None] * len(shape)
        pinned = {}
        if self.tp_rules is not None:
            for dim, axis in _normalize_rule(self.tp_rules(path, tuple(shape))):
                axis_size = self.topo.axis_size(axis)
                if axis_size > 1 and shape[dim] % axis_size == 0:
                    spec[dim] = axis
                    pinned[dim] = axis
        if not sharded:
            return PartitionSpec(*spec)
        # a mesh axis may appear in a PartitionSpec only once: axes already
        # pinned by tp_rules (tensor, expert, ...) leave the ZeRO pool for
        # this leaf
        avail = tuple(a for a in shard_axes if a not in pinned.values())
        world = 1
        for a in avail:
            world *= self.topo.axis_size(a)
        if world == 1:
            return PartitionSpec(*spec)
        if respect_persistence and int(np.prod(shape)) <= self.persistence_threshold:
            # persistent small params stay gathered (reference
            # param_persistence_threshold, partition_parameters.py:1479) —
            # COMPUTE params only; master/moments always partition
            return PartitionSpec(*spec)
        zero_axes = avail if len(avail) > 1 else avail[0]
        # largest dim divisible by the shard world, excluding pinned dims;
        # fall back to stacking zero axes onto a pinned dim if it alone divides
        candidates = [(d, s) for d, s in enumerate(shape) if s % world == 0 and d not in pinned]
        if candidates:
            dim = max(candidates, key=lambda t: t[1])[0]
            spec[dim] = zero_axes
        else:
            for dim, axis in pinned.items():
                if shape[dim] % (world * self.topo.axis_size(axis)) == 0:
                    spec[dim] = (axis, *avail)
                    break
        return PartitionSpec(*spec)

    def _tree_shardings(self, tree, sharded: bool, axes=None, respect_persistence: bool = False):
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        out = [
            NamedSharding(self.topo.mesh,
                          self._spec_for_shape(np.shape(leaf), sharded, _path_str(path),
                                               axes=axes, respect_persistence=respect_persistence))
            for path, leaf in flat
        ]
        return jax.tree_util.tree_unflatten(treedef, out)

    # -- roles ---------------------------------------------------------------
    def param_shardings(self, params):
        """Compute (bit16) params: sharded only at stage 3; leaves at or under
        param_persistence_threshold stay gathered (persistent params)."""
        return self._tree_shardings(params, sharded=self.stage >= 3,
                                    respect_persistence=True)

    def master_shardings(self, master_params):
        """FP32 master copy: sharded from stage 1 up."""
        return self._tree_shardings(master_params, sharded=self.stage >= 1)

    def opt_state_shardings(self, opt_state):
        """Optimizer moments: sharded from stage 1 up (scalars replicated)."""
        return self._tree_shardings(opt_state, sharded=self.stage >= 1)

    def secondary_shardings(self, params):
        """hpZ secondary partition (reference zero_hpz_partition_size,
        partition_parameters.py:1171): the compute copy sharded over the fast
        intra-slice 'fsdp' axis only — the 'data' gather happens ONCE at the
        secondary materialization, per-layer gathers then ride fsdp/ICI.
        Persistent small params stay gathered here too (same compute-copy
        contract as param_shardings)."""
        return self._tree_shardings(params, sharded=True, axes=(FSDP_AXIS, ),
                                    respect_persistence=True)

    def grad_shardings(self, grads):
        """Gradients: sharded from stage 2 up (reduce-scatter instead of allreduce)."""
        return self._tree_shardings(grads, sharded=self.stage >= 2)

    def constrain_grads(self, grads):
        """Annotate gradients inside the jitted step so XLA lowers the dp reduction
        to reduce-scatter (stage>=2) rather than allreduce — the analog of
        average_tensor's rank-sliced reduce (stage_1_and_2.py:1020).  The leaf
        path threads through so tp/expert pins match grad_shardings exactly
        (a pathless spec would drop pins and force per-step reshards)."""
        if self.stage < 2:
            return grads
        return jax.tree_util.tree_map_with_path(
            lambda path, g: jax.lax.with_sharding_constraint(
                g, NamedSharding(self.topo.mesh,
                                 self._spec_for_shape(np.shape(g), True, _path_str(path)))),
            grads)


def build_sharding_plan(zero_config, topo: MeshTopology, tp_rules: Optional[TpRuleFn] = None) -> ShardingPlan:
    # ZeRO states shard over data x fsdp x SEQUENCE x EXPERT: params are
    # replicated across sequence and expert ranks, so both join the
    # partitioning pool — the reference's seq_data_parallel_group
    # (engine.py:1515) and expert_data_parallel groups (groups.py:113)
    # as-ZeRO-dp-group compositions.  Expert-sharded leaves keep their
    # pinned expert dim; the zero axes land on another dim.
    axes = tuple(a for a in (DATA_AXIS, FSDP_AXIS, SEQUENCE_AXIS, EXPERT_AXIS)
                 if topo.axis_size(a) > 1) or (DATA_AXIS, )
    mics = int(getattr(zero_config, "mics_shard_size", -1) or -1)
    if mics > 0 and zero_config.stage >= 3:
        # MiCS (reference runtime/zero/mics.py:48): ZeRO-3 scoped to a shard
        # group — params partitioned over the 'fsdp' axis only (the replica
        # scale-out rides 'data'; grads still reduce over both).  The mesh's
        # fsdp axis IS the shard group; its size must match mics_shard_size.
        if topo.axis_size(FSDP_AXIS) != mics:
            raise ValueError(f"mics_shard_size={mics} requires mesh axis fsdp={mics} "
                             f"(got fsdp={topo.axis_size(FSDP_AXIS)}); replicas ride 'data'")
        replicated = [a for a in (SEQUENCE_AXIS, EXPERT_AXIS) if topo.axis_size(a) > 1]
        if replicated:
            from ...utils.logging import logger
            logger.warning(f"MiCS shard groups are fsdp-scoped: ZeRO state will "
                           f"REPLICATE across {replicated} (no seq/expert_data "
                           f"composition under mics_shard_size)")
        axes = (FSDP_AXIS, )
    threshold = zero_config.param_persistence_threshold if zero_config.stage >= 3 else 0
    return ShardingPlan(topo=topo,
                        stage=zero_config.stage,
                        shard_axes=axes,
                        persistence_threshold=threshold,
                        tp_rules=tp_rules)
