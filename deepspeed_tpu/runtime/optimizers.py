"""Native optimizer suite.

Analog of the reference's fused/CPU optimizers (csrc/adam/multi_tensor_adam.cu
FusedAdam, csrc/lamb, csrc/lion, csrc/adagrad + deepspeed/ops wrappers).  The
reference needs hand-written multi-tensor CUDA kernels because eager torch
launches one kernel per tensor; under XLA a vectorized pytree update compiles to
fused HBM-bandwidth-bound loops already, so the core implementations here are
pure jnp update rules (a Pallas fused-flat-buffer variant lives in
deepspeed_tpu/ops/adam for the cases XLA underperforms).

Interface: ``opt = get_optimizer(name, **hyperparams)``;
``state = opt.init(params)``; ``updates, state = opt.update(grads, state, params, lr)``
where ``updates`` are deltas added to the master params.  All state is a pytree
so ZeRO sharding rules apply to it transparently.
"""

import dataclasses
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class Optimizer:
    init: Callable
    update: Callable  # (grads, state, params, lr) -> (updates, new_state)
    name: str = "optimizer"
    # Optional fused whole-step: (grads, state, params, lr) -> (new_params, new_state).
    # When set, the engine applies it directly (no delta round-trip) so the Pallas
    # flat-buffer kernel (ops/adam/fused_adam.py) does ONE aliased HBM pass —
    # the multi-tensor-apply analog (csrc/adam/multi_tensor_adam.cu).
    step_fn: Optional[Callable] = None
    # 1-bit optimizers (runtime/onebit.py): comm-coupled local-step spec; the
    # engine builds a shard_map train step around it (reference fp16/onebit/).
    onebit: Optional[Any] = None


def _tree_zeros_like(params, dtype=None):
    return jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, dtype=dtype or p.dtype), params)


class _Packed:
    """Opaque multi-value leaf for tree_map fan-out.  Deliberately NOT a
    pytree: a structural tuple/NamedTuple inside the params tree can never be
    confused with it, unlike the old ``is_leaf=isinstance(t, tuple)`` pattern
    that silently mis-split tuple-structured models (ADVICE r3 #3)."""
    __slots__ = ("vals", )

    def __init__(self, *vals):
        self.vals = vals


def _split(packed_tree, n: int):
    """Fan a tree of _Packed leaves out into ``n`` parallel trees."""
    return tuple(jax.tree_util.tree_map(lambda t: t.vals[i], packed_tree,
                                        is_leaf=lambda t: isinstance(t, _Packed))
                 for i in range(n))


class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any  # m
    exp_avg_sq: Any  # v


def adam(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, adam_w_mode=True, bias_correction=True) -> Optimizer:
    """FusedAdam semantics (csrc/adam/fused_adam_frontend.cpp + ops/adam/fused_adam.py):
    adam_w_mode=True decouples weight decay (AdamW); False adds L2 into the grad."""
    b1, b2 = betas

    def init(params):
        return AdamState(step=jnp.zeros((), jnp.int32),
                         exp_avg=_tree_zeros_like(params),
                         exp_avg_sq=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        step = state.step + 1
        stepf = step.astype(jnp.float32)
        if bias_correction:
            bc1 = 1.0 - b1**stepf
            bc2 = 1.0 - b2**stepf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(g, m, v, p):
            if not adam_w_mode and weight_decay != 0.0:
                g = g + weight_decay * p
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            denom = jnp.sqrt(v_new / bc2) + eps
            upd = -lr * (m_new / bc1) / denom
            if adam_w_mode and weight_decay != 0.0:
                upd = upd - lr * weight_decay * p
            return _Packed(upd, m_new, v_new)

        flat = jax.tree_util.tree_map(leaf, grads, state.exp_avg, state.exp_avg_sq, params)
        updates, m, v = _split(flat, 3)
        return updates, AdamState(step=step, exp_avg=m, exp_avg_sq=v)

    return Optimizer(init=init, update=update, name="adamw" if adam_w_mode else "adam")


def fused_adam(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, adam_w_mode=True,
               bias_correction=True) -> Optimizer:
    """FusedAdam backed by the Pallas flat-buffer kernel (ops/adam/fused_adam.py,
    the csrc/adam/multi_tensor_adam.cu analog).  ``step_fn`` ravels each leaf and
    updates p/m/v in one aliased VMEM sweep; the generic delta-form ``update``
    stays available (identical math via the plain-jnp path) for callers that
    need deltas (offload, tests)."""
    from ..ops.adam.fused_adam import fused_adamw_flat
    base = adam(betas=betas, eps=eps, weight_decay=weight_decay,
                adam_w_mode=adam_w_mode, bias_correction=bias_correction)
    b1, b2 = betas

    def step_fn(grads, state, params, lr):
        step = state.step + 1

        def leaf(g, m, v, p):
            p2, m2, v2 = fused_adamw_flat(p.ravel(), m.ravel(), v.ravel(), g.ravel(),
                                          lr=lr, beta1=b1, beta2=b2, eps=eps,
                                          weight_decay=weight_decay, step=step)
            return _Packed(p2.reshape(p.shape), m2.reshape(m.shape), v2.reshape(v.shape))

        flat = jax.tree_util.tree_map(leaf, grads, state.exp_avg, state.exp_avg_sq, params)
        new_params, m, v = _split(flat, 3)
        return new_params, AdamState(step=step, exp_avg=m, exp_avg_sq=v)

    # the kernel hard-codes decoupled decay + bias correction; other modes run
    # through the generic path only
    return Optimizer(init=base.init, update=base.update, name="fused_adam",
                     step_fn=step_fn if (adam_w_mode and bias_correction) else None)


class Adam8bitState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any  # int8 (groups, group_size) per leaf
    exp_avg_sq: Any  # int8 sqrt-domain (groups, group_size) per leaf
    scale_m: Any  # fp32 (groups, 1) per leaf
    scale_v: Any  # fp32 (groups, 1) per leaf


def fused_adam8bit(betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                   group_size: int = 1024, bias_correction: bool = True) -> Optimizer:
    """AdamW with blockwise int8 moments (ops/adam/adam8bit.py): optimizer
    state shrinks 8 -> ~2.01 bytes/param, the lever that fits ~1.4B params on
    one 16GB chip.  Decoupled decay + bias correction only (AdamW semantics,
    matching the Pallas kernel)."""
    from ..ops.adam.adam8bit import fused_adamw8bit_flat, init_quantized_moment
    if not bias_correction:
        raise ValueError("fused_adam8bit implements AdamW with bias correction; "
                         "set bias_correction true or use adamw/fused_adam")
    b1, b2 = betas

    def init(params):
        def leaf(p):
            q, s = init_quantized_moment(int(np.prod(p.shape)) if p.shape else 1,
                                         group_size)
            return _Packed(q, s)

        pairs = jax.tree_util.tree_map(leaf, params)
        q, s = _split(pairs, 2)
        return Adam8bitState(step=jnp.zeros((), jnp.int32),
                             exp_avg=q, exp_avg_sq=jax.tree_util.tree_map(jnp.copy, q),
                             scale_m=s, scale_v=jax.tree_util.tree_map(jnp.copy, s))

    def _apply(grads, state, params, lr, use_kernel):
        step = state.step + 1

        def leaf(g, m8, v8, sm, sv, p):
            p2, m2, v2, sm2, sv2 = fused_adamw8bit_flat(
                p.ravel(), m8, v8, sm, sv, g.ravel(), lr=lr, beta1=b1, beta2=b2,
                eps=eps, weight_decay=weight_decay, step=step,
                group_size=group_size, use_kernel=use_kernel)
            return _Packed(p2.reshape(p.shape), m2, v2, sm2, sv2)

        flat = jax.tree_util.tree_map(
            leaf, grads, state.exp_avg, state.exp_avg_sq,
            state.scale_m, state.scale_v, params)
        new_params, m, v, sm, sv = _split(flat, 5)
        new_state = Adam8bitState(step=step, exp_avg=m, exp_avg_sq=v,
                                  scale_m=sm, scale_v=sv)
        return new_params, new_state

    def update(grads, state, params, lr):
        # delta form, plain-XLA math: runs under GSPMD on any mesh (a
        # pallas_call would pin/replicate sharded leaves)
        new_params, new_state = _apply(grads, state, params, lr, use_kernel=False)
        updates = jax.tree_util.tree_map(lambda n, p: n - p, new_params, params)
        return updates, new_state

    return Optimizer(init=init, update=update, name="fused_adam8bit",
                     step_fn=lambda g, s, p, lr: _apply(g, s, p, lr, use_kernel=True))


class SGDState(NamedTuple):
    momentum_buf: Any


def sgd(momentum=0.0, weight_decay=0.0, nesterov=False) -> Optimizer:

    def init(params):
        return SGDState(momentum_buf=_tree_zeros_like(params))

    def update(grads, state, params, lr):

        def leaf(g, buf, p):
            if weight_decay != 0.0:
                g = g + weight_decay * p
            buf_new = momentum * buf + g
            d = (g + momentum * buf_new) if nesterov else (buf_new if momentum != 0.0 else g)
            return _Packed(-lr * d, buf_new)

        flat = jax.tree_util.tree_map(leaf, grads, state.momentum_buf, params)
        updates, buf = _split(flat, 2)
        return updates, SGDState(momentum_buf=buf)

    return Optimizer(init=init, update=update, name="sgd")


class LionState(NamedTuple):
    exp_avg: Any


def lion(betas=(0.9, 0.99), weight_decay=0.0) -> Optimizer:
    """FusedLion semantics (csrc/lion/fused_lion_frontend.cpp): sign-of-interpolation
    update; decoupled weight decay."""
    b1, b2 = betas

    def init(params):
        return LionState(exp_avg=_tree_zeros_like(params))

    def update(grads, state, params, lr):

        def leaf(g, m, p):
            upd = -lr * jnp.sign(b1 * m + (1.0 - b1) * g)
            if weight_decay != 0.0:
                upd = upd - lr * weight_decay * p
            m_new = b2 * m + (1.0 - b2) * g
            return _Packed(upd, m_new)

        flat = jax.tree_util.tree_map(leaf, grads, state.exp_avg, params)
        updates, m = _split(flat, 2)
        return updates, LionState(exp_avg=m)

    return Optimizer(init=init, update=update, name="lion")


class AdagradState(NamedTuple):
    accum: Any


def adagrad(eps=1e-10, weight_decay=0.0) -> Optimizer:
    """DeepSpeedCPUAdagrad semantics (csrc/adagrad/cpu_adagrad.cpp)."""

    def init(params):
        return AdagradState(accum=_tree_zeros_like(params))

    def update(grads, state, params, lr):

        def leaf(g, acc, p):
            if weight_decay != 0.0:
                g = g + weight_decay * p
            acc_new = acc + g * g
            return _Packed(-lr * g / (jnp.sqrt(acc_new) + eps), acc_new)

        flat = jax.tree_util.tree_map(leaf, grads, state.accum, params)
        updates, acc = _split(flat, 2)
        return updates, AdagradState(accum=acc)

    return Optimizer(init=init, update=update, name="adagrad")


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def lamb(betas=(0.9, 0.999), eps=1e-6, weight_decay=0.0, max_coeff=10.0, min_coeff=0.01) -> Optimizer:
    """FusedLamb semantics (csrc/lamb/fused_lamb_cuda_kernel.cu): Adam direction
    rescaled by trust ratio ||p|| / ||update||, clamped to [min_coeff, max_coeff]."""
    b1, b2 = betas

    def init(params):
        return LambState(step=jnp.zeros((), jnp.int32),
                         exp_avg=_tree_zeros_like(params),
                         exp_avg_sq=_tree_zeros_like(params))

    def update(grads, state, params, lr):
        step = state.step + 1

        def leaf(g, m, v, p):
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * (g * g)
            u = m_new / (jnp.sqrt(v_new) + eps)
            if weight_decay != 0.0:
                u = u + weight_decay * p
            p_norm = jnp.linalg.norm(p.astype(jnp.float32).ravel())
            u_norm = jnp.linalg.norm(u.astype(jnp.float32).ravel())
            trust = jnp.where((p_norm > 0) & (u_norm > 0), jnp.clip(p_norm / u_norm, min_coeff, max_coeff), 1.0)
            return _Packed(-lr * trust * u, m_new, v_new)

        flat = jax.tree_util.tree_map(leaf, grads, state.exp_avg, state.exp_avg_sq, params)
        updates, m, v = _split(flat, 3)
        return updates, LambState(step=step, exp_avg=m, exp_avg_sq=v)

    return Optimizer(init=init, update=update, name="lamb")


# Registry — names match the reference's accepted optimizer type spellings
# (deepspeed/runtime/config.py ADAM_OPTIMIZER etc. + engine._configure_basic_optimizer:1267)
_OPTIMIZERS: Dict[str, Callable[..., Optimizer]] = {}


def _register(names, builder):
    for n in names:
        _OPTIMIZERS[n] = builder


_register(["adam"], lambda lr=None, **kw: adam(adam_w_mode=False, **_strip(kw)))
_register(["adamw"], lambda lr=None, **kw: adam(adam_w_mode=True, **_strip(kw)))
_register(["fusedadam", "fused_adam"], lambda lr=None, **kw: fused_adam(**_strip(kw)))
_register(["fusedadam8bit", "fused_adam8bit", "adam8bit"],
          lambda lr=None, **kw: fused_adam8bit(**_strip(kw)))
_register(["sgd"], lambda lr=None, **kw: sgd(**_strip(kw)))
_register(["lion", "fusedlion"], lambda lr=None, **kw: lion(**_strip(kw)))
_register(["adagrad"], lambda lr=None, **kw: adagrad(**_strip(kw)))
_register(["lamb", "fusedlamb"], lambda lr=None, **kw: lamb(**_strip(kw)))


def _onebit_builder(which):

    def build(lr=None, **kw):
        from . import onebit as _ob
        return getattr(_ob, which)(**_strip(kw))

    return build


# reference spellings: ONEBIT_ADAM_OPTIMIZER 'onebitadam', ONEBIT_LAMB_OPTIMIZER
# 'onebitlamb', ZERO_ONE_ADAM_OPTIMIZER 'zerooneadam' (runtime/config.py)
_register(["onebitadam", "onebit_adam"], _onebit_builder("onebit_adam"))
_register(["onebitlamb", "onebit_lamb"], _onebit_builder("onebit_lamb"))
_register(["zerooneadam", "zero_one_adam"], _onebit_builder("zero_one_adam"))


def _strip(kw):
    # Drop torch-style kwargs that don't map (e.g. torch_adam, fused flags).
    drop = {"torch_adam", "fused", "cuda_aware", "adam_w_mode", "comm_backend_name",
            "check_overflow", "pipeline_enabled"}
    out = {k: v for k, v in kw.items() if k not in drop}
    if "betas" in out:
        out["betas"] = tuple(out["betas"])
    return out


def get_optimizer(name: str, **params) -> Optimizer:
    key = name.lower()
    if key not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {name!r}; supported: {sorted(set(_OPTIMIZERS))}")
    return _OPTIMIZERS[key](**params)


# ---------------------------------------------------------------------------
# Loss scaling (reference runtime/fp16/loss_scaler.py LossScaler/DynamicLossScaler)
# ---------------------------------------------------------------------------


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray
    growth_counter: jnp.ndarray  # consecutive non-overflow steps
    hysteresis: jnp.ndarray


def init_loss_scale(fp16_cfg, static: bool = False) -> LossScaleState:
    if fp16_cfg.loss_scale and fp16_cfg.loss_scale > 0:
        scale = float(fp16_cfg.loss_scale)
    else:
        scale = float(2.0**fp16_cfg.initial_scale_power)
    return LossScaleState(cur_scale=jnp.float32(scale),
                          growth_counter=jnp.zeros((), jnp.int32),
                          hysteresis=jnp.asarray(fp16_cfg.hysteresis, jnp.int32))


def update_loss_scale(state: LossScaleState, overflow, fp16_cfg) -> LossScaleState:
    """Pure analog of DynamicLossScaler.update_scale (runtime/fp16/loss_scaler.py:175):
    halve on overflow (after hysteresis), double every loss_scale_window clean steps."""
    dynamic = not (fp16_cfg.loss_scale and fp16_cfg.loss_scale > 0)
    if not dynamic:
        return state
    min_scale = jnp.float32(max(fp16_cfg.min_loss_scale, 1.0))

    def on_overflow(s):
        hyst = s.hysteresis - 1
        new_scale = jnp.where(hyst <= 0, jnp.maximum(s.cur_scale / 2.0, min_scale), s.cur_scale)
        new_hyst = jnp.where(hyst <= 0, jnp.asarray(fp16_cfg.hysteresis, jnp.int32), hyst)
        return LossScaleState(cur_scale=new_scale, growth_counter=jnp.zeros((), jnp.int32), hysteresis=new_hyst)

    def on_clean(s):
        counter = s.growth_counter + 1
        grow = counter >= fp16_cfg.loss_scale_window
        return LossScaleState(cur_scale=jnp.where(grow, s.cur_scale * 2.0, s.cur_scale),
                              growth_counter=jnp.where(grow, 0, counter),
                              hysteresis=s.hysteresis if fp16_cfg.consecutive_hysteresis else jnp.asarray(
                                  fp16_cfg.hysteresis, jnp.int32))

    return jax.tree_util.tree_map(lambda a, b: jnp.where(overflow, a, b), on_overflow(state), on_clean(state))


def global_grad_norm(grads) -> jnp.ndarray:
    """L2 norm over the whole gradient pytree (reference get_global_norm /
    scaled_global_norm stage_1_and_2.py:1752)."""
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads)]
    return jnp.sqrt(sum(leaves))


def clip_by_global_norm(grads, max_norm: float, precomputed_norm=None):
    norm = precomputed_norm if precomputed_norm is not None else global_grad_norm(grads)
    clip_coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    # preserve each leaf's dtype (bf16 grads must not silently promote to f32)
    return jax.tree_util.tree_map(lambda g: (g * clip_coef).astype(g.dtype), grads), norm


def has_overflow(grads) -> jnp.ndarray:
    """NaN/Inf scan (reference stage3.py:2114 _has_inf_or_nan)."""
    leaves = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in jax.tree_util.tree_leaves(grads)]
    out = jnp.zeros((), jnp.bool_)
    for l in leaves:
        out = jnp.logical_or(out, l)
    return out
