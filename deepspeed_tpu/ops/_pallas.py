"""Shared Pallas dispatch policy for the ops kernels.

One flag + one predicate, imported by flash/fused_adam/quantize so tests can
monkeypatch a single module and dispatch-policy changes happen in one place.
"""

import jax

INTERPRET = False  # flipped by tests / debugging


def use_pallas() -> bool:
    return INTERPRET or jax.default_backend() == "tpu"
