"""Pallas fused AdamW with blockwise 8-bit optimizer states.

Memory-native analog of the reference's quantized-state direction (ZeRO++
qwZ/qgZ quantize *communication*; this quantizes the *resident* Adam moments,
the way bitsandbytes-style 8-bit optimizers do): both moments live in HBM as
int8 with one fp32 scale per ``group_size`` elements, cutting optimizer state
from 8 bytes/param to ~2.01 bytes/param.  With the fp32 master that is
~6 bytes/param steady state instead of 14 with a separate bf16 copy — the
difference between 770M and ~1.4B fitting on one 16GB v5e chip.

Quantization scheme (per group of ``group_size`` elements, one fp32 scale):
- ``m`` (first moment, signed): symmetric abs-max int8 in [-127, 127].
  Known limit (ADVICE r3 #5): linear coding flushes |m| < absmax/254 within a
  group each requant — outlier-heavy groups lose small-momentum signal.  The
  300-step convergence test (test_optimizers.py) bounds the practical impact;
  a bitsandbytes-style nonlinear code or smaller groups is the upgrade path
  if longer horizons drift.
- ``v`` (second moment, non-negative): stored in the **sqrt domain** —
  ``u = sqrt(v)`` quantized abs-max to [0, 127].  Linear int8 on raw ``v``
  zeroes everything below absmax/127 and the resulting 1/(sqrt(0)+eps) updates
  blow up; quantizing ``u`` squares the effective resolution near zero, which
  is where ``v`` lives for most params.

The whole step (dequant -> AdamW -> requant, p/m/v/scales updated in place via
input_output_aliases) is ONE Pallas grid sweep: one HBM read+write per buffer,
never materializing fp32 moments.  Off-TPU the identical math runs as plain
XLA for tests.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._pallas import use_pallas as _use_pallas
from .. import _pallas

GROUP = 1024  # elements per quantization group (one fp32 scale each)
_ROWS = 64  # groups per grid step: 64 x 1024 x ~10B live ~ 0.7MB VMEM


def init_quantized_moment(n: int, group_size: int = GROUP):
    """Zeroed int8 moment + unit scales for a flat buffer of ``n`` elements."""
    groups = int(np.ceil(n / group_size))
    return (jnp.zeros((groups, group_size), jnp.int8),
            jnp.ones((groups, 1), jnp.float32))


def _requant(x, qmax):
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(jnp.int8)
    return q, scale


def _adamw8_kernel(scal_ref, p_ref, m_ref, v_ref, sm_ref, sv_ref, g_ref,
                   po_ref, mo_ref, vo_ref, smo_ref, svo_ref):
    lr, beta1, beta2, eps, wd, bc1, bc2 = (scal_ref[0], scal_ref[1], scal_ref[2],
                                           scal_ref[3], scal_ref[4], scal_ref[5],
                                           scal_ref[6])
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:].astype(jnp.float32) * sm_ref[:]
    u = v_ref[:].astype(jnp.float32) * sv_ref[:]  # u = sqrt(v)
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * (u * u) + (1.0 - beta2) * g * g
    u_new = jnp.sqrt(v_new)
    denom = jnp.sqrt(v_new / bc2) + eps
    update = (m_new / bc1) / denom + wd * p_ref[:]
    po_ref[:] = p_ref[:] - lr * update
    mq, ms = _requant(m_new, 127.0)
    uq, us = _requant(u_new, 127.0)
    mo_ref[:] = mq
    smo_ref[:] = ms
    vo_ref[:] = uq
    svo_ref[:] = us


def fused_adamw8bit_flat(p, m8, v8, sm, sv, g, *, lr, beta1=0.9, beta2=0.999,
                         eps=1e-8, weight_decay=0.0, step=1,
                         group_size: int = GROUP, use_kernel: bool = True):
    """One AdamW step on a flat fp32 master ``p`` with int8 moments.

    ``m8``/``v8`` are (groups, group_size) int8, ``sm``/``sv`` (groups, 1)
    fp32 scales, ``g`` flat (len(p)) grad in any float dtype.  Returns
    (p_new, m8_new, v8_new, sm_new, sv_new).
    """
    n = p.shape[0]
    groups = m8.shape[0]
    n_pad = groups * group_size
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - jnp.power(jnp.asarray(beta1, jnp.float32), step)
    bc2 = 1.0 - jnp.power(jnp.asarray(beta2, jnp.float32), step)
    scal = jnp.stack([jnp.asarray(x, jnp.float32) for x in
                      (lr, beta1, beta2, eps, weight_decay)] + [bc1, bc2])
    pg = jnp.pad(p, (0, n_pad - n)).reshape(groups, group_size)
    gg = jnp.pad(g, (0, n_pad - n)).reshape(groups, group_size)

    if not use_kernel or not _use_pallas() or group_size % 128 != 0:
        gf = gg.astype(jnp.float32)
        m = m8.astype(jnp.float32) * sm
        u = v8.astype(jnp.float32) * sv
        m_new = beta1 * m + (1 - beta1) * gf
        v_new = beta2 * (u * u) + (1 - beta2) * gf * gf
        update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + weight_decay * pg
        p_new = (pg - scal[0] * update).reshape(n_pad)[:n]
        mq, ms = _requant(m_new, 127.0)
        uq, us = _requant(jnp.sqrt(v_new), 127.0)
        return p_new, mq, uq, ms, us

    rows = min(_ROWS, groups)
    g_pad = int(np.ceil(groups / rows)) * rows
    pad_rows = ((0, g_pad - groups), (0, 0))
    spec = pl.BlockSpec((rows, group_size), lambda i: (i, 0))
    sspec = pl.BlockSpec((rows, 1), lambda i: (i, 0))
    outs = pl.pallas_call(
        _adamw8_kernel,
        grid=(g_pad // rows, ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  spec, spec, spec, sspec, sspec, spec],
        out_specs=[spec, spec, spec, sspec, sspec],
        out_shape=[
            jax.ShapeDtypeStruct((g_pad, group_size), jnp.float32),
            jax.ShapeDtypeStruct((g_pad, group_size), jnp.int8),
            jax.ShapeDtypeStruct((g_pad, group_size), jnp.int8),
            jax.ShapeDtypeStruct((g_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((g_pad, 1), jnp.float32),
        ],
        input_output_aliases={1: 0, 2: 1, 3: 2, 4: 3, 5: 4},
        interpret=_pallas.INTERPRET,
    )(scal, jnp.pad(pg, pad_rows), jnp.pad(m8, pad_rows), jnp.pad(v8, pad_rows),
      jnp.pad(sm, pad_rows), jnp.pad(sv, pad_rows), jnp.pad(gg, pad_rows))
    p_new, mq, uq, ms, us = outs
    return (p_new[:groups].reshape(n_pad)[:n], mq[:groups], uq[:groups],
            ms[:groups], us[:groups])


def dequantize_moments(m8, v8, sm, sv, n: int):
    """Recover fp32 (m, v) flat buffers — for checkpoints/tests/offload."""
    m = (m8.astype(jnp.float32) * sm).reshape(-1)[:n]
    u = (v8.astype(jnp.float32) * sv).reshape(-1)[:n]
    return m, u * u
