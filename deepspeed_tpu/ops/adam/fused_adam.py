"""Pallas fused AdamW over flat parameter buffers.

TPU-native analog of the reference's multi-tensor-apply FusedAdam
(csrc/adam/multi_tensor_adam.cu, deepspeed/ops/adam/fused_adam.py): instead of
a multi-tensor CUDA launch, the optimizer state lives as ONE flat fp32 buffer
per (param/m/v) — the same flattening ZeRO does anyway — and a single grid
sweep updates p/m/v in place (input_output_aliases) with all elementwise math
fused in VMEM, one HBM read + write per buffer.

The engine uses this through ``fused_adamw_flat``; off-TPU the identical math
runs as plain XLA (which fuses it just as well on CPU — the kernel's win is
guaranteed aliasing + no small-op overhead on real chips).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .._pallas import use_pallas as _use_pallas
from .. import _pallas

_BLOCK = 1 << 16  # elements per grid step (fp32: 256KB/buffer in VMEM)


def _flat_kernel_call(kernel, scal, arrays, n_out):
    """Run an elementwise flat-buffer kernel over (rows, 128) tiles.

    The first ``n_out`` arrays alias their outputs in place.  Returns the
    updated buffers, un-padded back to the original length.
    """
    n = arrays[0].shape[0]
    rows = max(8, min(_BLOCK // 128, int(np.ceil(n / 128))))
    chunk = rows * 128
    n_pad = int(np.ceil(n / chunk)) * chunk
    as2d = lambda x: jnp.pad(x, (0, n_pad - n)).reshape(n_pad // 128, 128)
    spec = pl.BlockSpec((rows, 128), lambda i: (i, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(n_pad // chunk, ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] + [spec] * len(arrays),
        out_specs=[spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct((n_pad // 128, 128), jnp.float32)] * n_out,
        input_output_aliases={i + 1: i for i in range(n_out)},
        interpret=_pallas.INTERPRET,
    )(scal, *[as2d(a) for a in arrays])
    return tuple(o.reshape(n_pad)[:n] for o in outs)


def _adamw_kernel(scal_ref, p_ref, m_ref, v_ref, g_ref, po_ref, mo_ref, vo_ref):
    lr = scal_ref[0]
    beta1, beta2, eps, wd, bc1, bc2 = (scal_ref[1], scal_ref[2], scal_ref[3],
                                       scal_ref[4], scal_ref[5], scal_ref[6])
    g = g_ref[:].astype(jnp.float32)
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    m_hat = m / bc1
    v_hat = v / bc2
    update = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p_ref[:]
    po_ref[:] = p_ref[:] - lr * update
    mo_ref[:] = m
    vo_ref[:] = v


def fused_adamw_flat(p, m, v, g, *, lr, beta1=0.9, beta2=0.999, eps=1e-8,
                     weight_decay=0.0, step=1):
    """One AdamW step on flat fp32 buffers p/m/v with (possibly bf16) grad g.

    Returns (p_new, m_new, v_new).  ``step`` is 1-based; bias correction is
    computed host-side when static, traced otherwise.
    """
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - jnp.power(jnp.asarray(beta1, jnp.float32), step)
    bc2 = 1.0 - jnp.power(jnp.asarray(beta2, jnp.float32), step)
    scal = jnp.stack([jnp.asarray(x, jnp.float32) for x in
                      (lr, beta1, beta2, eps, weight_decay)] + [bc1, bc2])
    if not _use_pallas():
        gf = g.astype(jnp.float32)
        m_new = beta1 * m + (1 - beta1) * gf
        v_new = beta2 * v + (1 - beta2) * gf * gf
        update = (m_new / bc1) / (jnp.sqrt(v_new / bc2) + eps) + weight_decay * p
        return p - scal[0] * update, m_new, v_new

    return _flat_kernel_call(_adamw_kernel, scal, (p, m, v, g), n_out=3)


def _lion_kernel(scal_ref, p_ref, m_ref, g_ref, po_ref, mo_ref):
    lr, beta1, beta2, wd = scal_ref[0], scal_ref[1], scal_ref[2], scal_ref[3]
    g = g_ref[:].astype(jnp.float32)
    c = beta1 * m_ref[:] + (1.0 - beta1) * g
    po_ref[:] = p_ref[:] - lr * (jnp.sign(c) + wd * p_ref[:])
    mo_ref[:] = beta2 * m_ref[:] + (1.0 - beta2) * g


def fused_lion_flat(p, m, g, *, lr, beta1=0.9, beta2=0.99, weight_decay=0.0):
    """Lion step on flat buffers (reference csrc/lion/ analog)."""
    scal = jnp.stack([jnp.asarray(x, jnp.float32) for x in (lr, beta1, beta2, weight_decay)])
    if not _use_pallas():
        gf = g.astype(jnp.float32)
        c = beta1 * m + (1 - beta1) * gf
        return p - scal[0] * (jnp.sign(c) + weight_decay * p), beta2 * m + (1 - beta2) * gf
    return _flat_kernel_call(_lion_kernel, scal, (p, m, g), n_out=2)
