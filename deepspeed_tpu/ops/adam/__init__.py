"""Fused optimizer kernels (reference csrc/adam multi_tensor_adam analog)."""
from .fused_adam import fused_adamw_flat, fused_lion_flat
from .cpu_adam import DeepSpeedCPUAdam
