"""Host-resident AdamW (reference DeepSpeedCPUAdam, deepspeed/ops/adam/cpu_adam.py).

Steps fp32 master params + moments in host RAM via the OpenMP/SIMD C++ kernel
(csrc/cpu_adam/cpu_adam.cpp); numpy fallback keeps identical math when no
compiler is present.  Used by the optimizer-offload path (runtime/swap_tensor).
"""

from typing import Optional

import numpy as np

from ...utils.logging import logger
from ..op_builder import CPUAdamBuilder


class DeepSpeedCPUAdam:

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.step_count = 0
        self._lib = None
        try:
            self._lib = CPUAdamBuilder().load()
        except Exception as exc:
            logger.warning(f"native cpu_adam unavailable ({exc}); using numpy fallback")

    def step(self, p: np.ndarray, m: np.ndarray, v: np.ndarray, g: np.ndarray,
             lr: Optional[float] = None, step: Optional[int] = None) -> None:
        """In-place AdamW on flat fp32 host buffers."""
        lr = self.lr if lr is None else float(lr)
        if step is None:
            self.step_count += 1
            step = self.step_count
        assert p.dtype == np.float32 and p.flags["C_CONTIGUOUS"]
        g32 = np.ascontiguousarray(g, dtype=np.float32)
        if self._lib is not None:
            import ctypes
            f32p = ctypes.POINTER(ctypes.c_float)
            self._lib.dstpu_adamw_step(p.ctypes.data_as(f32p), m.ctypes.data_as(f32p),
                                       v.ctypes.data_as(f32p), g32.ctypes.data_as(f32p),
                                       p.size, lr, self.beta1, self.beta2, self.eps,
                                       self.weight_decay, step)
            return
        # numpy fallback — identical math
        np.multiply(m, self.beta1, out=m)
        m += (1 - self.beta1) * g32
        np.multiply(v, self.beta2, out=v)
        v += (1 - self.beta2) * g32 * g32
        bc1 = 1 - self.beta1**step
        bc2 = 1 - self.beta2**step
        update = (m / bc1) / (np.sqrt(v / bc2) + self.eps) + self.weight_decay * p
        p -= lr * update
