"""Native op builder — JIT-compiles C++ host libraries and binds them via ctypes.

Analog of the reference's OpBuilder system (op_builder/builder.py:108): each op
declares sources + flags, is compiled on first use into a cached shared object,
and exposes ``load()`` returning the binding.  CUDA/nvcc machinery is replaced
by plain g++ building HOST-side libraries (async file I/O, CPU optimizers) —
on TPU the device compute path is XLA/Pallas, so native code serves the
host runtime exactly where the reference uses csrc/aio + csrc/adam/cpu_adam.
"""

import ctypes
import hashlib
import os
import shutil
import subprocess
import sysconfig
from typing import List, Optional

from ..utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CSRC_DIR = os.path.join(_REPO_ROOT, "csrc")
DEFAULT_BUILD_DIR = os.environ.get("DSTPU_BUILD_DIR",
                                   os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"))


class OpBuilder:
    name: str = "base"
    sources: List[str] = []
    extra_cxx_flags: List[str] = []
    extra_ld_flags: List[str] = []

    def __init__(self):
        self._lib: Optional[ctypes.CDLL] = None

    # -- compatibility probing (reference builder.is_compatible) -------------
    def compiler(self) -> Optional[str]:
        for cc in ("g++", "c++", "clang++"):
            if shutil.which(cc):
                return cc
        return None

    def is_compatible(self) -> bool:
        return self.compiler() is not None

    def abs_sources(self) -> List[str]:
        return [os.path.join(CSRC_DIR, s) for s in self.sources]

    def _signature(self) -> str:
        h = hashlib.sha256()
        for src in self.abs_sources():
            with open(src, "rb") as fh:
                h.update(fh.read())
        h.update(" ".join(self.extra_cxx_flags + self.extra_ld_flags).encode())
        return h.hexdigest()[:16]

    def lib_path(self) -> str:
        return os.path.join(DEFAULT_BUILD_DIR, f"{self.name}-{self._signature()}.so")

    def build(self) -> str:
        """Compile the shared object if the cached build is stale."""
        out = self.lib_path()
        if os.path.exists(out):
            return out
        cc = self.compiler()
        if cc is None:
            raise RuntimeError(f"no C++ compiler found for op '{self.name}'")
        os.makedirs(DEFAULT_BUILD_DIR, exist_ok=True)
        tmp = f"{out}.{os.getpid()}.tmp"  # unique per process; os.replace is atomic
        cmd = [cc, "-O3", "-shared", "-fPIC", "-std=c++17", "-march=native", "-fopenmp",
               *self.extra_cxx_flags, *self.abs_sources(), "-o", tmp,
               "-lpthread", *self.extra_ld_flags]
        logger.info(f"building native op '{self.name}': {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as exc:
            raise RuntimeError(f"native build of '{self.name}' failed:\n{exc.stderr}") from exc
        os.replace(tmp, out)
        return out

    def load(self) -> ctypes.CDLL:
        if self._lib is None:
            self._lib = ctypes.CDLL(self.build())
            self._bind(self._lib)
        return self._lib

    def _bind(self, lib: ctypes.CDLL) -> None:
        """Subclasses declare argtypes/restypes here."""


class AsyncIOBuilder(OpBuilder):
    """Reference csrc/aio (deepspeed_aio_thread.cpp) analog: threaded
    pread/pwrite file I/O for NVMe offload."""
    name = "dstpu_aio"
    sources = ["aio/aio.cpp"]

    def _bind(self, lib):
        lib.dstpu_aio_open.restype = ctypes.c_void_p
        lib.dstpu_aio_open.argtypes = [ctypes.c_int]
        lib.dstpu_aio_open_ex.restype = ctypes.c_void_p
        lib.dstpu_aio_open_ex.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.dstpu_aio_close.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_pwrite.restype = ctypes.c_int
        lib.dstpu_aio_pwrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                                         ctypes.c_size_t]
        lib.dstpu_aio_pread.restype = ctypes.c_int
        lib.dstpu_aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                                        ctypes.c_size_t]
        lib.dstpu_aio_wait.restype = ctypes.c_longlong
        lib.dstpu_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dstpu_aio_wait_all.restype = ctypes.c_int
        lib.dstpu_aio_wait_all.argtypes = [ctypes.c_void_p]


class CPUAdamBuilder(OpBuilder):
    """Reference csrc/adam/cpu_adam.cpp analog: OpenMP/SIMD AdamW stepping
    host-resident fp32 buffers (offloaded optimizer states)."""
    name = "dstpu_cpu_adam"
    sources = ["cpu_adam/cpu_adam.cpp"]

    def _bind(self, lib):
        f32p = ctypes.POINTER(ctypes.c_float)
        lib.dstpu_adamw_step.argtypes = [f32p, f32p, f32p, f32p, ctypes.c_size_t,
                                         ctypes.c_float, ctypes.c_float, ctypes.c_float,
                                         ctypes.c_float, ctypes.c_float, ctypes.c_int]
