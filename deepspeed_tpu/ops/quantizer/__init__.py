"""Block quantization kernels (reference csrc/quantization analog)."""
from .quantize import (dequantize_int4, dequantize_int8, quantize_int4, quantize_int8,
                       quantized_allgather_int8, quantized_psum_scatter_int4)
