"""Block quantization kernels — int8/int4 symmetric, per-group scales.

TPU-native analog of the reference's quantizer tree
(csrc/quantization/{quantize.cu,dequantize.cu,quant_reduce.cu}): used by the
ZeRO++-style quantized collectives (qwZ int8 weight allgather, qgZ
hierarchical int4 gradient reduction in runtime/zero/quantized_collectives).

Layout: a flat buffer is viewed as [num_groups, group_size]; each group gets a
symmetric abs-max fp32 scale.  int4 packs two nibbles per int8 lane.  On TPU
the quantize step runs as a Pallas kernel (one pass: abs-max + scale + cast);
off-TPU the identical math runs as XLA ops (tests compare both).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import axis_size
from .._pallas import use_pallas as _use_pallas
from .. import _pallas


def _quant_kernel(x_ref, q_ref, s_ref, *, qmax):
    x = x_ref[:].astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = scale


def _view_groups(x, group_size):
    n = x.size
    g = min(group_size, n)
    n_pad = int(np.ceil(n / g)) * g
    return jnp.pad(x.reshape(-1), (0, n_pad - n)).reshape(n_pad // g, g), n


def quantize_int8(x, group_size: int = 2048):
    """x: any shape -> (q int8 [G, gs], scales fp32 [G, 1], orig_size)."""
    xg, n = _view_groups(x, group_size)
    if not _use_pallas() or xg.shape[1] % 128 != 0:
        xf = xg.astype(jnp.float32)
        absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
        scale = jnp.where(absmax == 0.0, 1.0, absmax / 127.0)
        q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
        return q, scale, n
    groups, gs = xg.shape
    rows = max(8, min(512, groups))
    g_pad = int(np.ceil(groups / rows)) * rows
    xg = jnp.pad(xg, ((0, g_pad - groups), (0, 0)))
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=127.0),
        grid=(g_pad // rows, ),
        in_specs=[pl.BlockSpec((rows, gs), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((rows, gs), lambda i: (i, 0)),
            pl.BlockSpec((rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g_pad, gs), jnp.int8),
            jax.ShapeDtypeStruct((g_pad, 1), jnp.float32),
        ],
        interpret=_pallas.INTERPRET,
    )(xg)
    return q[:groups], s[:groups], n


def dequantize_int8(q, scales, orig_size, shape=None, dtype=jnp.float32):
    x = (q.astype(jnp.float32) * scales).reshape(-1)[:orig_size].astype(dtype)
    return x.reshape(shape) if shape is not None else x


def quantize_int4(x, group_size: int = 2048):
    """Symmetric int4 ([-7, 7]) with two values packed per int8.

    Returns (packed int8 [G, gs//2], scales [G, 1], orig_size).
    """
    if x.size < group_size and x.size % 2 == 1:
        group_size = x.size + 1  # keep the group width even for nibble pairing
    xg, n = _view_groups(x, group_size)
    if xg.shape[1] % 2 == 1:
        xg = jnp.pad(xg, ((0, 0), (0, 1)))
    xf = xg.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / 7.0)
    q = jnp.clip(jnp.round(xf / scale), -7, 7).astype(jnp.int8)
    lo, hi = q[:, 0::2], q[:, 1::2]
    packed = ((hi.astype(jnp.int32) & 0xF) << 4 | (lo.astype(jnp.int32) & 0xF)).astype(jnp.int8)
    return packed, scale, n


def dequantize_int4(packed, scales, orig_size, shape=None, dtype=jnp.float32):
    p = packed.astype(jnp.int32)
    lo = (p << 28) >> 28  # sign-extend low nibble
    hi = (p << 24) >> 28  # sign-extend high nibble
    g, half = packed.shape
    q = jnp.stack([lo, hi], axis=-1).reshape(g, half * 2).astype(jnp.float32)
    x = (q * scales).reshape(-1)[:orig_size].astype(dtype)
    return x.reshape(shape) if shape is not None else x


def quantized_allgather_int8(x, axis_name: str, group_size: int = 2048):
    """qwZ-style collective: quantize locally, allgather int8 + scales, dequant.

    4x wire traffic reduction vs fp32 allgather (reference
    partition_parameters.py:1171 zero_quantized_weights path).  Must run inside
    shard_map/pjit with ``axis_name`` bound.
    """
    q, s, n = quantize_int8(x, group_size)
    q_all = jax.lax.all_gather(q, axis_name)
    s_all = jax.lax.all_gather(s, axis_name)
    world = q_all.shape[0]
    deq = jax.vmap(lambda qq, ss: dequantize_int8(qq, ss, n, dtype=x.dtype))(q_all, s_all)
    return deq.reshape((world, ) + x.shape)


def quantized_psum_scatter_int4(x, axis_name: str, group_size: int = 2048):
    """qgZ-style gradient reduction: int4 all-to-all then local reduce.

    Maps the reference's swizzled-quantization hierarchical qgZ
    (csrc/quantization/swizzled_quantize.cu, coalesced_collectives.py:31) to a
    single-axis quantized reduce-scatter: each rank quantizes its shard-slices,
    all-to-alls the int4 payload, dequantizes, and reduces locally.  x: [n]
    with n divisible by axis size * 2.
    """
    world = axis_size(axis_name)
    shard = x.shape[0] // world
    xs = x.reshape(world, shard)
    packed, scales, n_per = _quant_a2a_prep(xs, group_size)
    packed_t = jax.lax.all_to_all(packed, axis_name, split_axis=0, concat_axis=0)
    scales_t = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0)
    deq = jax.vmap(lambda qq, ss: dequantize_int4(qq, ss, n_per))(packed_t, scales_t)
    return jnp.sum(deq, axis=0).astype(x.dtype)


def _quant_a2a_prep(xs, group_size):
    def one(row):
        packed, scales, _ = quantize_int4(row, group_size)
        return packed, scales
    packed, scales = jax.vmap(one)(xs)
    return packed, scales, xs.shape[1]
