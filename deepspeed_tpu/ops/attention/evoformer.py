"""Evoformer attention (DeepSpeed4Science analog).

Parity: the reference ships a 14.9k-LoC CUTLASS tree
(csrc/deepspeed4science/evoformer_attn/) exposing
``DS4Sci_EvoformerAttention(Q, K, V, [bias1, bias2])`` — fused
attention-with-pair-bias for AlphaFold-style triangle/MSA attention, built
because eager PyTorch materializes the [*, H, S, S] logits for every bias
add.  Under XLA the same fusion falls out of ``jit`` + a remat policy: the
logits tensor exists only inside the fused kernel schedule, so the TPU-native
implementation is the straightforward einsum math wrapped in
``jax.checkpoint`` (recompute-over-store, the memory behavior the CUTLASS
kernel hand-codes).

Shapes follow the reference binding: Q/K/V ``[*, S_q, H, D]`` with arbitrary
leading batch dims (MSA rows, residue pairs); ``biases`` broadcastable to
``[*, H, S_q, S_k]`` — canonically bias1 = mask ``[*, 1, 1, S_k]`` (-inf
style) and bias2 = pair bias ``[*, H, S_q, S_k]``.
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _evoformer_core(q, k, v, biases):
    scale = 1.0 / np.sqrt(q.shape[-1])
    logits = jnp.einsum("...qhd,...khd->...hqk", q, k).astype(jnp.float32) * scale
    for b in biases:
        logits = logits + b.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, v)


def evoformer_attention(q, k, v, biases: Optional[Sequence] = None,
                        remat: bool = True):
    """``DS4Sci_EvoformerAttention`` analog: attention with additive biases.

    q/k/v: [*, S, H, D]; biases: list of arrays broadcastable to
    [*, H, S_q, S_k] (mask bias + pair bias).  ``remat`` recomputes the
    logits in the backward pass instead of storing them — the memory contract
    of the reference kernel.
    """
    biases = tuple(biases or ())
    if len(biases) > 2:
        raise ValueError("evoformer attention takes at most [mask_bias, pair_bias]")
    fn = jax.checkpoint(_evoformer_core) if remat else _evoformer_core
    return fn(q, k, v, biases)


def msa_row_attention_with_pair_bias(msa, pair_bias, params, num_heads: int):
    """One MSA-row gated self-attention block (the op's canonical consumer,
    reference evoformer_attn usage in DS4Science examples): projections +
    evoformer_attention + sigmoid gating.

    msa: [rows, S, C]; pair_bias: [H, S, S] (from the pair representation);
    params: {wq, wk, wv, wg, wo} each [C, H*Dh] / [H*Dh, C].
    """
    rows, s, c = msa.shape
    dh = params["wq"].shape[1] // num_heads

    def proj(w):
        return (msa @ w.astype(msa.dtype)).reshape(rows, s, num_heads, dh)

    q, k, v = proj(params["wq"]), proj(params["wk"]), proj(params["wv"])
    out = evoformer_attention(q, k, v, biases=[pair_bias[None]])
    gate = jax.nn.sigmoid(msa @ params["wg"].astype(msa.dtype))
    out = out.reshape(rows, s, num_heads * dh) * gate
    return out @ params["wo"].astype(msa.dtype)
