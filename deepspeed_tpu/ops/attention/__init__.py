"""Attention kernels (Pallas flash attention; reference csrc/transformer analog)."""
from .flash import flash_attention
