"""Pallas TPU paged (blocked-KV) attention for ragged serving.

Replaces the dense block-table gather the v2 engine shipped with (the analog of
the reference's blocked flash kernels, inference/v2/kernels/ragged_ops/
blocked_flash + linear_blocked_kv_rotary): instead of gathering every
sequence's whole block table into a dense [N, MAXB*bs, KV, Dh] context (HBM
traffic O(MAXB) regardless of actual length), the kernel walks each sequence's
block table with **scalar-prefetched indices** — the block index feeds the KV
BlockSpec index_map, so only blocks below the sequence's live length are ever
read, with online-softmax accumulation across blocks.

Layout: q [N, T, H, Dh] (T = SplitFuse chunk, 1 at decode); KV pool
[NB, KV, bs, Dh] (one layer's pool — heads-major so the (bs, Dh) tile is the
trailing pair, as the TPU lowering requires); tables [N, MAXB] int32 (padded
entries may point anywhere — never read past ``lengths``); lengths [N] = live
context per sequence (including this chunk); start_pos/n_tokens [N] describe
the chunk's absolute query positions.  Causality is absolute-position based so
chunked prefill and decode share one kernel.

GQA maps q-head -> kv-head in the index_map.  Off-TPU falls back to the dense
gather + masked sdpa (identical math; tests compare the two).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams
from .. import _pallas
from .._pallas import use_pallas as _use_pallas

NEG_INF = -1e30


def _paged_kernel(tables_ref, lengths_ref, start_ref, ntok_ref, *rest,
                  scale, block_size, t_pad, window, alibi):
    if alibi:
        slopes_ref, q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc = rest
    else:
        q_ref, k_ref, v_ref, o_ref, acc, m_sc, l_sc = rest
    n, h, b = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    nb = pl.num_programs(2)

    @pl.when(b == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    length = lengths_ref[n]

    @pl.when(b * block_size < length)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [T, Dh]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, Dh]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [T, bs]
        kpos = b * block_size + jax.lax.broadcasted_iota(jnp.int32, (t_pad, block_size), 1)
        t_iota = jax.lax.broadcasted_iota(jnp.int32, (t_pad, block_size), 0)
        qp = start_ref[n] + t_iota  # absolute query positions
        if alibi:
            # ALiBi key-only form: slope_h * absolute key index (softmax-
            # equivalent to the relative-distance form per query row —
            # models/bloom.py docstring; HF build_alibi_tensor)
            s = s + slopes_ref[h] * kpos.astype(jnp.float32)
        mask = (kpos <= qp) & (kpos < length) & (t_iota < ntok_ref[n])
        if window is not None:
            mask = jnp.logical_and(mask, kpos > qp - window)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, 0:1] = l_sc[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:, 0:1] = m_new
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(b == nb - 1)
    def _finalize():
        l = l_sc[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)


def paged_attention(q, kpool, vpool, tables, lengths, start_pos, n_tokens, *,
                    block_size: int, softmax_scale: Optional[float] = None,
                    window: Optional[int] = None, alibi_slopes=None):
    """q [N, T, H, Dh]; kpool/vpool [NB, KV, bs, Dh]; tables [N, MAXB] int32;
    lengths/start_pos/n_tokens [N] int32.  Returns [N, T, H, Dh] (rows at
    t >= n_tokens[n] are zero).  ``window`` = sliding-window size (Mistral);
    ``alibi_slopes`` [H] f32 adds slope_h * key_index to the scores (BLOOM —
    reference serves ALiBi through its softmax op's alibi path,
    ops/transformer/inference/op_binding/softmax.py)."""
    n, t, hq, dh = q.shape
    kvh, bs = kpool.shape[1], kpool.shape[2]
    maxb = tables.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(dh))
    if not _use_pallas():
        return _dense_fallback(q, kpool, vpool, tables, lengths, start_pos, n_tokens,
                               scale, window, alibi_slopes)

    group = hq // kvh
    t_pad = max(8, int(np.ceil(t / 8)) * 8)
    qt = jnp.pad(q.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, t_pad - t), (0, 0)))

    alibi = alibi_slopes is not None
    kernel = functools.partial(_paged_kernel, scale=scale, block_size=bs,
                               t_pad=t_pad, window=window, alibi=alibi)
    nsp = 5 if alibi else 4
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=nsp,
        grid=(n, hq, maxb),
        in_specs=[
            pl.BlockSpec((1, 1, t_pad, dh), lambda ni, h, b, *refs: (ni, h, 0, 0)),
            pl.BlockSpec((1, 1, bs, dh),
                         lambda ni, h, b, tables, *refs: (tables[ni, b], h // group, 0, 0)),
            pl.BlockSpec((1, 1, bs, dh),
                         lambda ni, h, b, tables, *refs: (tables[ni, b], h // group, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, t_pad, dh), lambda ni, h, b, *refs: (ni, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t_pad, dh), jnp.float32),
            pltpu.VMEM((t_pad, 128), jnp.float32),
            pltpu.VMEM((t_pad, 128), jnp.float32),
        ],
    )
    scalars = [tables.astype(jnp.int32), lengths.astype(jnp.int32),
               start_pos.astype(jnp.int32), n_tokens.astype(jnp.int32)]
    if alibi:
        scalars.append(jnp.asarray(alibi_slopes, jnp.float32))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n, hq, t_pad, dh), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(*scalars, qt, kpool, vpool)
    return out[:, :, :t].transpose(0, 2, 1, 3)


def _dense_fallback(q, kpool, vpool, tables, lengths, start_pos, n_tokens, scale,
                    window, alibi_slopes=None):
    """Reference-math path: gather the whole table, masked sdpa (the v2
    engine's original implementation — kept as the CPU/parity baseline)."""
    from ...models.transformer import sdpa
    n, t, hq, dh = q.shape
    maxb = tables.shape[1]
    kvh, bs = kpool.shape[1], kpool.shape[2]
    ctx_k = kpool[tables].transpose(0, 1, 3, 2, 4).reshape(n, maxb * bs, kvh, dh)
    ctx_v = vpool[tables].transpose(0, 1, 3, 2, 4).reshape(n, maxb * bs, kvh, dh)
    positions = start_pos[:, None] + jnp.arange(t)[None, :]
    qpos = jnp.where(jnp.arange(t)[None, :] < n_tokens[:, None], positions, -1)
    kpos = jnp.arange(maxb * bs)[None, None, :]
    qp = qpos[:, :, None]
    mask = (kpos <= qp) & (kpos < lengths[:, None, None]) & (qp >= 0)
    if window is not None:
        mask = jnp.logical_and(mask, kpos > qp - window)
    bias = None
    if alibi_slopes is not None:
        bias = (jnp.asarray(alibi_slopes, jnp.float32)[None, :, None, None]
                * jnp.arange(maxb * bs, dtype=jnp.float32)[None, None, None, :])
    out = sdpa(q, ctx_k, ctx_v, causal=False, mask=mask[:, None, :, :],
               softmax_scale=scale, bias=bias)
    return jnp.where((qp >= 0)[..., None], out, 0.0)
