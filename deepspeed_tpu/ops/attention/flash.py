"""Pallas TPU flash attention (forward + custom-VJP backward).

TPU-native replacement for the reference's fused attention kernels
(csrc/transformer/ds_attention.cu and the blocked-flash wrappers in
deepspeed/inference/v2/kernels/ragged_ops/): an online-softmax blocked kernel
that never materialises the [S, S] score matrix, keeping HBM traffic at
O(S * D) and feeding the MXU [block_q, d] x [d, block_k] tiles.

Layout: q/k/v are [B, S, H, D] (model layout); the kernel grid is
(batch, q_head, q_block, k_block) with the k_block axis innermost so the fp32
accumulators in VMEM scratch carry across k steps.  GQA maps q-head -> kv-head
in the k/v index_map (no jnp.repeat materialisation).  Backward recomputes
scores from the saved logsumexp (flash-attention-2 style): one kernel
accumulates dk/dv over q blocks, one accumulates dq over k blocks.

Falls back to the XLA soft(max) path off-TPU unless interpret mode is forced
(tests run interpret=True on CPU).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams
from .. import _pallas
from .._pallas import use_pallas as _use_pallas

NEG_INF = -1e30


# --------------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_sc, l_sc, *, scale,
                causal, block_q, block_k, kv_len, offset):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    q_start = iq * block_q
    k_start = ik * block_k
    # whole k block above the causal diagonal -> skip compute entirely
    should_run = jnp.logical_or(not causal, k_start <= q_start + offset + block_q - 1)

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kpos < kv_len  # padded keys
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, kpos <= qpos + offset)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:, 0:1]  # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [bq, bk]
        corr = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_sc[:, 0:1] = l_sc[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:, 0:1] = m_new
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_sc[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)
        # lane-replicated [bq, 128] (TPU block constraint: last dim 128)
        lse_ref[0, 0] = jnp.broadcast_to(m_sc[:, 0:1] + jnp.log(l_safe), (block_q, 128))


def _flash_fwd(q, k, v, scale, causal, block_q, block_k):
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    # clamp to the 8-ALIGNED sequence length: a raw-S block (e.g. 900) has a
    # non-sublane-multiple second-minor dim that Mosaic may reject
    block_q = min(block_q, max(8, int(np.ceil(sq / 8)) * 8))
    block_k = min(block_k, max(8, int(np.ceil(sk / 8)) * 8))
    sq_p = int(np.ceil(sq / block_q)) * block_q
    sk_p = int(np.ceil(sk / block_k)) * block_k
    qt = jnp.pad(q.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    kt = jnp.pad(k.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    grid = (b, hq, sq_p // block_q, sk_p // block_k)
    group = hq // hk

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=sk,
                               offset=sk - sq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, iq, ik: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, iq, ik: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, iq, ik: (bi, h // group, ik, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, iq, ik: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, h, iq, ik: (bi, h, iq, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(qt, kt, vt)
    return out[:, :, :sq].transpose(0, 2, 1, 3), lse[:, :, :sq, 0]


# -------------------------------------------------------------------- backward
def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
                     dv_ref, dk_acc, dv_acc, *, scale, causal, block_q, block_k, kv_len,
                     offset):
    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start, k_start = iq * block_q, ik * block_k
    should_run = jnp.logical_or(not causal, k_start <= q_start + offset + block_q - 1)

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]  # [bq, 1] (lane-replicated input)
        delta = delta_ref[0, 0, :, 0:1]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kpos < kv_len
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, kpos <= qpos + offset)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)  # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(p, do, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc, *, scale, causal, block_q, block_k, kv_len, offset):
    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start, k_start = iq * block_q, ik * block_k
    should_run = jnp.logical_or(not causal, k_start <= q_start + offset + block_q - 1)

    @pl.when(should_run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]
        delta = delta_ref[0, 0, :, 0:1]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = kpos < kv_len
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            mask = jnp.logical_and(mask, kpos <= qpos + offset)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(ds, k, (((1, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_bwd(scale, causal, block_q, block_k, res, g, g_lse=None):
    """``g_lse`` [B,H,Sq]: cotangent on the logsumexp output (flash_with_lse).
    It folds into the existing delta term: dL/ds_ij gains g_lse_i * p_ij, and
    since ds = p * (dp - delta) * scale, passing delta' = delta - g_lse
    computes the lse contribution with ZERO extra kernel work."""
    q, k, v, out, lse = res
    do = g
    b, sq, hq, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    group = hq // hk
    block_q = min(block_q, max(8, int(np.ceil(sq / 8)) * 8))  # 8-aligned clamp
    block_k = min(block_k, max(8, int(np.ceil(sk / 8)) * 8))
    sq_p = int(np.ceil(sq / block_q)) * block_q
    sk_p = int(np.ceil(sk / block_k)) * block_k

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)  # [B,S,H]
    delta = delta.transpose(0, 2, 1)  # [B,H,S]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)

    def padq(x):  # [B,S,H,D] -> [B,H,Sp,D]
        return jnp.pad(x.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sq_p - x.shape[1]), (0, 0)))

    def padk(x):
        return jnp.pad(x.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sk_p - x.shape[1]), (0, 0)))

    qt, kt, vt, dot = padq(q), padk(k), padk(v), padq(do)
    lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, sq_p - sq)))
    delta_p = jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq)))
    lse_p = jnp.broadcast_to(lse_p[..., None], lse_p.shape + (128, ))
    delta_p = jnp.broadcast_to(delta_p[..., None], delta_p.shape + (128, ))
    nq, nk = sq_p // block_q, sk_p // block_k

    # dk/dv: one pass per q-head (GQA heads accumulate via XLA add after)
    kern = functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                             block_q=block_q, block_k=block_k, kv_len=sk, offset=sk - sq)
    dk_h, dv_h = pl.pallas_call(
        kern,
        grid=(b, hq, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, ik, iq: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, ik, iq: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, ik, iq: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, ik, iq: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, h, ik, iq: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, h, ik, iq: (bi, h, iq, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, ik, iq: (bi, h, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, ik, iq: (bi, h, ik, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sk_p, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sk_p, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(qt, kt, vt, dot, lse_p, delta_p)
    # fold grouped q-heads into their kv head
    dk = dk_h.reshape(b, hk, group, sk_p, d).sum(axis=2)
    dv = dv_h.reshape(b, hk, group, sk_p, d).sum(axis=2)

    kern_q = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, kv_len=sk, offset=sk - sq)
    dq = pl.pallas_call(
        kern_q,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, iq, ik: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, iq, ik: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, h, iq, ik: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda bi, h, iq, ik: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, h, iq, ik: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block_q, 128), lambda bi, h, iq, ik: (bi, h, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, h, iq, ik: (bi, h, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(qt, kt, vt, dot, lse_p, delta_p)

    dq = dq[:, :, :sq].transpose(0, 2, 1, 3)
    dk = dk[:, :, :sk].transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv[:, :, :sk].transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


# ------------------------------------------------------------------ public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, res, g):
    return _flash_bwd(scale, causal, block_q, block_k, res, g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, scale, causal, block_q, block_k):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k)


def _flash_lse_vjp_fwd(q, k, v, scale, causal, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(scale, causal, block_q, block_k, res, g):
    g_out, g_lse = g
    return _flash_bwd(scale, causal, block_q, block_k, res, g_out, g_lse=g_lse)


_flash_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def flash_attention_with_lse(q, k, v, causal: bool = True,
                             softmax_scale: Optional[float] = None,
                             block_q: int = 1024, block_k: int = 1024):
    """Flash attention returning (out [B,Sq,H,D], lse [B,H,Sq]) — the form a
    blockwise/ring outer loop needs to merge per-block results (VERDICT r4 #3:
    'expose logsumexp and let the ring dispatch to it').  Differentiable in
    BOTH outputs: the lse cotangent folds into the backward kernels' delta
    term, so ring gradients cost the same as plain flash gradients.  Supports
    sq != sk with the same absolute-position causal offset as the main kernel
    (queries sit at the END of the key sequence — exactly the zigzag ring's
    high-chunk diagonal step).  Off-TPU falls back to a dense XLA path (same
    fallback contract as flash_attention)."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    if not _use_pallas():
        hq, hk = q.shape[2], k.shape[2]
        kk = jnp.repeat(k, hq // hk, axis=2) if hq != hk else k
        vv = jnp.repeat(v, hq // hk, axis=2) if hq != hk else v
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kk).astype(jnp.float32) * scale
        if causal:
            sq, sk = q.shape[1], k.shape[1]
            qpos = jnp.arange(sq)[:, None] + (sk - sq)
            s = jnp.where((jnp.arange(sk)[None, :] <= qpos)[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), vv).astype(q.dtype)
        return out, lse
    return _flash_lse(q, k, v, scale, causal, block_q, block_k)


def flash_attention(q, k, v, causal: bool = True, mask=None,
                    softmax_scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024):
    # default 1024x1024 blocks: r5 sweep at the training shape (6 x 2048 x
    # 18h GQA, d=128) measured fwd 10.7 vs 11.6 ms and fwd+bwd 22.8 vs 25.4
    # ms against 512x512 — ~10%; 2048 blocks exceed VMEM.  Shorter sequences
    # clamp the block to the 8-aligned sequence length.
    """Drop-in for models.transformer.sdpa: q/k/v [B, S, H, D], GQA allowed.

    Dense ``mask`` forces the XLA fallback (the blocked kernel handles only the
    causal/padding structure); off-TPU also falls back unless interpret mode.
    """
    from ...models.transformer import sdpa
    if mask is not None or not _use_pallas():
        return sdpa(q, k, v, causal=causal, mask=mask, softmax_scale=softmax_scale)
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    return _flash(q, k, v, scale, causal, block_q, block_k)
