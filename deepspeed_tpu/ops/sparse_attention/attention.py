"""Fused Pallas block-sparse attention (fwd + custom-VJP bwd).

TPU-native replacement for the reference's Triton blocksparse stack
(deepspeed/ops/sparse_attention/matmul.py SDD/DSD + softmax.py +
sparse_self_attention.py:99 SparseSelfAttention.forward): instead of three
kernels materialising a block-sparse score tensor, one online-softmax kernel
per direction walks a **compacted active-block table** built host-side from
the layout — for every (head, q-block) row only the live kv blocks appear in
the scalar-prefetch table, so dead blocks cost neither FLOPs nor HBM reads
(the splash-attention recipe).

Layout convention matches sparsity_config.py: uint8 [H or 1, NB, NB].
Element-level masking inside live blocks (causal diagonal, key padding) is
applied in-kernel, matching the reference softmax's attn_mask stage
(sparse_self_attention.py:139-146).  Only self-attention (sq == sk) is
supported, as in the reference (sparse_self_attention.py:121).

Off-TPU (and whenever a dense mask is supplied) falls back to XLA sdpa with
the layout expanded to an element mask — the parity baseline for tests.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...compat import CompilerParams
from .. import _pallas
from .._pallas import use_pallas as _use_pallas

NEG_INF = -1e30


# ----------------------------------------------------------------- host tables
class _Tables:
    """Compacted active-block tables for one (layout, block, n_heads) triple.

    kvmap [H, NQ, A]  : for q-block iq, the a-th live kv block index
    cnt   [H, NQ]     : how many of the A slots are live
    qmap  [H, NK, At] : transpose — for kv-block ik, the live q blocks
    cnt_t [H, NK]
    """

    def __init__(self, layout: np.ndarray, n_heads: int):
        lh, nq, nk = layout.shape
        layout = np.broadcast_to(layout, (n_heads, nq, nk)) if lh != n_heads else layout
        cnt = layout.sum(axis=2).astype(np.int32)              # [H, NQ]
        cnt_t = layout.sum(axis=1).astype(np.int32)            # [H, NK]
        a = max(1, int(cnt.max()))
        at = max(1, int(cnt_t.max()))
        kvmap = np.zeros((n_heads, nq, a), dtype=np.int32)
        qmap = np.zeros((n_heads, nk, at), dtype=np.int32)
        for h in range(n_heads):
            for i in range(nq):
                (live,) = np.nonzero(layout[h, i])
                kvmap[h, i, :live.size] = live
            for j in range(nk):
                (live,) = np.nonzero(layout[h, :, j])
                qmap[h, j, :live.size] = live
        self.kvmap, self.cnt, self.qmap, self.cnt_t = kvmap, cnt, qmap, cnt_t
        self.key = (layout.tobytes(), n_heads)

    def __hash__(self):
        return hash(self.key)

    def __eq__(self, other):
        return isinstance(other, _Tables) and self.key == other.key


# --------------------------------------------------------------------- forward
def _fwd_kernel(kvmap_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_sc, l_sc, *, scale, causal, block, kv_len):
    h, iq, a = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    na = pl.num_programs(3)

    @pl.when(a == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)

    ik = kvmap_ref[h, iq, a]
    q_start, k_start = iq * block, ik * block
    live = a < cnt_ref[h, iq]
    if causal:
        live = jnp.logical_and(live, k_start <= q_start + block - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        mask = kpos < kv_len
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
            mask = jnp.logical_and(mask, kpos <= qpos)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_sc[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_sc[:, 0:1] = l_sc[:, 0:1] * corr + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:, 0:1] = m_new
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(a == na - 1)
    def _finalize():
        l = l_sc[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(m_sc[:, 0:1] + jnp.log(l_safe), lse_ref[0, 0].shape)


def _sparse_fwd(q, k, v, tables, scale, causal, block):
    b, s, hq, d = q.shape
    hk = k.shape[2]
    group = hq // hk
    nq = tables.cnt.shape[1]
    sp = nq * block
    qt = jnp.pad(q.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sp - s), (0, 0)))
    kt = jnp.pad(k.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sp - s), (0, 0)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sp - s), (0, 0)))
    a = tables.kvmap.shape[2]

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block=block, kv_len=s)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hq, nq, a),
        in_specs=[
            pl.BlockSpec((1, 1, block, d), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block, d),
                         lambda bi, h, iq, ai, kvmap, cnt: (bi, h // group, kvmap[h, iq, ai], 0)),
            pl.BlockSpec((1, 1, block, d),
                         lambda bi, h, iq, ai, kvmap, cnt: (bi, h // group, kvmap[h, iq, ai], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block, d), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block, 128), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sp, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sp, 128), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(jnp.asarray(tables.kvmap), jnp.asarray(tables.cnt), qt, kt, vt)
    return out[:, :, :s].transpose(0, 2, 1, 3), lse[:, :, :s, 0]


# -------------------------------------------------------------------- backward
def _bwd_dkdv_kernel(qmap_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                     block, kv_len):
    h, ik, a = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    na = pl.num_programs(3)

    @pl.when(a == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    iq = qmap_ref[h, ik, a]
    q_start, k_start = iq * block, ik * block
    live = a < cnt_ref[h, ik]
    if causal:
        live = jnp.logical_and(live, k_start <= q_start + block - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]
        delta = delta_ref[0, 0, :, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        mask = kpos < kv_len
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
            mask = jnp.logical_and(mask, kpos <= qpos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_acc[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(a == na - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(kvmap_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_acc, *, scale, causal, block, kv_len):
    h, iq, a = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    na = pl.num_programs(3)

    @pl.when(a == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    ik = kvmap_ref[h, iq, a]
    q_start, k_start = iq * block, ik * block
    live = a < cnt_ref[h, iq]
    if causal:
        live = jnp.logical_and(live, k_start <= q_start + block - 1)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]
        delta = delta_ref[0, 0, :, 0:1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        mask = kpos < kv_len
        if causal:
            qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
            mask = jnp.logical_and(mask, kpos <= qpos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_acc[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(a == na - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)


def _sparse_bwd(tables, scale, causal, block, res, g):
    q, k, v, out, lse = res
    do = g
    b, s, hq, d = q.shape
    hk = k.shape[2]
    group = hq // hk
    nq = tables.cnt.shape[1]
    sp = nq * block

    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = delta.transpose(0, 2, 1)

    def padt(x):
        return jnp.pad(x.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sp - x.shape[1]), (0, 0)))

    qt, kt, vt, dot = padt(q), padt(k), padt(v), padt(do)
    lse_p = jnp.pad(lse, ((0, 0), (0, 0), (0, sp - s)))
    delta_p = jnp.pad(delta, ((0, 0), (0, 0), (0, sp - s)))
    lse_p = jnp.broadcast_to(lse_p[..., None], lse_p.shape + (128,))
    delta_p = jnp.broadcast_to(delta_p[..., None], delta_p.shape + (128,))

    at = tables.qmap.shape[2]
    kern = functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                             block=block, kv_len=s)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hq, nq, at),
        in_specs=[
            pl.BlockSpec((1, 1, block, d),
                         lambda bi, h, ik, ai, qmap, cnt: (bi, h, qmap[h, ik, ai], 0)),
            pl.BlockSpec((1, 1, block, d), lambda bi, h, ik, ai, *refs: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block, d), lambda bi, h, ik, ai, *refs: (bi, h // group, ik, 0)),
            pl.BlockSpec((1, 1, block, d),
                         lambda bi, h, ik, ai, qmap, cnt: (bi, h, qmap[h, ik, ai], 0)),
            pl.BlockSpec((1, 1, block, 128),
                         lambda bi, h, ik, ai, qmap, cnt: (bi, h, qmap[h, ik, ai], 0)),
            pl.BlockSpec((1, 1, block, 128),
                         lambda bi, h, ik, ai, qmap, cnt: (bi, h, qmap[h, ik, ai], 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block, d), lambda bi, h, ik, ai, *refs: (bi, h, ik, 0)),
            pl.BlockSpec((1, 1, block, d), lambda bi, h, ik, ai, *refs: (bi, h, ik, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block, d), jnp.float32),
            pltpu.VMEM((block, d), jnp.float32),
        ],
    )
    dk_h, dv_h = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sp, d), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, sp, d), jnp.float32),
        ],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(jnp.asarray(tables.qmap), jnp.asarray(tables.cnt_t), qt, kt, vt, dot, lse_p, delta_p)
    dk = dk_h.reshape(b, hk, group, sp, d).sum(axis=2)
    dv = dv_h.reshape(b, hk, group, sp, d).sum(axis=2)

    a = tables.kvmap.shape[2]
    kern_q = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                               block=block, kv_len=s)
    grid_spec_q = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hq, nq, a),
        in_specs=[
            pl.BlockSpec((1, 1, block, d), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block, d),
                         lambda bi, h, iq, ai, kvmap, cnt: (bi, h // group, kvmap[h, iq, ai], 0)),
            pl.BlockSpec((1, 1, block, d),
                         lambda bi, h, iq, ai, kvmap, cnt: (bi, h // group, kvmap[h, iq, ai], 0)),
            pl.BlockSpec((1, 1, block, d), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block, 128), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
            pl.BlockSpec((1, 1, block, 128), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block, d), lambda bi, h, iq, ai, *refs: (bi, h, iq, 0)),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
    )
    dq = pl.pallas_call(
        kern_q,
        grid_spec=grid_spec_q,
        out_shape=jax.ShapeDtypeStruct((b, hq, sp, d), q.dtype),
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary")),
        interpret=_pallas.INTERPRET,
    )(jnp.asarray(tables.kvmap), jnp.asarray(tables.cnt), qt, kt, vt, dot, lse_p, delta_p)

    dq = dq[:, :, :s].transpose(0, 2, 1, 3)
    dk = dk[:, :, :s].transpose(0, 2, 1, 3).astype(k.dtype)
    dv = dv[:, :, :s].transpose(0, 2, 1, 3).astype(v.dtype)
    return dq, dk, dv


# ------------------------------------------------------------------ public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _sparse(q, k, v, tables, scale, causal, block):
    out, _ = _sparse_fwd(q, k, v, tables, scale, causal, block)
    return out


def _sparse_vjp_fwd(q, k, v, tables, scale, causal, block):
    out, lse = _sparse_fwd(q, k, v, tables, scale, causal, block)
    return out, (q, k, v, out, lse)


_sparse.defvjp(_sparse_vjp_fwd, _sparse_bwd)

_tables_cache = {}
_TABLES_CACHE_MAX = 64  # bounds host memory for variable-seq-len serving


def _get_tables(layout: np.ndarray, n_heads: int) -> _Tables:
    key = (layout.tobytes(), layout.shape, n_heads)
    if key not in _tables_cache:
        if len(_tables_cache) >= _TABLES_CACHE_MAX:
            _tables_cache.pop(next(iter(_tables_cache)))
        _tables_cache[key] = _Tables(np.asarray(layout, dtype=np.uint8), n_heads)
    return _tables_cache[key]


def _layout_element_mask(layout: np.ndarray, block: int, s: int, n_heads: int):
    """Expand a block layout to a [1, H, S, S] element mask (dense fallback)."""
    lh = layout.shape[0]
    m = np.repeat(np.repeat(layout, block, axis=1), block, axis=2)[:, :s, :s]
    if lh != n_heads:
        m = np.broadcast_to(m, (n_heads, s, s))
    return jnp.asarray(m[None].astype(bool))


def sparse_attention(q, k, v, layout, block: int, *, causal: bool = False,
                     softmax_scale: Optional[float] = None, mask=None):
    """Block-sparse attention.  q/k/v [B, S, H, D] (GQA allowed), ``layout``
    uint8 [H or 1, NB, NB] from a SparsityConfig, ``block`` its block size.

    NB * block must cover S (pad rows are masked).  A dense element ``mask``
    (or running off-TPU) routes to the XLA fallback — identical math, used as
    the parity baseline in tests.  On TPU, block >= 128 keeps the MXU fed;
    the reference default 16 works but under-utilises the hardware.
    """
    b, s, hq, d = q.shape
    if k.shape[1] != s:
        raise NotImplementedError(
            "sparse_attention supports self-attention only (sq == sk), as in the "
            "reference (sparse_self_attention.py:121) — the block layout has no "
            "meaning for a query/cache length mismatch (decode)")
    layout = np.asarray(layout, dtype=np.uint8)
    nb = layout.shape[1]
    if nb * block < s:
        raise ValueError(f"layout covers {nb * block} positions < seq_len {s}")
    scale = softmax_scale if softmax_scale is not None else 1.0 / float(np.sqrt(d))
    if mask is not None or not _use_pallas() or block % 8 != 0:
        if block % 8 != 0 and _use_pallas():
            from ...utils.logging import logger
            logger.warning(
                f"sparse_attention: block={block} is not a multiple of 8; falling "
                f"back to the dense-masked XLA path (O(S^2) mask) — use a multiple "
                f"of 8 (ideally 128) for the Pallas kernel")
        from ...models.transformer import sdpa
        lm = _layout_element_mask(layout, block, s, hq)
        if mask is not None:
            lm = jnp.logical_and(lm, mask)
        return sdpa(q, k, v, causal=causal, mask=lm, softmax_scale=scale)
    tables = _get_tables(layout, hq)
    return _sparse(q, k, v, tables, scale, causal, block)


def make_sparse_attention_fn(config, max_seq_length: int):
    """Build an ``attention_fn`` for models.transformer.attention_block from a
    SparsityConfig — the functional analog of the reference's
    SparseSelfAttention module (sparse_self_attention.py:12): the layout is
    made once at ``max_seq_length`` (master_layout) and sliced per call."""
    master = config.make_layout(max_seq_length)

    def attention_fn(q, k, v, causal=True, mask=None, softmax_scale=None):
        s = q.shape[1]
        nb = -(-s // config.block)
        layout = master[:, :nb, :nb]
        return sparse_attention(q, k, v, layout, config.block, causal=causal,
                                softmax_scale=softmax_scale, mask=mask)

    return attention_fn


def make_config_attention_fn(section):
    """Build an ``attention_fn`` straight from the runtime config's
    ``sparse_attention`` section (runtime/config.py SparseAttentionConfig) —
    the path the reference covers by constructing SparseSelfAttention from
    ``get_sparse_attention(config)`` (sparse_self_attention.py:99).

    The SparsityConfig needs ``num_heads`` and the layout needs the sequence
    length, both known only at trace time from q's shape — so the layout is
    built lazily and cached per (heads, seq).  Decode-shaped calls
    (s_q != s_k) and sequences not divisible by ``block`` fall back to the
    dense default (the reference pads via sparse_attention_utils; here models
    own their padding, see pad_to_block_size)."""
    cache = {}

    def attention_fn(q, k, v, causal=True, mask=None, softmax_scale=None):
        s, h = q.shape[1], q.shape[2]
        if q.shape[1] != k.shape[1] or s % section.block != 0:
            from ...models.transformer import default_attention
            return default_attention()(q, k, v, causal=causal, mask=mask,
                                       softmax_scale=softmax_scale)
        if (h, s) not in cache:
            cache[(h, s)] = section.build(h).make_layout(s)
        return sparse_attention(q, k, v, cache[(h, s)], section.block, causal=causal,
                                softmax_scale=softmax_scale, mask=mask)

    return attention_fn


def pad_to_block_size(block: int, x, pad_token_id: int = 0):
    """Right-pad token ids [B, S] to a multiple of ``block`` (the analog of
    sparse_attention_utils.pad_to_block_size, which the reference applies to
    HF inputs before sparse layers).  Returns (padded, pad_len)."""
    s = x.shape[1]
    pad = (-s) % block
    if pad == 0:
        return x, 0
    return jnp.pad(x, ((0, 0), (0, pad)), constant_values=pad_token_id), pad
