"""Block-sparsity layout generators.

Same pattern family and knobs as the reference
(deepspeed/ops/sparse_attention/sparsity_config.py: SparsityConfig:10,
FixedSparsityConfig:95, VariableSparsityConfig:239, BigBirdSparsityConfig:411,
BSLongformerSparsityConfig:546, LocalSlidingWindowSparsityConfig) but built
with vectorised numpy index grids instead of per-element loops — the layout is
host-side planning data that feeds the Pallas kernel's scalar-prefetch tables,
so it lives in numpy, not torch.

A layout is ``uint8 [num_heads, num_blocks, num_blocks]``: ``layout[h, i, j]``
says whether query block ``i`` of head ``h`` may attend to key block ``j``.
Element-level masking inside live blocks (causal diagonal, padding) is applied
by the kernel, matching the reference's softmax-stage attn_mask handling
(sparse_self_attention.py:139-146).
"""

import random

import numpy as np


class SparsityConfig:
    """Base class: block size, head count, and per-head layout policy.

    ``seed`` drives every random-block placement through a private
    ``random.Random`` stream (never the global ``random`` module), so a given
    config produces the SAME layout on every rank and every rerun — the layout
    feeds each rank's kernel prefetch tables, and divergent tables would make
    attention itself rank-dependent."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False, seed=1234):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1
        self.seed = seed

    def layout_rng(self):
        """A fresh seeded stream per make_layout call: layouts are a pure
        function of (config, seq_len), not of how many were built before."""
        return random.Random(self.seed)

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(
                f"Sequence length {seq_len} must be divisible by block size {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.uint8)

    def propagate_first_head(self, layout):
        """If all heads share one layout, copy head 0 everywhere."""
        if not self.different_layout_per_head:
            layout[1:] = layout[0:1]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError

    # ---- shared vectorised primitives -------------------------------------
    @staticmethod
    def _block_grid(num_blocks):
        """(row, col) index grids for one head's [NB, NB] layout."""
        r = np.arange(num_blocks)[:, None]
        c = np.arange(num_blocks)[None, :]
        return r, c

    @staticmethod
    def _tril(layout_h):
        return np.tril(layout_h).astype(np.uint8)

    def _set_sliding_band(self, h, layout, num_window_blocks):
        """Symmetric sliding band of ±(num_window_blocks // 2) around the diagonal."""
        nb = layout.shape[1]
        if nb < num_window_blocks:
            raise ValueError(f"num_sliding_window_blocks ({num_window_blocks}) "
                             f"exceeds row width ({nb})")
        w = num_window_blocks // 2
        r, c = self._block_grid(nb)
        layout[h] |= (np.abs(r - c) <= w).astype(np.uint8)
        return layout

    @staticmethod
    def _validate_global_ranges(starts, ends):
        if ends is not None:
            if len(starts) != len(ends):
                raise ValueError("global start/end index lists must have equal length")
            for s, e in zip(starts, ends):
                if e <= s:
                    raise ValueError("global block end must exceed its start")


class DenseSparsityConfig(SparsityConfig):
    """All blocks live — degenerates to (optionally causal) dense attention."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer style fixed pattern: local windows of
    ``num_local_blocks`` plus per-window global representative columns
    (last ``num_global_blocks`` of each window, rotated across heads by
    ``num_different_global_patterns``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1, attention="bidirectional",
                 horizontal_global_attention=False, num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks ({num_local_blocks}) must be divisible by "
                f"num_global_blocks ({num_global_blocks})")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("attention must be uni/bidirectional")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("multiple global patterns require different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError("num_different_global_patterns cannot exceed "
                             "num_local_blocks // num_global_blocks")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def _local(self, h, layout):
        nb = layout.shape[1]
        r, c = self._block_grid(nb)
        same_window = (r // self.num_local_blocks) == (c // self.num_local_blocks)
        if self.attention == "unidirectional":
            same_window = same_window & (c <= r)
        layout[h] |= same_window.astype(np.uint8)
        return layout

    def _global(self, h, layout):
        nb = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        first = L - (1 + h % self.num_different_global_patterns) * G
        full_end = nb - (nb % L)
        starts = list(range(first, full_end, L))
        if full_end < nb:  # short trailing window: clamp its representative
            starts.append(min(full_end + first, nb - G))
        for g in starts:
            row0 = 0 if self.attention == "bidirectional" else g
            layout[h, row0:, g:g + G] = 1  # vertical stripe
            if self.horizontal_global_attention:
                layout[h, g:g + G, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._local(h, layout)
            layout = self._global(h, layout)
        return self.propagate_first_head(layout)


class VariableSparsityConfig(SparsityConfig):
    """Random + variable-width local windows + user-chosen global blocks.
    ``local_window_blocks`` lists successive window widths (last one repeats);
    ``global_block_indices``/``global_block_end_indices`` choose global columns
    either as single blocks or [start, end) ranges."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=None,
                 global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=1234):
        super().__init__(num_heads, block, different_layout_per_head, seed=seed)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("attention must be uni/bidirectional")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        self._validate_global_ranges(self.global_block_indices, global_block_end_indices)
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def _random(self, h, layout, rng):
        nb = layout.shape[1]
        if self.num_random_blocks == 0:
            return layout
        if nb < self.num_random_blocks:
            raise ValueError(f"num_random_blocks ({self.num_random_blocks}) exceeds "
                             f"row width ({nb})")
        for row in range(nb):
            cols = rng.sample(range(nb), self.num_random_blocks)
            layout[h, row, cols] = 1
        return layout

    def _local(self, h, layout):
        nb = layout.shape[1]
        start = 0
        widths = list(self.local_window_blocks)
        # repeat the final width over any remaining rows
        while start < nb:
            w = widths.pop(0) if widths else self.local_window_blocks[-1]
            end = min(start + w, nb)
            r, c = np.meshgrid(np.arange(start, end), np.arange(start, end), indexing="ij")
            if self.attention == "unidirectional":
                keep = c <= r
                layout[h, r[keep], c[keep]] = 1
            else:
                layout[h, start:end, start:end] = 1
            start = end
        return layout

    def _global(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices]
        else:
            ranges = list(zip(self.global_block_indices, self.global_block_end_indices))
        for s, e in ranges:
            if s >= nb:
                continue
            e = min(e, nb)
            if self.horizontal_global_attention:
                layout[h, s:e, :] = 1
            row0 = 0 if self.attention == "bidirectional" else s
            layout[h, row0:, s:e] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        rng = self.layout_rng()
        for h in range(self.num_layout_heads):
            layout = self._random(h, layout, rng)
            layout = self._local(h, layout)
            layout = self._global(h, layout)
        return self.propagate_first_head(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird ITC: random blocks + sliding window + leading global blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3, num_global_blocks=1,
                 attention="bidirectional", seed=1234):
        super().__init__(num_heads, block, different_layout_per_head, seed=seed)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("attention must be uni/bidirectional")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def _random(self, h, layout, rng):
        nb = layout.shape[1]
        if nb < self.num_random_blocks:
            raise ValueError(f"num_random_blocks ({self.num_random_blocks}) exceeds "
                             f"row width ({nb})")
        for row in range(nb):
            pool = range(nb) if self.attention == "bidirectional" else range(row + 1)
            k = min(self.num_random_blocks, len(pool))
            layout[h, row, rng.sample(pool, k)] = 1
        return layout

    def _sliding(self, h, layout):
        return self._set_sliding_band(h, layout, self.num_sliding_window_blocks)

    def _global(self, h, layout):
        nb = layout.shape[1]
        if nb < self.num_global_blocks:
            raise ValueError(f"num_global_blocks ({self.num_global_blocks}) exceeds "
                             f"row width ({nb})")
        G = self.num_global_blocks
        layout[h, :G, :] = 1
        layout[h, :, :G] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        rng = self.layout_rng()
        for h in range(self.num_layout_heads):
            layout = self._random(h, layout, rng)
            layout = self._sliding(h, layout)
            layout = self._global(h, layout)
            if self.attention == "unidirectional":
                layout[h] = self._tril(layout[h])
        return self.propagate_first_head(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Blocked Longformer: sliding window + symmetric (row+col) global blocks."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=None,
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        self._validate_global_ranges(self.global_block_indices, global_block_end_indices)
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def _sliding(self, h, layout):
        return self._set_sliding_band(h, layout, self.num_sliding_window_blocks)

    def _global(self, h, layout):
        nb = layout.shape[1]
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices]
        else:
            ranges = list(zip(self.global_block_indices, self.global_block_end_indices))
        for s, e in ranges:
            if s >= nb:
                continue
            e = min(e, nb)
            layout[h, s:e, :] = 1
            layout[h, :, s:e] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._sliding(h, layout)
            layout = self._global(h, layout)
            if self.attention == "unidirectional":
                layout[h] = self._tril(layout[h])
        return self.propagate_first_head(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding-window attention (the Mistral pattern, block-granular)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(f"num_sliding_window_blocks "
                             f"({self.num_sliding_window_blocks}) exceeds row width ({nb})")
        w = self.num_sliding_window_blocks // 2
        r, c = self._block_grid(nb)
        band = (r - c <= w) & (c <= r) if self.attention == "unidirectional" \
            else (np.abs(r - c) <= w)
        layout[0] |= band.astype(np.uint8)
        return self.propagate_first_head(layout)
