"""Block-sparse attention for TPU.

The reference ships a Triton blocksparse stack (deepspeed/ops/sparse_attention/:
matmul.py SDD/DSD kernels, softmax.py, sparsity_config.py, and the
SparseSelfAttention / BertSparseSelfAttention modules).  Here the same
capability is a single fused Pallas kernel (attention.py) driven by the same
family of block-layout generators (sparsity_config.py) — on TPU there is no
reason to split QK^T / softmax / PV into three kernels, the fused online-softmax
form is strictly better (no materialised block-sparse score tensor in HBM).
"""

from .sparsity_config import (SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,
                              VariableSparsityConfig, BigBirdSparsityConfig,
                              BSLongformerSparsityConfig, LocalSlidingWindowSparsityConfig)
from .attention import sparse_attention, make_sparse_attention_fn, pad_to_block_size
