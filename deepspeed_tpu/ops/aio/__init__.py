"""Async file I/O for tensor offload (reference deepspeed/ops/aio + csrc/aio).

``build_aio_handle()`` returns the native threaded pread/pwrite library when a
C++ toolchain is available, else a Python thread-pool fallback with the same
interface: submit pwrite/pread -> request id; wait(id) -> byte count; wait_all().
"""

import ctypes
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Dict

import numpy as np

from ...utils.logging import logger
from ..op_builder import AsyncIOBuilder


class AsyncIOHandle:
    """ctypes wrapper over the native aio library.

    ``use_odirect`` routes bulk transfers through O_DIRECT (page-cache bypass,
    the reference's libaio mode); filesystems that reject it (tmpfs) fall back
    to buffered I/O inside the library, per file."""

    def __init__(self, num_threads: int = 4, use_odirect: bool = False):
        self._lib = AsyncIOBuilder().load()
        self._h = self._lib.dstpu_aio_open_ex(num_threads, int(use_odirect))

    def pwrite(self, path: str, arr: np.ndarray) -> int:
        arr = np.ascontiguousarray(arr)
        self._keepalive = getattr(self, "_keepalive", {})
        rid = self._lib.dstpu_aio_pwrite(self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
                                         arr.nbytes)
        self._keepalive[rid] = arr  # pin until waited
        return rid

    def pread(self, path: str, arr: np.ndarray) -> int:
        assert arr.flags["C_CONTIGUOUS"] and arr.flags["WRITEABLE"]
        rid = self._lib.dstpu_aio_pread(self._h, path.encode(), arr.ctypes.data_as(ctypes.c_void_p),
                                        arr.nbytes)
        self._keepalive = getattr(self, "_keepalive", {})
        self._keepalive[rid] = arr
        return rid

    def wait(self, rid: int) -> int:
        out = int(self._lib.dstpu_aio_wait(self._h, rid))
        self._keepalive.pop(rid, None)
        if out < 0:
            raise OSError(-out, os.strerror(-out))
        return out

    def wait_all(self) -> None:
        failures = self._lib.dstpu_aio_wait_all(self._h)
        self._keepalive = {}
        if failures:
            raise OSError(f"{failures} async IO requests failed")

    def close(self):
        if getattr(self, "_h", None):
            self._lib.dstpu_aio_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # dslint: disable=silent-except  # interpreter-shutdown teardown: the ctypes lib may be unloaded already; raising from __del__ only prints noise
            pass


class PyAsyncIOHandle:
    """Pure-Python fallback (ThreadPoolExecutor) with the same surface."""

    def __init__(self, num_threads: int = 4):
        self._pool = ThreadPoolExecutor(max_workers=num_threads)
        self._futs: Dict[int, object] = {}
        self._next = 1

    def _submit(self, fn) -> int:
        rid = self._next
        self._next += 1
        self._futs[rid] = self._pool.submit(fn)
        return rid

    def pwrite(self, path: str, arr: np.ndarray) -> int:
        data = np.ascontiguousarray(arr)
        return self._submit(lambda: open(path, "wb").write(data.tobytes()))

    def pread(self, path: str, arr: np.ndarray) -> int:
        def read():
            with open(path, "rb") as fh:
                buf = fh.read(arr.nbytes)
            arr.view(np.uint8).reshape(-1)[:len(buf)] = np.frombuffer(buf, np.uint8)
            return len(buf)

        return self._submit(read)

    def wait(self, rid: int) -> int:
        return int(self._futs.pop(rid).result())

    def wait_all(self) -> None:
        for rid in list(self._futs):
            self.wait(rid)

    def close(self):
        self._pool.shutdown(wait=True)


def build_aio_handle(num_threads: int = 4, use_odirect: bool = False):
    try:
        return AsyncIOHandle(num_threads, use_odirect=use_odirect)
    except Exception as exc:  # no compiler / build failure
        logger.warning(f"native aio unavailable ({exc}); using Python thread-pool fallback")
        return PyAsyncIOHandle(num_threads)
