"""Prometheus text-format (0.0.4) exposition for the metrics registry.

Renders a :class:`~.metrics.MetricsRegistry` as the plain-text format every
Prometheus-compatible scraper speaks::

    # HELP dstpu_serving_shed_total requests load-shed at the admission door
    # TYPE dstpu_serving_shed_total counter
    dstpu_serving_shed_total 3
    # HELP dstpu_request_ttft_seconds time to first token
    # TYPE dstpu_request_ttft_seconds histogram
    dstpu_request_ttft_seconds_bucket{le="1e-05"} 0
    dstpu_request_ttft_seconds_bucket{le="2.1544346900318823e-05"} 2
    dstpu_request_ttft_seconds_bucket{le="+Inf"} 7
    dstpu_request_ttft_seconds_sum 0.004
    dstpu_request_ttft_seconds_count 7

Histogram conversion is EXACT, not approximated: the log-bucket
:class:`~.tracing.StreamingHistogram` keeps one count per occupied bucket,
and every bucket's upper edge becomes a cumulative ``le`` boundary (the
underflow bucket's edge is ``min_value``), with ``_sum``/``_count`` taken
from the histogram's own running total/count.  :func:`histogram_from_samples`
reverses the mapping (``le`` edge -> bucket index), so a histogram
round-trips through exposition with IDENTICAL quantiles — the property the
unit tests pin, and the reason a fleet endpoint can be scraped instead of
queried in-process without losing SLO accuracy.

Also here: :func:`parse_exposition`, a strict mini parser used by the tests
and the ops-smoke lane to validate that everything we render (HELP/TYPE
lines, label escaping, histogram cumulativity, ``+Inf`` == ``_count``) is
well-formed — the in-tree scraper contract.

All host-side string/arithmetic work; nothing here imports jax or numpy
(dslint's host-sync scan covers this file — see metrics.py).
"""

import math
import re
from typing import Any, Dict, List, Optional, Tuple

from .metrics import (COUNTER, GAUGE, HISTOGRAM, METRIC_NAME_RE, MetricFamily,
                      MetricsRegistry)
from .tracing import StreamingHistogram

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)"
    r"(?:\s+(?P<ts>-?\d+))?$")
_LABEL_RE = re.compile(
    r'\s*(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\.)*)"\s*(?:,|$)')


def escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        ch = value[i]
        if ch == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            out.append({"n": "\n", '"': '"', "\\": "\\"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def format_value(value: float) -> str:
    """Prometheus-parseable value: integral floats render as ints (counters
    stay pretty), everything else as ``repr`` (which round-trips exactly)."""
    value = float(value)
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if math.isnan(value):
        return "NaN"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _labels_text(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(str(v))}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


# ------------------------------------------------------------ histogram maps
def cumulative_buckets(hist: StreamingHistogram) -> List[Tuple[float, int]]:
    """``[(le_upper_edge, cumulative_count)]`` over occupied buckets, in
    ascending edge order.  Bucket ``i`` of the log histogram covers
    ``[min * 10^(i/bpd), min * 10^((i+1)/bpd))``; its Prometheus boundary is
    the exclusive upper edge (the count of values <= edge equals the count
    of values < edge for these half-open buckets up to measure-zero ties,
    and the histogram itself assigns exact edges to the upper bucket, so
    the cumulative counts are exact)."""
    out: List[Tuple[float, int]] = []
    cum = 0
    for idx in sorted(hist.counts):
        cum += hist.counts[idx]
        out.append((bucket_upper_edge(hist, idx), cum))
    return out


def bucket_upper_edge(hist: StreamingHistogram, index: int) -> float:
    """Exclusive upper edge of log-bucket ``index`` (underflow's edge is
    exactly ``min_value``: the formula holds for index -1 too)."""
    return hist.min_value * 10.0 ** ((index + 1) / hist.buckets_per_decade)


def bucket_index_of_edge(le: float, buckets_per_decade: int,
                         min_value: float) -> int:
    """Inverse of :func:`bucket_upper_edge` (round-trip reconstruction)."""
    return round(math.log10(le / min_value) * buckets_per_decade) - 1


def histogram_from_samples(samples: List[Tuple[Dict[str, str], float]], *,
                           buckets_per_decade: int,
                           min_value: float) -> StreamingHistogram:
    """Rebuild a :class:`StreamingHistogram` from parsed exposition samples
    of one histogram family (the ``_bucket``/``_sum``/``_count`` triplet as
    ``(labels, value)`` pairs, ``le`` in labels).  Quantiles of the result
    are IDENTICAL to the source histogram's — the round-trip contract.
    ``max_seen`` is not carried by the text format and stays None."""
    hist = StreamingHistogram(buckets_per_decade, min_value)
    edges: List[Tuple[float, int]] = []
    for labels, value in samples:
        le = labels.get("le")
        if le is None:
            continue
        if le == "+Inf":
            hist.count = int(value)
            continue
        edges.append((float(le), int(value)))
    edges.sort()
    prev = 0
    for le, cum in edges:
        n = cum - prev
        prev = cum
        if n:
            hist.counts[bucket_index_of_edge(le, buckets_per_decade,
                                             min_value)] = n
    if hist.count < prev:
        hist.count = prev
    return hist


# ------------------------------------------------------------------- render
def render_family(fam: MetricFamily) -> List[str]:
    lines = [f"# HELP {fam.name} {escape_help(fam.help)}",
             f"# TYPE {fam.name} {fam.kind}"]
    for key in sorted(fam.samples):
        labels = dict(key)
        value = fam.samples[key]
        if fam.kind == HISTOGRAM:
            for le, cum in cumulative_buckets(value):
                lines.append(f"{fam.name}_bucket"
                             f"{_labels_text({**labels, 'le': repr(le)})} {cum}")
            lines.append(f"{fam.name}_bucket"
                         f"{_labels_text({**labels, 'le': '+Inf'})} {value.count}")
            lines.append(f"{fam.name}_sum{_labels_text(labels)} "
                         f"{format_value(value.total)}")
            lines.append(f"{fam.name}_count{_labels_text(labels)} {value.count}")
        else:
            lines.append(f"{fam.name}{_labels_text(labels)} "
                         f"{format_value(value)}")
    return lines


def render(registry: MetricsRegistry, *, collect: bool = True) -> str:
    """The full /metrics payload.  ``collect=False`` skips the registered
    collector callbacks and renders the registry as-is — the ops server's
    cache-refresh path collects explicitly on the owning thread."""
    families = registry.collect() if collect else registry.families
    lines: List[str] = []
    for name in sorted(families):
        lines.extend(render_family(families[name]))
    return "\n".join(lines) + ("\n" if lines else "")


# -------------------------------------------------------------------- parse
class ExpositionError(ValueError):
    """A rendered payload violated the text-format contract (the mini
    parser is strict on purpose: it is the in-tree stand-in for every
    external scraper)."""


def _base_name(sample_name: str, histogram_families: set) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix) and \
                sample_name[:-len(suffix)] in histogram_families:
            return sample_name[:-len(suffix)]
    return sample_name


def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Strict parse of a 0.0.4 payload.

    Returns ``{family: {"type", "help", "samples": [(name, labels, value)]}}``
    and raises :class:`ExpositionError` on: malformed HELP/TYPE/sample lines,
    a sample with no preceding TYPE for its family, bad label syntax or a
    histogram sample without ``le``, non-monotone cumulative buckets, or a
    ``+Inf`` bucket disagreeing with ``_count``."""
    families: Dict[str, Dict[str, Any]] = {}
    histogram_families: set = set()
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            name = parts[0]
            if not METRIC_NAME_RE.match(name):
                raise ExpositionError(f"line {lineno}: bad metric name in HELP: {name!r}")
            fam = families.setdefault(name, {"type": None, "help": "", "samples": []})
            fam["help"] = _unescape(parts[1]) if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split()
            if len(parts) != 2 or parts[1] not in (COUNTER, GAUGE, HISTOGRAM,
                                                   "summary", "untyped"):
                raise ExpositionError(f"line {lineno}: bad TYPE line: {raw!r}")
            name, kind = parts
            fam = families.setdefault(name, {"type": None, "help": "", "samples": []})
            if fam["samples"]:
                raise ExpositionError(f"line {lineno}: TYPE for {name} after its samples")
            fam["type"] = kind
            if kind == HISTOGRAM:
                histogram_families.add(name)
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ExpositionError(f"line {lineno}: unparseable sample: {raw!r}")
        sample_name = m.group("name")
        labels: Dict[str, str] = {}
        labels_text = m.group("labels")
        if labels_text is not None:
            pos = 0
            while pos < len(labels_text):
                lm = _LABEL_RE.match(labels_text, pos)
                if not lm:
                    raise ExpositionError(
                        f"line {lineno}: bad label syntax at {labels_text[pos:]!r}")
                labels[lm.group("name")] = _unescape(lm.group("value"))
                pos = lm.end()
        value_text = m.group("value")
        try:
            value = float("inf") if value_text == "+Inf" else \
                float("-inf") if value_text == "-Inf" else float(value_text)
        except ValueError:
            raise ExpositionError(f"line {lineno}: bad value {value_text!r}")
        base = _base_name(sample_name, histogram_families)
        fam = families.get(base)
        if fam is None or fam["type"] is None:
            raise ExpositionError(
                f"line {lineno}: sample {sample_name} has no preceding # TYPE")
        if fam["type"] == HISTOGRAM and sample_name == f"{base}_bucket" \
                and "le" not in labels:
            raise ExpositionError(f"line {lineno}: histogram bucket without le label")
        fam["samples"].append((sample_name, labels, value))
    _validate_histograms(families, histogram_families)
    return families


def _series_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))


def _validate_histograms(families: Dict[str, Dict[str, Any]],
                         histogram_families: set) -> None:
    for name in histogram_families:
        fam = families[name]
        buckets: Dict[Tuple, List[Tuple[float, float]]] = {}
        counts: Dict[Tuple, float] = {}
        for sample_name, labels, value in fam["samples"]:
            key = _series_key(labels)
            if sample_name == f"{name}_bucket":
                le = labels["le"]
                edge = float("inf") if le == "+Inf" else float(le)
                buckets.setdefault(key, []).append((edge, value))
            elif sample_name == f"{name}_count":
                counts[key] = value
        for key, series in buckets.items():
            series.sort()
            last = -1.0
            for edge, cum in series:
                if cum < last:
                    raise ExpositionError(
                        f"{name}{dict(key)}: cumulative bucket counts decrease "
                        f"at le={edge}")
                last = cum
            if not series or not math.isinf(series[-1][0]):
                raise ExpositionError(f"{name}{dict(key)}: missing +Inf bucket")
            inf_count = series[-1][1]
            if key in counts and counts[key] != inf_count:
                raise ExpositionError(
                    f"{name}{dict(key)}: +Inf bucket ({inf_count}) != _count "
                    f"({counts[key]})")


def parsed_histogram(families: Dict[str, Dict[str, Any]], name: str, *,
                     buckets_per_decade: int, min_value: float,
                     labels: Optional[Dict[str, str]] = None
                     ) -> StreamingHistogram:
    """Convenience for tests/smokes: reconstruct one (family, label-set)
    histogram straight from :func:`parse_exposition` output."""
    fam = families[name]
    want = _series_key(labels or {})
    samples = [(lab, value) for sample_name, lab, value in fam["samples"]
               if sample_name == f"{name}_bucket" and _series_key(lab) == want]
    hist = histogram_from_samples(samples, buckets_per_decade=buckets_per_decade,
                                  min_value=min_value)
    for sample_name, lab, value in fam["samples"]:
        if _series_key(lab) != want:
            continue
        if sample_name == f"{name}_sum":
            hist.total = value
        elif sample_name == f"{name}_count":
            hist.count = int(value)
    return hist
