"""Serving performance observatory (ISSUE 16).

Three instruments that let the v2 serving stack attribute where a serve
iteration's wall-clock went, where every XLA compile came from, and how close
decode is running to the HBM roofline — the serving twin of the training-side
``wall_clock_breakdown`` + flops-profiler story (PARITY rows 43/57):

- :class:`StepPhaseProfiler` — mark-based per-iteration phase attribution for
  the serve loop.  The engine calls ``begin_iteration()`` at the top of each
  loop pass and ``mark(phase)`` after each phase's work; the profiler charges
  the time since the previous mark to that phase and sends whatever is left at
  ``end_iteration()`` to the ``other`` phase, so per-iteration phase spans sum
  to the iteration wall time *exactly* (FakeClock tests assert equality, not
  tolerance).  Per-phase :class:`~.tracing.StreamingHistogram` s give
  deterministic quantiles; every phase marked in an iteration records one
  sample (a 0.0 span lands in the underflow bucket, so families fill even
  under a zero-tick FakeClock).
- :class:`CompileLedger` — single source of truth for ``ServeCounters.compiles``.
  Every compile seam (engine fwd buckets, AOT prewarm, pick/burst programs,
  cow-copy, fastpath scatter/feed) records ``(site, key)`` here; the ledger
  classifies each as ``prewarmed`` / ``cold`` / first-seen vs ``warm``
  (a key recompiled after being seen — the runtime twin of dslint's
  ``recompile-risk`` rule) and bumps the counter exactly once per record, so
  counter values are unchanged from the pre-ledger ``+= 1`` sites.
- :class:`RooflineModel` — per-bucket FLOPs + HBM bytes captured once at AOT
  compile time from ``compiled.cost_analysis()`` (the engine passes plain
  floats; this module never sees a jax object), accumulated per dispatch into
  live ``hbm_bytes_per_token`` / ``roofline_fraction`` /
  ``model_flops_utilization`` gauges — the live counterpart of BENCH's
  ``hbm_stream_fraction_of_spec``.

Zero-device-sync contract (same as heartbeat/metrics/exposition/ops_server,
enforced by the dslint whole-file scan): nothing here imports jax or numpy,
and every timestamp is a host float handed in by the engine's injectable
clock.  The profiler reads that clock ONLY while ``enabled`` — observatory
off adds zero clock reads, so FakeClock call counts (and therefore tokens and
``ServeCounters``) are byte-identical with the observatory on or off.
"""

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

from .tracing import StreamingHistogram

# serve-loop phases, in rough per-iteration order; ``other`` absorbs the
# residual (heartbeat stamp, ops refresh, watchdog, journal flush) so the
# per-iteration spans always sum to the full iteration wall time
PHASES = ("admission_pump", "scatter_upload", "dispatch", "absorb_patch",
          "burst", "flush", "expire", "other")

# compile classes a ledger record can carry
CLASS_PREWARMED = "prewarmed"  # built ahead of traffic by _prewarm
CLASS_COLD = "cold"            # first build of this (site, key) under traffic
CLASS_WARM = "warm"            # rebuilt after already being seen: a recompile


class StepPhaseProfiler:
    """Mark-based phase attribution for the serve loop.

    ``begin_iteration()`` opens an iteration; ``mark(phase)`` charges the time
    since the previous mark (or the iteration start) to ``phase``;
    ``end_iteration()`` charges the residual to ``other``, folds the
    per-iteration spans into per-phase histograms and lifetime totals, and
    optionally emits an every-N-iterations phase-budget line to the flight
    recorder.  Marks outside an open iteration are ignored (the engine's
    public ``step()``/``decode_burst()`` run outside the serve loop too).
    """

    def __init__(self, config=None, *, clock: Optional[Callable[[], float]] = None,
                 tracer=None):
        cfg = config
        self.enabled = bool(getattr(cfg, "enabled", False))
        self.budget_every = int(getattr(cfg, "phase_budget_every", 50))
        bpd = int(getattr(cfg, "histogram_buckets_per_decade", 6))
        min_s = float(getattr(cfg, "histogram_min_s", 1e-7))
        self._clock = clock if clock is not None else (lambda: 0.0)
        self._tracer = tracer
        self.hists: Dict[str, StreamingHistogram] = {
            p: StreamingHistogram(buckets_per_decade=bpd, min_value=min_s)
            for p in PHASES}
        self.totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.iterations = 0
        self.wall_s = 0.0
        self._active = False
        self._t_iter0 = 0.0
        self._t_mark = 0.0
        self._spans: Dict[str, float] = {}
        # window accumulator for the flight-recorder phase-budget line
        self._win_spans: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._win_iters = 0

    def begin_iteration(self) -> None:
        if not self.enabled:
            return
        now = self._clock()
        self._active = True
        self._t_iter0 = now
        self._t_mark = now
        self._spans = {}

    def mark(self, phase: str) -> None:
        """Charge time since the previous mark to ``phase``."""
        if not self.enabled or not self._active:
            return
        now = self._clock()
        span = now - self._t_mark
        self._t_mark = now
        self._spans[phase] = self._spans.get(phase, 0.0) + span

    def end_iteration(self) -> None:
        if not self.enabled or not self._active:
            return
        now = self._clock()
        self._spans["other"] = self._spans.get("other", 0.0) + (now - self._t_mark)
        self._active = False
        wall = now - self._t_iter0
        self.iterations += 1
        self.wall_s += wall
        self._win_iters += 1
        start = self._t_iter0
        for phase, span in self._spans.items():
            self.hists[phase].add(span)
            self.totals[phase] += span
            self._win_spans[phase] += span
            if self._tracer is not None:
                self._tracer.phase_span(phase, start, span,
                                        track=PHASES.index(phase))
            start += span
        if self._win_iters >= self.budget_every:
            self._emit_budget()

    def _emit_budget(self) -> None:
        """Flight-recorder line: where the last window's wall time went."""
        if self._tracer is not None:
            total = sum(self._win_spans.values()) or 1.0
            fields = {p: round(self._win_spans[p], 6) for p in PHASES
                      if self._win_spans[p] > 0.0}
            top = max(self._win_spans, key=lambda p: self._win_spans[p])
            self._tracer.event("phase_budget", iters=self._win_iters,
                               wall_s=round(total, 6), top=top, **fields)
        self._win_spans = {p: 0.0 for p in PHASES}
        self._win_iters = 0

    def histograms(self) -> Dict[str, StreamingHistogram]:
        """Per-phase histograms that have seen at least one sample."""
        return {p: h for p, h in self.hists.items() if h.count}

    def snapshot(self) -> Dict[str, Any]:
        phases = {p: dict(self.hists[p].snapshot(),
                          total_s=round(self.totals[p], 9))
                  for p in PHASES if self.hists[p].count}
        return {"enabled": self.enabled, "iterations": self.iterations,
                "wall_s": round(self.wall_s, 9), "phases": phases}

    def reset(self) -> None:
        for h in self.hists.values():
            h.reset()
        self.totals = {p: 0.0 for p in PHASES}
        self.iterations = 0
        self.wall_s = 0.0
        self._active = False
        self._win_spans = {p: 0.0 for p in PHASES}
        self._win_iters = 0


class CompileLedger:
    """Attributed record of every XLA compile the serving engine triggers.

    Always on (it adds no clock reads and no device work): each compile seam
    calls :meth:`record` instead of bumping ``ServeCounters.compiles``
    directly, and the ledger bumps the counter exactly once per record — the
    counter's values are unchanged, but every unit now carries a jit-site
    name, a bucket key, a class, and (for AOT prewarm, the only seam where
    the compile happens synchronously on the host) a wall time.  A ``warm``
    record — a key rebuilt after already being seen at its site — is the
    runtime event dslint's ``recompile-risk`` rule predicts statically; it
    lands in the flight recorder and the per-site warm counters behind
    ``serving_recompiles_total{site=...}``.
    """

    def __init__(self, counters=None, *, tracer=None):
        self._counters = counters
        self._tracer = tracer
        self._seen: Dict[Tuple[str, str], int] = {}
        self.by_site: Dict[str, Dict[str, int]] = {}
        self.warm_by_site: Dict[str, int] = {}
        self.compile_wall_s = 0.0
        self.total = 0
        self.events: collections.deque = collections.deque(maxlen=256)

    @staticmethod
    def _key_str(key: Any) -> str:
        return key if isinstance(key, str) else repr(key)

    def record(self, site: str, key: Any, *, wall_s: float = 0.0,
               prewarmed: bool = False) -> str:
        """Record one compile at ``site`` for bucket ``key``; returns class."""
        k = (site, self._key_str(key))
        seen = self._seen.get(k, 0)
        self._seen[k] = seen + 1
        if seen:
            cls = CLASS_WARM
            self.warm_by_site[site] = self.warm_by_site.get(site, 0) + 1
            if self._tracer is not None:
                self._tracer.event("warm_recompile", site=site, key=k[1],
                                   builds=seen + 1)
        else:
            cls = CLASS_PREWARMED if prewarmed else CLASS_COLD
        per_site = self.by_site.setdefault(site, {})
        per_site[cls] = per_site.get(cls, 0) + 1
        self.compile_wall_s += float(wall_s)
        self.total += 1
        self.events.append({"site": site, "key": k[1], "class": cls,
                            "wall_s": round(float(wall_s), 6)})
        if self._counters is not None:
            self._counters.compiles += 1
        return cls

    @property
    def warm_total(self) -> int:
        return sum(self.warm_by_site.values())

    def snapshot(self) -> Dict[str, Any]:
        return {"total": self.total,
                "warm_total": self.warm_total,
                "compile_wall_s": round(self.compile_wall_s, 6),
                "by_site": {s: dict(c) for s, c in sorted(self.by_site.items())},
                "recent": list(self.events)[-8:]}


class RooflineModel:
    """Live tokens-per-HBM-byte roofline for the serve loop.

    The engine captures ``cost_analysis()`` floats once per AOT-compiled
    bucket (:meth:`note_cost`) and charges them per dispatch
    (:meth:`note_dispatch`); :meth:`gauges` divides the accumulated bytes and
    FLOPs by the profiler's measured wall time against the configured HBM
    spec and peak-FLOPs numbers.  Dispatches of buckets that were never
    AOT-costed (lazily-compiled shapes outside the prewarm set) are counted
    in ``uncosted_dispatches`` so a low roofline fraction is distinguishable
    from missing cost coverage.
    """

    def __init__(self, config=None):
        cfg = config
        self.hbm_gbps_spec = float(getattr(cfg, "hbm_gbps_spec", 819.0))
        self.peak_flops = getattr(cfg, "peak_flops_per_chip", None)
        self._costs: Dict[str, Tuple[float, float]] = {}  # key -> (flops, bytes)
        self.flops = 0.0
        self.bytes = 0.0
        self.tokens = 0
        self.dispatches = 0
        self.uncosted_dispatches = 0

    def reset(self) -> None:
        """Zero the dispatch accumulators (timed-pass isolation, e.g. bench's
        warm-then-measure discipline).  The per-bucket cost table survives:
        costs are a property of the compiled bucket, not of any one pass."""
        self.flops = 0.0
        self.bytes = 0.0
        self.tokens = 0
        self.dispatches = 0
        self.uncosted_dispatches = 0

    def note_cost(self, key: Any, flops: float, bytes_accessed: float) -> None:
        self._costs[CompileLedger._key_str(key)] = (float(flops),
                                                    float(bytes_accessed))

    def note_dispatch(self, key: Any, tokens: int) -> None:
        self.dispatches += 1
        self.tokens += int(tokens)
        cost = self._costs.get(CompileLedger._key_str(key))
        if cost is None:
            self.uncosted_dispatches += 1
            return
        self.flops += cost[0]
        self.bytes += cost[1]

    def gauges(self, wall_s: float) -> Dict[str, float]:
        """Finite gauge values; zeros until there is data to divide."""
        out = {"serving_hbm_bytes_per_token":
               (self.bytes / self.tokens) if self.tokens else 0.0,
               "serving_roofline_fraction": 0.0,
               "serving_model_flops_utilization": 0.0}
        if wall_s > 0.0:
            out["serving_roofline_fraction"] = (
                self.bytes / wall_s) / (self.hbm_gbps_spec * 1e9)
            if self.peak_flops:
                out["serving_model_flops_utilization"] = (
                    self.flops / wall_s) / float(self.peak_flops)
        return out

    def snapshot(self, wall_s: float = 0.0) -> Dict[str, Any]:
        return {"costed_buckets": len(self._costs),
                "dispatches": self.dispatches,
                "uncosted_dispatches": self.uncosted_dispatches,
                "tokens": self.tokens,
                "flops": self.flops,
                "hbm_bytes": self.bytes,
                "gauges": {k: round(v, 9)
                           for k, v in self.gauges(wall_s).items()}}
