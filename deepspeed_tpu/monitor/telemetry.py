"""Unified telemetry subsystem.

One collector joins the observability islands the reference spreads over
``wall_clock_breakdown`` timers, ``see_memory_usage``, the comms logger, the
FLOPs profiler and the monitor writers (deepspeed/runtime/engine.py
``_report_progress`` + monitor/monitor.py): per train step it assembles ONE
structured record — loss, grad-norm, lr, step wall-time, samples/sec,
tokens/sec, model-FLOPs-utilization, HBM high-water mark — and fans it out to

- ``MonitorMaster`` (TensorBoard / W&B / CSV writers, rank-0 only), and
- a rank-0 JSONL sink (``TelemetryConfig.jsonl_path``), one json object per
  line, machine-readable for regression tracking (bench.py computes the same
  MFU externally; this makes the engine report about itself).

It also owns config-driven ``jax.profiler`` capture windows
(``profile_step_start``/``profile_step_stop`` → ``start_trace``/``stop_trace``
into a TensorBoard-readable directory) and hands out ``StepTraceAnnotation`` /
``TraceAnnotation`` context managers so the engine's step, batch-prep and
checkpoint IO show up as named ranges in the trace.

MFU derivation (ISSUE: bench.py parity): ``flops_per_step`` comes ONCE from
the XLA cost analysis of the compiled train step (FlopsProfiler), divided by
the measured wall-time and the per-chip peak FLOPs × chip count.  Peak FLOPs
resolve from ``TelemetryConfig.peak_flops_per_chip``, the
``PALLAS_AXON_TPU_GEN`` env (the bench.py convention), or the device kind;
unknown hardware (CPU test backend) yields ``mfu: null`` unless the config
pins a peak.
"""

import contextlib
import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils.logging import logger, warning_once
from ..utils.memory import device_memory_stats

Event = Tuple[str, float, int]

# bf16 peak FLOPs per chip by TPU generation (bench.py PEAK_FLOPS)
PEAK_FLOPS_BY_GEN = {
    "v6e": 918e12,
    "v5p": 459e12,
    "v5e": 197e12,
    "v4": 275e12,
}

_FLOPS_UNSET = object()  # distinguishes "not yet profiled" from "profiling failed"


def detect_peak_flops_per_chip() -> Optional[float]:
    """Per-chip bf16 peak from env (bench.py convention) or device kind;
    None when the hardware is unknown (e.g. the CPU test backend)."""
    probe = os.environ.get("PALLAS_AXON_TPU_GEN", "")
    try:
        import jax
        probe += " " + getattr(jax.devices()[0], "device_kind", "")
    except Exception as exc:  # no backend: MFU falls back to the config pin
        warning_once(f"telemetry: device-kind probe failed ({exc!r}); peak FLOPs "
                     f"detection degrades to the PALLAS_AXON_TPU_GEN env / "
                     f"telemetry.peak_flops_per_chip config")
    probe = probe.lower().replace("tpu ", "").replace(" lite", "e")
    for gen, peak in PEAK_FLOPS_BY_GEN.items():
        if gen in probe:
            return peak
    return None


class TelemetryCollector:
    """Assembles per-step records and fans them out (monitor + JSONL).

    Disabled collectors (``config.enabled`` false and no ``jsonl_path``) keep
    every method a cheap no-op, so call sites never branch.
    """

    def __init__(self, config=None, monitor=None, batch_size: int = 1,
                 n_chips: Optional[int] = None):
        from ..runtime.config import TelemetryConfig
        self.config = config if config is not None else TelemetryConfig()
        self.monitor = monitor
        self.batch_size = max(int(batch_size), 1)
        self.enabled = bool(self.config.enabled)
        try:
            import jax
            self._is_rank0 = jax.process_index() == 0
            self.n_chips = int(n_chips) if n_chips else jax.device_count()
        except Exception:
            self._is_rank0 = True
            self.n_chips = int(n_chips) if n_chips else 1
        self.peak_flops_per_chip = (self.config.peak_flops_per_chip
                                    if self.config.peak_flops_per_chip is not None
                                    else detect_peak_flops_per_chip())
        self._flops_per_step: Any = _FLOPS_UNSET
        self._jsonl_fh = None
        self._unflushed = 0
        self._tracing = False
        self._profile_done = False  # the capture window fires at most once
        self.records_written = 0
        # requests/sec rate tracking for serving gauges (name -> (t, count))
        self._rates: Dict[str, Tuple[float, float]] = {}
        # host-side caches for the pull-based ops plane (monitor/metrics.py
        # populate_from_telemetry): the newest train-step record, the newest
        # gauges per prefix, and lifetime resilience-event counts — reading
        # them re-reads values this collector already assembled, so an ops
        # refresh can never trigger a device sync
        self.last_train_record: Optional[Dict[str, Any]] = None
        self.last_gauges: Dict[str, Dict[str, Any]] = {}
        self.resilience_counts: Dict[str, int] = {}

    # ------------------------------------------------------------- flops / mfu
    def wants_flops(self) -> bool:
        """True while the one-time train-step cost analysis is still pending."""
        return self.enabled and self._flops_per_step is _FLOPS_UNSET

    def set_flops_per_step(self, flops: Optional[float]) -> None:
        self._flops_per_step = float(flops) if flops else None

    @property
    def flops_per_step(self) -> Optional[float]:
        return None if self._flops_per_step is _FLOPS_UNSET else self._flops_per_step

    def _mfu(self, step_time_s: Optional[float]) -> Optional[float]:
        flops = self.flops_per_step
        if not flops or not step_time_s or not self.peak_flops_per_chip:
            return None
        return flops / step_time_s / (self.peak_flops_per_chip * self.n_chips)

    # ----------------------------------------------------------------- records
    def record_train_step(self, *, step: int, samples: int, loss: Optional[float] = None,
                          grad_norm: Optional[float] = None, lr: Optional[float] = None,
                          step_time_s: Optional[float] = None, tokens: Optional[int] = None,
                          extra: Optional[Dict[str, Any]] = None) -> Optional[Dict[str, Any]]:
        """One structured record per optimizer step; returns the record (None
        when disabled).  ``tokens`` is the global token count this step; when
        the batch has no sequence dim it defaults to one token per sample so
        tokens/sec degrades to samples/sec instead of going null."""
        if not self.enabled:
            return None
        tokens = int(tokens) if tokens else self.batch_size
        step_time_ms = step_time_s * 1e3 if step_time_s else None
        samples_per_sec = self.batch_size / step_time_s if step_time_s else None
        tokens_per_sec = tokens / step_time_s if step_time_s else None
        flops = self.flops_per_step
        record: Dict[str, Any] = {
            "kind": "train_step",
            "step": int(step),
            "samples": int(samples),
            "loss": loss,
            "grad_norm": grad_norm,
            "lr": lr,
            "step_time_ms": step_time_ms,
            "samples_per_sec": samples_per_sec,
            "tokens_per_sec": tokens_per_sec,
            "flops_per_step": flops,
            "tflops_per_sec": (flops / step_time_s / 1e12 if flops and step_time_s else None),
            "mfu": self._mfu(step_time_s),
            "hbm": device_memory_stats(),
            "timestamp": time.time(),
        }
        if extra:
            record.update(extra)
        self.last_train_record = record
        self._write_jsonl(record)
        return record

    def record_gauges(self, gauges: Dict[str, Any], step: int,
                      prefix: str = "Inference",
                      timestamp: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """Point-in-time gauges (scheduler/serving state) → monitor events and
        a ``kind: gauges`` JSONL record.  ``timestamp`` lets a caller on an
        injectable clock (the v2 serving engine under a FakeClock) stamp the
        record deterministically; None keeps the wall-clock default."""
        if not self.enabled:
            return None
        self.record_events([(f"{prefix}/{k}", float(v), int(step))
                            for k, v in gauges.items() if v is not None])
        record = {"kind": "gauges", "prefix": prefix, "step": int(step),
                  "timestamp": time.time() if timestamp is None else float(timestamp),
                  **gauges}
        # cache the GAUGES only, not the whole record — the ops adapter
        # exports every numeric cached key as a metric family, and the
        # record's step/timestamp bookkeeping must not become one
        self.last_gauges[prefix] = dict(gauges)
        self._write_jsonl(record)
        return record

    def record_resilience(self, event: str, *, step: int = 0, samples: int = 0,
                          **fields) -> Optional[Dict[str, Any]]:
        """Fault-path happenings (save retries, fallback loads, watchdog trips,
        preemption saves) → a ``kind: resilience`` JSONL record plus monitor
        events for the numeric fields, so recoveries are visible in the same
        stream as the steps they interrupt."""
        if not self.enabled:
            return None
        record = {"kind": "resilience", "event": event, "step": int(step),
                  "timestamp": time.time(), **fields}
        self.resilience_counts[event] = self.resilience_counts.get(event, 0) + 1
        self._write_jsonl(record)
        self.record_events([(f"Resilience/{event}/{k}", float(v), int(samples))
                            for k, v in fields.items()
                            if isinstance(v, (int, float)) and not isinstance(v, bool)])
        return record

    def record_trace(self, trace: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """One completed request-lifecycle trace (monitor/tracing.py
        RequestTracer) → a ``kind: trace`` JSONL record: uid, terminal
        status, span chain, SLO marks (ttft_s/e2e_s/queue_wait_s)."""
        if not self.enabled:
            return None
        record = {"kind": "trace", "timestamp": time.time(), **trace}
        self._write_jsonl(record)
        return record

    def record_events(self, events: List[Event]) -> None:
        """Fan events out to MonitorMaster (rank-0; no JSONL — events are the
        monitor-native shape, records are the JSONL-native shape)."""
        if not self.enabled or not events:
            return
        if self.monitor is not None and self._is_rank0:
            self.monitor.write_events(list(events))

    def rate(self, name: str, count: float) -> Optional[float]:
        """Per-second rate of a monotonically increasing counter between
        successive calls (None on the first observation of ``name``)."""
        now = time.perf_counter()
        prev = self._rates.get(name)
        self._rates[name] = (now, count)
        if prev is None or now <= prev[0]:
            return None
        return (count - prev[1]) / (now - prev[0])

    # ------------------------------------------------------------- JSONL sink
    def _write_jsonl(self, record: Dict[str, Any]) -> None:
        path = self.config.jsonl_path
        if path is None or not self._is_rank0:
            return
        if self._jsonl_fh is None:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._jsonl_fh = open(path, "a")
        self._jsonl_fh.write(json.dumps(record) + "\n")
        self.records_written += 1
        # buffered flush policy (ISSUE 6 satellite): the default of 1 keeps
        # the every-record durability tests rely on; high-rate trace streams
        # raise jsonl_flush_every so flushes amortize off the serve loop
        self._unflushed += 1
        if self._unflushed >= self.config.jsonl_flush_every:
            self._jsonl_fh.flush()
            self._unflushed = 0

    def flush_jsonl(self) -> None:
        """Force out any buffered JSONL records (close() does this too)."""
        if self._jsonl_fh is not None:
            self._jsonl_fh.flush()
        self._unflushed = 0

    # ------------------------------------------------- jax.profiler windows
    @property
    def tracing(self) -> bool:
        return self._tracing

    def profile_step_boundary(self, step: int) -> None:
        """Drive the configured capture window; call at the top of each train
        step with the CURRENT global step.  The window is [start, stop):
        start_trace fires entering any step inside the window (>= start, so a
        checkpoint-resumed run landing mid-window still captures), stop_trace
        entering ``profile_step_stop`` (or at close()); one window per run."""
        if not self.enabled:
            return
        start, stop = self.config.profile_step_start, self.config.profile_step_stop
        if self._tracing and stop >= 0 and step >= stop:
            self.stop_trace()
            self._profile_done = True
        if (not self._tracing and not self._profile_done and start >= 0
                and step >= start and (stop < 0 or step < stop)):
            self.start_trace()

    def serve_profile_begin(self) -> None:
        """Arm the serve-iteration capture window for one ``generate()`` call
        (ISSUE 16 satellite): the per-generate done-flag resets so every
        generate() can capture its own [start, stop) iteration window."""
        self._serve_profile_done = False

    def profile_serve_boundary(self, iteration: int) -> None:
        """Drive the serve-loop capture window; call at the top of each serve
        iteration with the CURRENT per-generate iteration index.  Same
        [start, stop) semantics as :meth:`profile_step_boundary`, but keyed on
        ``profile_serve_iteration_start/stop`` and re-armed per generate()."""
        if not self.enabled:
            return
        start = self.config.profile_serve_iteration_start
        stop = self.config.profile_serve_iteration_stop
        done = getattr(self, "_serve_profile_done", False)
        if self._tracing and stop >= 0 and iteration >= stop:
            self.stop_trace()
            self._serve_profile_done = True
        if (not self._tracing and not done and start >= 0
                and iteration >= start and (stop < 0 or iteration < stop)):
            self.start_trace()

    def serve_profile_end(self) -> None:
        """Close any serve window still open when generate() returns — one
        window per generate(), never a trace leaking across calls."""
        if (self.enabled and self._tracing
                and self.config.profile_serve_iteration_start >= 0):
            self.stop_trace()
            self._serve_profile_done = True

    def start_trace(self) -> bool:
        if self._tracing:
            return False
        try:
            import jax
            os.makedirs(self.config.profile_dir, exist_ok=True)
            jax.profiler.start_trace(self.config.profile_dir)
            self._tracing = True
            logger.info(f"telemetry: jax.profiler trace started -> {self.config.profile_dir}")
        except Exception as e:  # a failed trace must never kill training
            logger.warning(f"telemetry: start_trace failed: {e}")
        return self._tracing

    def stop_trace(self) -> None:
        if not self._tracing:
            return
        try:
            import jax
            jax.profiler.stop_trace()
            logger.info(f"telemetry: jax.profiler trace stopped ({self.config.profile_dir})")
        except Exception as e:
            logger.warning(f"telemetry: stop_trace failed: {e}")
        finally:
            self._tracing = False

    def step_annotation(self, step: int):
        """StepTraceAnnotation for the train step — the marker TensorBoard's
        profile tooling groups per-step stats by."""
        if not self.enabled:
            return contextlib.nullcontext()
        import jax
        return jax.profiler.StepTraceAnnotation("train_step", step_num=int(step))

    def annotation(self, name: str):
        """Named TraceAnnotation (batch-prep, checkpoint IO, eval, ...)."""
        if not self.enabled:
            return contextlib.nullcontext()
        import jax
        return jax.profiler.TraceAnnotation(name)

    # ---------------------------------------------------------------- teardown
    def close(self) -> None:
        self.stop_trace()
        if self._jsonl_fh is not None:
            self._jsonl_fh.close()  # close() flushes any buffered records
            self._jsonl_fh = None
        self._unflushed = 0

    def __del__(self):
        try:
            self.close()
        except Exception:  # dslint: disable=silent-except  # interpreter-shutdown teardown: logging/profiler may already be torn down, raising from __del__ only prints noise
            pass
