"""Experiment monitoring.

Analog of deepspeed/monitor/ (``Monitor`` ABC monitor.py:13, ``MonitorMaster:29``
fan-out to TensorBoard / W&B / CSV writers).  Events are ``(tag, value, step)``
triples; only process 0 writes (reference checks dist.get_rank()==0).
"""

import csv
import os
from typing import List, Tuple

from ..utils.logging import logger

Event = Tuple[str, float, int]


class Monitor:

    def write_events(self, events: List[Event]):
        raise NotImplementedError


class TensorBoardMonitor(Monitor):
    """Uses tensorboardX/torch SummaryWriter when importable, else disables
    itself (the env may not ship tensorboard)."""

    def __init__(self, config):
        self.enabled = False
        self.summary_writer = None
        try:
            from torch.utils.tensorboard import SummaryWriter  # type: ignore
            path = os.path.join(config.output_path or "runs", config.job_name)
            self.summary_writer = SummaryWriter(log_dir=path)
            self.enabled = True
        except Exception as e:
            logger.warning(f"TensorBoard monitor disabled: {e}", extra={"once": True})

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self.summary_writer.add_scalar(tag, value, step)
        self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, config):
        self.enabled = False
        try:
            import wandb  # type: ignore
            wandb.init(project=config.project, group=config.group, entity=config.team)
            self._wandb = wandb
            self.enabled = True
        except Exception as e:
            logger.warning(f"W&B monitor disabled: {e}", extra={"once": True})

    def write_events(self, events: List[Event]):
        if not self.enabled:
            return
        for tag, value, step in events:
            self._wandb.log({tag: value}, step=step)


class csvMonitor(Monitor):
    """CSV writer (reference monitor/csv_monitor.py) — one file per tag."""

    def __init__(self, config):
        self.output_path = os.path.join(config.output_path or "csv_logs", config.job_name)
        os.makedirs(self.output_path, exist_ok=True)
        self.enabled = True

    def write_events(self, events: List[Event]):
        for tag, value, step in events:
            fname = os.path.join(self.output_path, tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as fh:
                w = csv.writer(fh)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, value])


class MonitorMaster(Monitor):
    """Fan-out (reference monitor/monitor.py:29); rank-0 only."""

    def __init__(self, training_config):
        self.monitors: List[Monitor] = []
        import jax
        try:
            is_rank0 = jax.process_index() == 0
        except Exception:
            is_rank0 = True
        if not is_rank0:
            return
        mc = training_config.monitor_config
        tb = mc.tensorboard if mc else training_config.tensorboard
        wb = mc.wandb if mc else training_config.wandb
        cv = mc.csv_monitor if mc else training_config.csv_monitor
        if tb.enabled:
            self.monitors.append(TensorBoardMonitor(tb))
        if wb.enabled:
            self.monitors.append(WandbMonitor(wb))
        if cv.enabled:
            self.monitors.append(csvMonitor(cv))

    @property
    def enabled(self):
        return bool(self.monitors)

    def write_events(self, events: List[Event]):
        for m in self.monitors:
            m.write_events(events)
