"""Unified metrics registry — the export half of observability (ISSUE 11).

PRs 1-8 built rich telemetry, but every number lives in-process: gauges go to
JSONL/monitor writers, SLO histograms sit inside ``RequestTracer``, resilience
counters inside engines and supervisors.  The reference DeepSpeed ships a
``monitor/`` subsystem with pluggable PUSH backends (TensorBoard/WandB/CSV);
a serving fleet needs the PULL half: a standard registry of named
counters/gauges/histograms an HTTP endpoint can render as Prometheus text and
a router/aggregator can merge across ranks and worker restarts.

Three layers, all host-side (nothing here imports jax or numpy — dslint's
host-sync rule scans this file whole, like runtime/heartbeat.py, so a device
fetch sneaking into the ops plane is a lint error, not a scrape-time stall):

- :class:`MetricsRegistry` — named metric families (``counter`` | ``gauge`` |
  ``histogram``) with label sets.  Adapters POPULATE it by snapshotting host
  state the sources already own (:func:`populate_from_engine` reads the v2
  engine's ``ServeCounters``/admission/scheduler/tracer ints,
  :func:`populate_from_telemetry` the training collector's cached last
  record) — no hot path is re-instrumented and no population ever touches a
  device value.
- snapshot / restore — :meth:`MetricsRegistry.snapshot` is a JSON-safe dict
  (histograms carry their raw log-buckets, so cross-process merges stay
  EXACT) written atomically per rank by workers and read back tolerantly by
  supervisors (:mod:`.ops_server` owns the file IO).
- :class:`FleetAggregator` — merges per-rank snapshots into one fleet-level
  registry: counters and gauges keep a ``rank`` label, histograms fold into
  one fleet histogram via ``StreamingHistogram.merge`` (its first production
  caller), and a worker RESTART (generation bump resets the process's
  counters to zero) is absorbed by carrying the dead generation's last-seen
  totals — merged counters are monotone across restarts, which is the
  contract every Prometheus ``rate()`` over the fleet endpoint depends on.
"""

import math
import re
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .tracing import StreamingHistogram

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

LabelKey = Tuple[Tuple[str, str], ...]


def label_key(labels: Optional[Dict[str, str]]) -> LabelKey:
    """Canonical (sorted, stringified) form of a label set — the sample key."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _clone_histogram(hist: StreamingHistogram) -> StreamingHistogram:
    out = StreamingHistogram(hist.buckets_per_decade, hist.min_value)
    out.merge(hist)
    return out


class MetricFamily:
    """One named metric family: type, help text, and labeled samples.

    ``samples`` maps a canonical label tuple to either a float (counter /
    gauge) or a :class:`StreamingHistogram` copy (histogram) — a registry
    owns its histogram values (set_histogram clones), so a later mutation of
    the source never skews an already-collected snapshot.
    """

    def __init__(self, name: str, kind: str, help_text: str = ""):
        if not METRIC_NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r} (want "
                             f"[a-zA-Z_:][a-zA-Z0-9_:]*)")
        if kind not in (COUNTER, GAUGE, HISTOGRAM):
            raise ValueError(f"metric {name}: unknown type {kind!r}")
        self.name = name
        self.kind = kind
        self.help = str(help_text)
        self.samples: Dict[LabelKey, Any] = {}

    def validate_labels(self, labels: Optional[Dict[str, str]]) -> LabelKey:
        key = label_key(labels)
        for lname, _ in key:
            if not LABEL_NAME_RE.match(lname):
                raise ValueError(f"metric {self.name}: invalid label name {lname!r}")
            if lname == "le":
                raise ValueError(f"metric {self.name}: label 'le' is reserved "
                                 f"for histogram buckets")
        return key


class MetricsRegistry:
    """Named counter/gauge/histogram families with labels.

    Values are SET, not incremented: the ops plane snapshots lifetime
    counters the sources already maintain (``ServeCounters.host_syncs``,
    ``admission.shed_total``, ...) instead of double-counting events through
    a second instrumentation path.  A counter set to a smaller value than it
    already holds raises — catching exactly the bug class (a source counter
    that resets without a generation bump) that silently corrupts every
    downstream ``rate()``.  Restart-induced resets are legal and handled one
    layer up (:class:`FleetAggregator` carries totals across generations).
    """

    def __init__(self, namespace: str = "dstpu", generation: int = 0):
        self.namespace = str(namespace)
        self.generation = int(generation)
        self.families: Dict[str, MetricFamily] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    # ------------------------------------------------------------- population
    def family(self, name: str, kind: str, help_text: str = "") -> MetricFamily:
        fam = self.families.get(name)
        if fam is None:
            fam = MetricFamily(name, kind, help_text)
            self.families[name] = fam
        elif fam.kind != kind:
            raise ValueError(f"metric {name} already registered as {fam.kind}, "
                             f"not {kind}")
        if help_text and not fam.help:
            fam.help = str(help_text)
        return fam

    def set_counter(self, name: str, value: float, *,
                    labels: Optional[Dict[str, str]] = None,
                    help_text: str = "") -> None:
        value = float(value)
        if value < 0 or not math.isfinite(value):
            raise ValueError(f"counter {name}: value must be finite and >= 0, "
                             f"got {value}")
        fam = self.family(name, COUNTER, help_text)
        key = fam.validate_labels(labels)
        prev = fam.samples.get(key, 0.0)
        if value < prev:
            raise ValueError(
                f"counter {name}{dict(key)} went backwards ({prev} -> {value}) "
                f"within one generation — a source counter reset without a "
                f"restart; wire the reset through a generation bump so the "
                f"fleet aggregator can carry the old total")
        fam.samples[key] = value

    def set_gauge(self, name: str, value: float, *,
                  labels: Optional[Dict[str, str]] = None,
                  help_text: str = "") -> None:
        fam = self.family(name, GAUGE, help_text)
        fam.samples[fam.validate_labels(labels)] = float(value)

    def set_histogram(self, name: str, hist: StreamingHistogram, *,
                      labels: Optional[Dict[str, str]] = None,
                      help_text: str = "") -> None:
        fam = self.family(name, HISTOGRAM, help_text)
        fam.samples[fam.validate_labels(labels)] = _clone_histogram(hist)

    # ------------------------------------------------------------- collection
    def register_collector(self, fn: Callable[["MetricsRegistry"], None]) -> None:
        """``fn(registry)`` re-populates some families; run by :meth:`collect`.

        Collectors run on the OWNING thread (the serve loop / agent poll
        loop), never from a scrape handler — the HTTP side serves pre-rendered
        cached text, so a scrape can never execute source-reading code."""
        self._collectors.append(fn)

    def collect(self) -> Dict[str, MetricFamily]:
        for fn in self._collectors:
            fn(self)
        return self.families

    # ------------------------------------------------------- snapshot / merge
    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe deep dump: the per-rank exchange format.  Histograms
        carry raw buckets (not quantiles) so a cross-process merge is exact —
        quantiles of the merged histogram equal quantiles over the union of
        the original samples."""
        fams: Dict[str, Any] = {}
        for name, fam in self.families.items():
            samples = []
            for key, value in fam.samples.items():
                entry: Dict[str, Any] = {"labels": dict(key)}
                if fam.kind == HISTOGRAM:
                    entry["histogram"] = {
                        "buckets_per_decade": value.buckets_per_decade,
                        "min_value": value.min_value,
                        "counts": {str(i): n for i, n in value.counts.items()},
                        "count": value.count,
                        "total": value.total,
                        "max": value.max_seen,
                    }
                else:
                    entry["value"] = value
                samples.append(entry)
            fams[name] = {"type": fam.kind, "help": fam.help, "samples": samples}
        return {"namespace": self.namespace, "generation": self.generation,
                "families": fams}

    @staticmethod
    def _histogram_from_snapshot(h: Dict[str, Any]) -> StreamingHistogram:
        hist = StreamingHistogram(int(h["buckets_per_decade"]),
                                  float(h["min_value"]))
        hist.counts = {int(i): int(n) for i, n in h.get("counts", {}).items()}
        hist.count = int(h.get("count", 0))
        hist.total = float(h.get("total", 0.0))
        hist.max_seen = h.get("max")
        return hist

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "MetricsRegistry":
        reg = cls(namespace=snap.get("namespace", "dstpu"),
                  generation=int(snap.get("generation", 0)))
        for name, fam in snap.get("families", {}).items():
            for entry in fam.get("samples", []):
                labels = entry.get("labels") or None
                if fam["type"] == HISTOGRAM:
                    reg.set_histogram(name, cls._histogram_from_snapshot(
                        entry["histogram"]), labels=labels, help_text=fam.get("help", ""))
                elif fam["type"] == COUNTER:
                    reg.set_counter(name, float(entry["value"]), labels=labels,
                                    help_text=fam.get("help", ""))
                else:
                    reg.set_gauge(name, float(entry["value"]), labels=labels,
                                  help_text=fam.get("help", ""))
        return reg


class FleetAggregator:
    """Merge per-rank registry snapshots into one fleet registry, carrying
    counters (and histogram contents) across worker restarts.

    A supervised worker that crashes and restarts comes back with all of its
    process-lifetime counters at zero; serving its raw post-restart values
    would make every fleet counter jump backwards — poison for monitoring
    that computes rates.  The aggregator watches each rank's ``generation``
    stamp: when it advances, the dead generation's last-seen counter totals
    (and histogram buckets) fold into a per-rank CARRY, and the merged value
    becomes ``carry + current`` — monotone across any number of restarts.

    Gauges are point-in-time state and simply take the newest value per rank.
    Counters and gauges keep a ``rank`` label in the merged view; histograms
    merge rank-blind into one fleet histogram per family+label set
    (``StreamingHistogram.merge``), because fleet SLO percentiles are only
    meaningful over the union of samples.
    """

    def __init__(self):
        # rank -> generation of the newest absorbed snapshot
        self._generation: Dict[int, int] = {}
        # rank -> {(family, labelkey): last seen counter value this generation}
        self._last_counters: Dict[int, Dict[Tuple[str, LabelKey], float]] = {}
        # rank -> {(family, labelkey): carried total from dead generations}
        self._carry_counters: Dict[int, Dict[Tuple[str, LabelKey], float]] = {}
        # same split for histograms (carried = merged dead-generation buckets)
        self._last_hists: Dict[int, Dict[Tuple[str, LabelKey], StreamingHistogram]] = {}
        self._carry_hists: Dict[int, Dict[Tuple[str, LabelKey], StreamingHistogram]] = {}
        # rank -> {(family, labelkey): value} newest gauges
        self._gauges: Dict[int, Dict[Tuple[str, LabelKey], float]] = {}
        # family metadata (help/type) seen newest-wins
        self._meta: Dict[str, Tuple[str, str]] = {}
        self.absorbed_total = 0

    def _roll_generation(self, rank: int) -> None:
        carry = self._carry_counters.setdefault(rank, {})
        for key, value in self._last_counters.get(rank, {}).items():
            carry[key] = carry.get(key, 0.0) + value
        hcarry = self._carry_hists.setdefault(rank, {})
        for key, hist in self._last_hists.get(rank, {}).items():
            held = hcarry.get(key)
            if held is None:
                hcarry[key] = hist
            elif (held.buckets_per_decade == hist.buckets_per_decade
                  and held.min_value == hist.min_value):
                held.merge(hist)
            else:  # a restart changed the bucket shape: the old samples can't
                hcarry[key] = hist  # merge exactly — keep the newest shape
        self._last_counters[rank] = {}
        self._last_hists[rank] = {}

    def absorb(self, rank: int, snapshot: Dict[str, Any]) -> None:
        """Fold one rank's registry snapshot in (newest wins per rank)."""
        rank = int(rank)
        generation = int(snapshot.get("generation", 0))
        prev = self._generation.get(rank)
        if prev is not None and generation > prev:
            self._roll_generation(rank)
        if prev is None or generation >= prev:
            self._generation[rank] = generation
        elif generation < prev:
            return  # a stale straggler snapshot must not roll anything back
        reg = MetricsRegistry.from_snapshot(snapshot)
        self.absorbed_total += 1
        counters = self._last_counters.setdefault(rank, {})
        hists = self._last_hists.setdefault(rank, {})
        gauges = self._gauges.setdefault(rank, {})
        for name, fam in reg.families.items():
            self._meta[name] = (fam.kind, fam.help)
            for key, value in fam.samples.items():
                if fam.kind == COUNTER:
                    counters[(name, key)] = float(value)
                elif fam.kind == HISTOGRAM:
                    hists[(name, key)] = value
                else:
                    gauges[(name, key)] = float(value)

    def ranks(self) -> List[int]:
        return sorted(self._generation)

    def registry(self, namespace: str = "dstpu") -> MetricsRegistry:
        """The merged fleet view as a fresh registry (render-ready)."""
        reg = MetricsRegistry(namespace=namespace)
        for rank in self.ranks():
            rl = {"rank": str(rank)}
            totals: Dict[Tuple[str, LabelKey], float] = dict(
                self._carry_counters.get(rank, {}))
            for key, value in self._last_counters.get(rank, {}).items():
                totals[key] = totals.get(key, 0.0) + value
            for (name, key), value in sorted(totals.items()):
                kind, help_text = self._meta.get(name, (COUNTER, ""))
                reg.set_counter(name, value, labels={**dict(key), **rl},
                                help_text=help_text)
            for (name, key), value in sorted(self._gauges.get(rank, {}).items()):
                _, help_text = self._meta.get(name, (GAUGE, ""))
                reg.set_gauge(name, value, labels={**dict(key), **rl},
                              help_text=help_text)
        # histograms: rank-blind fleet merge (the StreamingHistogram.merge
        # production call-site fleet aggregation was designed for).  On a
        # bucket-shape conflict, LIVE data wins: a current-generation
        # histogram whose shape differs from the carried one (a restart
        # changed the histogram config) replaces it — same newest-wins
        # resolution as _roll_generation, so a reconfigured worker's fresh
        # SLO samples never silently vanish behind dead-generation buckets
        merged: Dict[Tuple[str, LabelKey], StreamingHistogram] = {}
        for source, live in ((self._carry_hists, False), (self._last_hists, True)):
            for rank in sorted(source):
                for key, hist in sorted(source[rank].items()):
                    held = merged.get(key)
                    if held is None:
                        merged[key] = _clone_histogram(hist)
                    elif (held.buckets_per_decade == hist.buckets_per_decade
                          and held.min_value == hist.min_value):
                        held.merge(hist)
                    elif live:
                        merged[key] = _clone_histogram(hist)
        for (name, key), hist in sorted(merged.items()):
            _, help_text = self._meta.get(name, (HISTOGRAM, ""))
            reg.set_histogram(name, hist, labels=dict(key) or None,
                              help_text=help_text)
        return reg


# ==========================================================================
# Adapters: snapshot the state PRs 1-8 already maintain into a registry.
# All reads are host-native python ints/floats the sources own — populating
# a registry can never trigger a device sync (the same contract stamped on
# runtime/heartbeat.py, and enforced by the same dslint whole-file scan).
# ==========================================================================

def populate_from_engine(reg: MetricsRegistry, engine) -> None:
    """v2 serving engine → registry: ServeCounters, admission/scheduler/
    manager counters and gauges, fault-tolerance section, and the tracer's
    SLO histograms (TTFT/TBT/e2e/queue-wait)."""
    c = engine.counters
    counter_help = {
        "host_syncs": "device->host materializations in the serve loop",
        "dispatches": "device program launches (forward/pick/burst/scatter)",
        "uploads": "host->device transfers issued",
        "upload_ints": "int32 elements moved host->device",
        "compiles": "distinct compiled programs (bucket shapes)",
        "loop_iterations": "serve-loop iterations observed",
        "step_tokens": "tokens emitted via stepwise decode",
        "burst_tokens": "tokens emitted via fused decode bursts",
        "flushes": "pipeline flushes forced by wave boundaries",
    }
    for field, help_text in counter_help.items():
        reg.set_counter(f"{reg.namespace}_fastpath_{field}_total",
                        getattr(c, field), help_text=help_text)
    reg.set_counter(f"{reg.namespace}_serving_shed_total",
                    engine.admission.shed_total,
                    help_text="requests load-shed at the admission door")
    # structured backpressure (ISSUE 17): per-code shed counters plus the
    # door's own latest retry_after_s estimate — a fleet router (or client)
    # backs off for the hinted interval instead of guessing
    for code, count in sorted(engine.admission.shed_by_code.items()):
        reg.set_counter(f"{reg.namespace}_serving_shed_reason_total", count,
                        labels={"code": code},
                        help_text="requests shed, by structured reason code "
                                  "(sums to serving_shed_total)")
    for code, hint in sorted(engine.admission.last_retry_after.items()):
        reg.set_gauge(f"{reg.namespace}_serving_shed_retry_after_seconds",
                      hint, labels={"code": code},
                      help_text="latest retry_after_s backpressure hint "
                                "attached to a shed of this code")
    reg.set_counter(f"{reg.namespace}_serving_preempted_total",
                    engine.scheduler.preempted_total,
                    help_text="KV-pressure preemptions (incl. exhausted evictions)")
    reg.set_counter(f"{reg.namespace}_serving_deadline_expired_total",
                    engine._deadline_expired_total,
                    help_text="requests evicted past their TTL deadline")
    reg.set_counter(f"{reg.namespace}_serving_completed_total",
                    engine.manager.completed_requests,
                    help_text="requests retired complete")
    reg.set_counter(f"{reg.namespace}_serving_failed_total",
                    engine.manager.failed_requests,
                    help_text="requests retired failed")
    reg.set_counter(f"{reg.namespace}_serving_stalls_total",
                    engine.stalls_total,
                    help_text="progress-watchdog trips (lifetime)")
    reg.set_counter(f"{reg.namespace}_scheduler_steps_total",
                    engine.scheduler.steps,
                    help_text="SplitFuse scheduler steps run")
    reg.set_gauge(f"{reg.namespace}_serving_live_seqs",
                  len(engine.manager.live_uids()),
                  help_text="live (unfinished) sequences in the state manager")
    reg.set_gauge(f"{reg.namespace}_serving_queue_depth",
                  len(engine.admission),
                  help_text="tickets waiting in the admission queue")
    # ---- KV-pool families, unified under ONE serving_kv_* namespace
    # (ISSUE 12 satellite): the scheduler's and decode_burst's kv-adjacent
    # gauges used to spell the pool three ways (serving_free_kv_blocks vs
    # serving_kv_utilization vs scheduler_kv_block_utilization).  Canonical
    # names only — the deprecated aliases (serving_free_kv_blocks,
    # scheduler_kv_block_utilization) were kept one release and removed in
    # ISSUE 13 (see README "KV-pool observability").
    ns_kv = f"{reg.namespace}_serving_kv"
    reg.set_gauge(f"{ns_kv}_free_blocks",
                  engine.manager.allocator.free_blocks,
                  help_text="free blocks in the paged KV pool")
    reg.set_gauge(f"{ns_kv}_utilization",
                  engine.manager.kv_utilization(),
                  help_text="paged KV pool utilization [0, 1]")
    # ---- realized copy-on-write prefix caching (ISSUE 13): the tree's
    # lifetime counters next to the observatory's counterfactual families
    # below — agreement between the two is the cache working as predicted
    prefix_cache = getattr(engine.manager, "prefix_cache", None)
    if prefix_cache is not None:
        reg.set_counter(f"{ns_kv}_prefix_hits_total",
                        prefix_cache.hit_blocks_total,
                        help_text="prompt blocks served from the prefix tree "
                                  "(read-only shared mappings + CoW copies)")
        reg.set_counter(f"{ns_kv}_prefill_tokens_saved_total",
                        prefix_cache.tokens_saved_total,
                        help_text="prefill tokens skipped by mapping shared "
                                  "prefix blocks (REALIZED; the counterfactual "
                                  "twin is serving_kv_prefix_tokens_saved_total)")
        reg.set_gauge(f"{ns_kv}_prefix_realized_hit_rate",
                      prefix_cache.realized_hit_rate(),
                      help_text="shared-or-copied blocks over all full prompt "
                                "blocks (lifetime) — read next to the "
                                "counterfactual serving_kv_prefix_hit_rate")
        reg.set_counter(f"{ns_kv}_prefix_cow_copies_total",
                        prefix_cache.cow_copies_total,
                        help_text="copy-on-write block copies (prompts cached "
                                  "to their last token)")
        reg.set_counter(f"{ns_kv}_prefix_deferrals_total",
                        prefix_cache.deferrals_total,
                        help_text="prefill chunks deferred one step onto a "
                                  "block another scheduled request was "
                                  "computing")
        reg.set_gauge(f"{ns_kv}_prefix_tree_entries",
                      len(prefix_cache.entries),
                      help_text="shareable fully-computed prompt blocks "
                                "currently in the prefix tree")
    # block-level observability (ISSUE 12): census, counterfactual prefix-
    # cache opportunity, capacity forecast — all host ints the engine's
    # kv_obs already assembled (absent => kv observability disabled)
    kv_obs = getattr(engine, "kv_obs", None)
    if kv_obs is not None:
        census, fc, prefix = kv_obs.census, kv_obs.forecaster, kv_obs.prefix
        reg.set_gauge(f"{ns_kv}_allocated_blocks", census.allocated_blocks,
                      help_text="census-owned blocks in the paged KV pool")
        reg.set_gauge(f"{ns_kv}_shared_blocks", census.shared_blocks(),
                      help_text="blocks currently mapped by more than one "
                                "sequence (copy-on-write prefix sharing)")
        reg.set_gauge(f"{ns_kv}_fragmentation_tokens",
                      census.fragmentation_tokens(),
                      help_text="allocated-but-unfilled token slots "
                                "(block-granularity + prefill/burst headroom)")
        reg.set_counter(f"{ns_kv}_blocks_allocated_total",
                        census.blocks_allocated_total,
                        help_text="KV blocks allocated (lifetime)")
        reg.set_counter(f"{ns_kv}_blocks_freed_total",
                        census.blocks_freed_total,
                        help_text="KV blocks freed (lifetime)")
        reg.set_histogram(f"{ns_kv}_block_age_steps", census.age_histogram(),
                          help_text="serve steps since each live block was "
                                    "allocated (a fused burst of k counts k)")
        reg.set_histogram(f"{ns_kv}_block_idle_steps", census.idle_histogram(),
                          help_text="serve steps since each live block was "
                                    "last written (cold-block signal)")
        reg.set_histogram(f"{ns_kv}_blocks_per_request",
                          census.blocks_per_request,
                          help_text="peak blocks held per retired request")
        reg.set_gauge(f"{ns_kv}_prefix_duplicate_blocks",
                      prefix.last_report["duplicate_blocks"],
                      help_text="duplicate prompt token-blocks across "
                                "live+admitted requests (last serve pass)")
        reg.set_gauge(f"{ns_kv}_prefix_hit_rate",
                      prefix.last_report["hit_rate"],
                      help_text="counterfactual prefix-cache hit-rate "
                                "(last serve pass)")
        reg.set_counter(f"{ns_kv}_prefix_tokens_saved_total",
                        prefix.prefill_tokens_saved_total,
                        help_text="prefill tokens a block-granular prefix "
                                  "cache would have saved (lifetime)")
        reg.set_counter(f"{ns_kv}_prefix_passes_total", prefix.passes_total,
                        help_text="PrefixObservatory passes run")
        reg.set_gauge(f"{ns_kv}_alloc_rate_blocks_per_step", fc.alloc_rate,
                      help_text="EWMA block allocation rate per serve step")
        reg.set_gauge(f"{ns_kv}_free_rate_blocks_per_step", fc.free_rate,
                      help_text="EWMA block free rate per serve step")
        ste = fc.steps_to_exhaustion()
        if ste is not None:
            # absent while the pool is not trending toward exhaustion — an
            # inf gauge would render fine on /metrics but poison the per-rank
            # JSON exchange files (json.dumps emits the non-RFC token
            # Infinity); absence is the idiomatic "no prediction"
            reg.set_gauge(f"{ns_kv}_steps_to_exhaustion", ste,
                          help_text="forecast serve steps until the KV pool "
                                    "exhausts at current net consumption "
                                    "(absent while not trending toward "
                                    "exhaustion) — read next to "
                                    "serving_shed_total/preempted_total")
        else:
            # the ops registry persists across refreshes: a gauge set while
            # the pool was trending must not linger once the prediction
            # clears, so the family is dropped, not left stale
            reg.families.pop(f"{ns_kv}_steps_to_exhaustion", None)
        reg.set_gauge(f"{ns_kv}_under_pressure",
                      1.0 if kv_obs.under_pressure else 0.0,
                      help_text="1 while steps-to-exhaustion is below the "
                                "configured pressure threshold")
        reg.set_counter(f"{ns_kv}_invariant_checks_total",
                        kv_obs.invariant_checks_total,
                        help_text="census-vs-allocator partition checks run")
    # scheduler per-step gauges (PR 1): queue depth / token occupancy / ...
    for key, value in engine.scheduler.last_gauges.items():
        if key == "preempted_total":
            continue  # already exported as a counter above
        if key == "kv_block_utilization":
            # canonical spelling under the serving_kv_* namespace (the
            # scheduler_-prefixed alias served its one deprecation release
            # and was removed in ISSUE 13)
            reg.set_gauge(f"{ns_kv}_block_utilization", value,
                          help_text="paged KV pool utilization at the last "
                                    "scheduled step")
            continue
        reg.set_gauge(f"{reg.namespace}_scheduler_{key}", value,
                      help_text="SplitFuse scheduler per-step gauge")
    # fault tolerance (PR 8): restart/recovery counters + journal state
    ft = engine._fault_tolerance_snapshot()
    reg.set_counter(f"{reg.namespace}_serving_restarts_total",
                    ft["restarts_total"],
                    help_text="supervised engine restarts")
    reg.set_counter(f"{reg.namespace}_serving_recovered_requests_total",
                    ft["recovered_requests_total"],
                    help_text="requests re-admitted with a journaled prefix")
    reg.set_gauge(f"{reg.namespace}_serving_degraded",
                  1.0 if ft["degraded"] else 0.0,
                  help_text="1 when the supervisor degraded to drain-only mode")
    reg.set_gauge(f"{reg.namespace}_serving_journal_bytes", ft["journal_bytes"],
                  help_text="durable request-journal size on disk")
    # SLO latency histograms (PR 6): the tracer's streaming histograms.
    # queue_wait fills even with span tracing off; ttft/tbt/e2e fill once
    # serving_tracing.enabled is set — empty histograms still render
    # (count 0), so dashboards see the family exists.
    hist_help = {
        "ttft": "time to first token (submit -> first host-visible token)",
        "tbt": "time between tokens (burst of k -> k samples of gap/k)",
        "e2e": "end-to-end request latency (completed requests)",
        "queue_wait": "admission-queue wait",
    }
    for name, hist in engine.tracer.histograms().items():
        reg.set_histogram(f"{reg.namespace}_request_{name}_seconds", hist,
                          help_text=hist_help[name])
    # serving performance observatory (ISSUE 16): per-phase wall-time
    # histograms, compile provenance counters, warm-recompile counters, and
    # the live roofline gauges — all host-side values the engine's perf
    # instruments already hold (the ledger/roofline sections export even with
    # the phase profiler off; phase + roofline-rate families need it on)
    profiler = getattr(engine, "phase_profiler", None)
    if profiler is not None:
        for phase, hist in profiler.histograms().items():
            reg.set_histogram(f"{reg.namespace}_serving_phase_seconds", hist,
                              labels={"phase": phase},
                              help_text="serve-iteration wall time attributed "
                                        "per phase (spans sum to the full "
                                        "iteration wall)")
        if profiler.enabled:
            reg.set_counter(f"{reg.namespace}_serving_phase_iterations_total",
                            profiler.iterations,
                            help_text="serve iterations the phase profiler "
                                      "attributed")
    ledger = getattr(engine, "ledger", None)
    if ledger is not None:
        for site, classes in sorted(ledger.by_site.items()):
            for cls, count in sorted(classes.items()):
                reg.set_counter(f"{reg.namespace}_serving_compiles_total",
                                count, labels={"site": site, "class": cls},
                                help_text="XLA compiles attributed by jit "
                                          "site and class (prewarmed/cold/"
                                          "warm) — sums to "
                                          "fastpath_compiles_total")
            # a zero per seen site keeps the recompile family present and
            # alert-able before the first (hopefully never) warm recompile
            reg.set_counter(f"{reg.namespace}_serving_recompiles_total",
                            ledger.warm_by_site.get(site, 0),
                            labels={"site": site},
                            help_text="warm recompiles: a bucket key rebuilt "
                                      "after being seen at its site (runtime "
                                      "twin of dslint's recompile-risk rule)")
    roofline = getattr(engine, "roofline", None)
    if roofline is not None and profiler is not None and profiler.enabled:
        for name, value in roofline.gauges(profiler.wall_s).items():
            reg.set_gauge(f"{reg.namespace}_{name}", value,
                          help_text={
                              "serving_hbm_bytes_per_token":
                                  "HBM bytes accessed per served token "
                                  "(cost_analysis over dispatched buckets)",
                              "serving_roofline_fraction":
                                  "achieved HBM bandwidth over the chip spec "
                                  "(live twin of BENCH's "
                                  "hbm_stream_fraction_of_spec)",
                              "serving_model_flops_utilization":
                                  "achieved FLOPs over peak (0 until "
                                  "serving_perf.peak_flops_per_chip is set)",
                          }[name])
        reg.set_counter(f"{reg.namespace}_serving_uncosted_dispatches_total",
                        roofline.uncosted_dispatches,
                        help_text="dispatches of buckets with no captured "
                                  "cost analysis (roofline blind spots)")
    # multi-tenant QoS (ISSUE 19): per-tenant admission, token, shed and
    # resident-KV families plus per-tenant SLO histograms — present only
    # when the policy layer is armed (serving_qos.enabled), so a QoS-off
    # scrape stays byte-identical to the pre-QoS exposition
    qos = getattr(engine, "qos", None)
    if qos is not None:
        for (tenant, cls), count in sorted(qos.admitted_by_tenant.items()):
            reg.set_counter(f"{reg.namespace}_serving_tenant_admitted_total",
                            count, labels={"tenant": tenant, "class": cls},
                            help_text="requests admitted, by tenant and "
                                      "service class")
        for tenant, count in sorted(qos.tokens_by_tenant.items()):
            reg.set_counter(f"{reg.namespace}_serving_tenant_tokens_total",
                            count, labels={"tenant": tenant},
                            help_text="prompt tokens charged against the "
                                      "tenant's rate quota at admission")
        for (tenant, code), count in sorted(qos.shed_by_tenant.items()):
            reg.set_counter(f"{reg.namespace}_serving_tenant_shed_total",
                            count, labels={"tenant": tenant, "code": code},
                            help_text="requests shed at the QoS door, by "
                                      "tenant and structured reason code")
        for tenant, hint in sorted(qos.last_retry_after_by_tenant.items()):
            reg.set_gauge(
                f"{reg.namespace}_serving_tenant_retry_after_seconds",
                hint, labels={"tenant": tenant},
                help_text="latest quota-derived retry hint per tenant "
                          "(time until the token bucket refills)")
        for tenant, blocks in sorted(engine.manager.tenant_block_usage().items()):
            reg.set_gauge(f"{reg.namespace}_serving_tenant_kv_blocks",
                          blocks, labels={"tenant": tenant},
                          help_text="KV blocks resident per tenant (live "
                                    "sequences only)")
        tenant_hist_help = {
            "ttft": "per-tenant time to first token",
            "e2e": "per-tenant end-to-end request latency",
        }
        for (tenant, name), hist in sorted(engine.tracer.tenant_histograms()
                                           .items()):
            reg.set_histogram(
                f"{reg.namespace}_serving_tenant_{name}_seconds", hist,
                labels={"tenant": tenant},
                help_text=tenant_hist_help[name])
    # speculative decoding (ISSUE 20): proposal/acceptance counters, live
    # acceptance gauge, and the tokens-per-verify histogram — present only
    # when the section is armed (serving_spec_decode.enabled), so a spec-off
    # scrape stays byte-identical to the pre-spec exposition
    spec = getattr(engine, "spec_stats", None)
    if spec is not None:
        reg.set_counter(f"{reg.namespace}_serving_spec_proposed_total",
                        spec.proposed_total,
                        help_text="draft tokens proposed to the verifier")
        reg.set_counter(f"{reg.namespace}_serving_spec_accepted_total",
                        spec.accepted_total,
                        help_text="draft tokens accepted by rejection "
                                  "sampling (bonus/corrected tokens excluded)")
        reg.set_counter(f"{reg.namespace}_serving_spec_rounds_total",
                        spec.rounds_total,
                        help_text="draft/verify rounds dispatched")
        reg.set_counter(f"{reg.namespace}_serving_spec_fallback_rounds_total",
                        spec.fallback_rounds_total,
                        help_text="rounds that declined to speculate and fell "
                                  "back to the plain fused burst")
        reg.set_gauge(f"{reg.namespace}_serving_spec_acceptance",
                      spec.acceptance_rate(),
                      help_text="lifetime draft-token acceptance rate [0, 1] "
                                "— the adaptive-k controller steers its EWMA "
                                "twin of this")
        # the per-round run lengths live as exact small-int counts on the
        # engine; rendered as a mergeable streaming histogram like every
        # other latency/size family (direct bucket fill — same idiom as
        # MetricsRegistry._histogram_from_snapshot)
        hist = StreamingHistogram()
        for length, n in sorted(spec.tokens_per_verify.items()):
            idx = hist._index(float(length))
            hist.counts[idx] = hist.counts.get(idx, 0) + int(n)
            hist.count += int(n)
            hist.total += float(length) * int(n)
            if hist.max_seen is None or float(length) > hist.max_seen:
                hist.max_seen = float(length)
        reg.set_histogram(f"{reg.namespace}_serving_spec_tokens_per_verify",
                          hist,
                          help_text="tokens emitted per verify round per "
                                    "sequence (accepted prefix + 1)")


def populate_from_telemetry(reg: MetricsRegistry, collector) -> None:
    """Training TelemetryCollector → registry: the cached last train-step
    record (loss/step-time/throughput/MFU), cached gauge records per prefix,
    and the lifetime resilience-event counters — all host-side values the
    collector already assembled for its JSONL/monitor fan-out."""
    record = collector.last_train_record
    if record:
        # absolute training position as GAUGES, matching the engine
        # adapter's spelling: the record's step is the restored global step,
        # which survives checkpoint resumes — counter semantics (and the
        # fleet carry that comes with them) belong to per-process work, which
        # only the engine knows (runtime/engine.py _populate_ops_registry)
        reg.set_gauge(f"{reg.namespace}_train_global_step",
                      record.get("step", 0),
                      help_text="absolute training step (checkpoint position)")
        reg.set_gauge(f"{reg.namespace}_train_global_samples",
                      record.get("samples", 0),
                      help_text="absolute samples consumed (checkpoint position)")
        gauge_fields = {
            "loss": "last training loss",
            "grad_norm": "last gradient norm",
            "lr": "last learning rate",
            "step_time_ms": "last step wall-time (ms)",
            "samples_per_sec": "training throughput (samples/s)",
            "tokens_per_sec": "training throughput (tokens/s)",
            "tflops_per_sec": "achieved TFLOP/s",
            "mfu": "model FLOPs utilization [0, 1]",
        }
        for field, help_text in gauge_fields.items():
            value = record.get(field)
            if value is not None:
                reg.set_gauge(f"{reg.namespace}_train_{field}", value,
                              help_text=help_text)
        hbm = record.get("hbm") or {}
        for field, value in hbm.items():
            if value is not None:
                reg.set_gauge(f"{reg.namespace}_hbm_{field}", value,
                              help_text="device memory stats (bytes)")
    for prefix, gauges in collector.last_gauges.items():
        slug = re.sub(r"[^a-zA-Z0-9_]", "_", prefix.lower())
        for key, value in gauges.items():
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                reg.set_gauge(f"{reg.namespace}_{slug}_{key}", value,
                              help_text=f"gauge from the {prefix} stream")
    for event, count in sorted(collector.resilience_counts.items()):
        reg.set_counter(f"{reg.namespace}_resilience_events_total", count,
                        labels={"event": event},
                        help_text="resilience events (save retries, fallbacks, "
                                  "watchdog trips, shed/preempt/restart)")


def populate_from_supervisor(reg: MetricsRegistry, supervisor) -> None:
    """ServingSupervisor lifecycle → registry (the process-level view the
    per-engine adapter can't see: restart budget, degradation, generations)."""
    reg.set_counter(f"{reg.namespace}_supervisor_restarts_total",
                    supervisor.restarts_total,
                    help_text="worker restarts performed by the supervisor")
    reg.set_counter(f"{reg.namespace}_supervisor_generations_total",
                    supervisor.generations,
                    help_text="worker generations spawned")
    reg.set_counter(f"{reg.namespace}_supervisor_recovered_requests_total",
                    supervisor.recovered_requests_total,
                    help_text="requests recovered across restarts")
    reg.set_gauge(f"{reg.namespace}_supervisor_degraded",
                  1.0 if supervisor.degraded else 0.0,
                  help_text="1 when the restart budget degraded to drain-only")


def populate_from_router(reg: MetricsRegistry, router) -> None:
    """FleetRouter → registry: the fleet-level view no single replica can
    see — routing distribution, prefix-affinity effectiveness, shed
    re-routes and backoff, failover migrations, and the zero-lost-requests
    invariant — merged into the same registry the FleetAggregator already
    filled with replica-carried counters (ISSUE 17)."""
    ns = reg.namespace
    for index, count in enumerate(router.routed_total):
        reg.set_counter(f"{ns}_router_routed_total", count,
                        labels={"replica": str(index)},
                        help_text="requests routed, by destination replica")
    reg.set_counter(f"{ns}_router_affinity_routed_total",
                    router.affinity_routed_total,
                    help_text="requests routed to their prefix-affinity home")
    reg.set_counter(f"{ns}_router_affinity_overridden_total",
                    router.affinity_overridden_total,
                    help_text="requests whose affinity home was unhealthy or "
                              "overloaded (fell back to least-loaded)")
    reg.set_counter(f"{ns}_router_reroutes_total", router.reroutes_total,
                    help_text="retryable sheds re-routed to another replica")
    reg.set_counter(f"{ns}_router_backoff_seconds_total",
                    router.backoff_seconds_total,
                    help_text="cumulative shed-backoff wait")
    reg.set_counter(f"{ns}_router_migrations_total", router.migrations_total,
                    help_text="replicas drained after restart-budget "
                              "exhaustion (journaled work migrated)")
    reg.set_counter(f"{ns}_router_migrated_requests_total",
                    router.migrated_requests_total,
                    help_text="in-flight journal entries transplanted to a "
                              "healthy replica")
    reg.set_counter(f"{ns}_router_adopted_from_journal_total",
                    router.adopted_from_journal_total,
                    help_text="terminals adopted from a drained replica's "
                              "journal during migration")
    reg.set_counter(f"{ns}_router_lost_total", router.lost_total,
                    help_text="requests finalized failed with NO replica "
                              "available — staying at zero is the fleet's "
                              "durability invariant")
    reg.set_gauge(f"{ns}_router_replicas", len(router.replicas),
                  help_text="fleet size")
    reg.set_gauge(f"{ns}_router_healthy_replicas",
                  len(router.healthy_indices()),
                  help_text="replicas currently routable and health-fresh")
    for replica in router.replicas:
        reg.set_gauge(f"{ns}_router_replica_drained",
                      1.0 if replica.drained else 0.0,
                      labels={"replica": str(replica.index)},
                      help_text="1 once the replica's restart budget "
                                "exhausted and its work migrated away")
    # per-tenant fleet counters (ISSUE 19): placement distribution and
    # tenant-global quota sheds (the sheds the router refuses to re-route —
    # families absent until a tenant-labeled workload arrives)
    for tenant, count in sorted(router.routed_by_tenant.items()):
        reg.set_counter(f"{ns}_router_tenant_routed_total", count,
                        labels={"tenant": tenant},
                        help_text="requests routed, by tenant")
    for tenant, count in sorted(router.quota_sheds_by_tenant.items()):
        reg.set_counter(f"{ns}_router_tenant_quota_sheds_total", count,
                        labels={"tenant": tenant},
                        help_text="quota_exceeded sheds surfaced to the "
                                  "caller (tenant-global — never re-routed "
                                  "to a sibling replica)")


def populate_from_agent(reg: MetricsRegistry, agent,
                        heartbeats: Optional[Dict[int, Dict[str, Any]]] = None,
                        alive_ranks: Optional[Iterable[int]] = None,
                        now: Optional[float] = None) -> None:
    """Elastic agent liveness → registry: restart/world state plus per-rank
    heartbeat ages and steps from the last liveness sweep — the rank-liveness
    gauges a fleet router admits on."""
    # function-local: keep this module import-light (it is loaded by the
    # runtime engine, and the age math must be THE liveness helper's, not a
    # divergent copy)
    from ..runtime.heartbeat import heartbeat_age
    reg.set_counter(f"{reg.namespace}_elastic_restarts_total",
                    agent.restart_count,
                    help_text="worker-group restarts (rescales included)")
    reg.set_gauge(f"{reg.namespace}_elastic_max_restarts", agent.max_restarts,
                  help_text="restart budget")
    heartbeats = heartbeats if heartbeats is not None else agent._last_heartbeats
    alive = set(alive_ranks) if alive_ranks is not None else None
    for rank, record in sorted(heartbeats.items()):
        labels = {"rank": str(rank)}
        reg.set_gauge(f"{reg.namespace}_rank_step",
                      record.get("step", 0), labels=labels,
                      help_text="last stamped training step per rank")
        if now is not None:
            reg.set_gauge(f"{reg.namespace}_rank_heartbeat_age_seconds",
                          heartbeat_age(record, now), labels=labels,
                          help_text="seconds since the rank's last heartbeat stamp")
        if alive is not None:
            reg.set_gauge(f"{reg.namespace}_rank_alive",
                          1.0 if rank in alive else 0.0, labels=labels,
                          help_text="1 while the rank's process is running")
