"""Monitoring (reference deepspeed/monitor/) + the unified telemetry collector."""
from .monitor import Monitor, MonitorMaster
from .telemetry import TelemetryCollector, detect_peak_flops_per_chip
from .tracing import FlightRecorder, RequestTracer, StreamingHistogram
