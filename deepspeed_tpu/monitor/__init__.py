"""Monitoring (reference deepspeed/monitor/) + the unified telemetry collector
+ the pull-based ops plane (metrics registry / Prometheus exposition / HTTP
endpoints)."""
from .exposition import parse_exposition, render
from .metrics import FleetAggregator, MetricsRegistry
from .monitor import Monitor, MonitorMaster
from .ops_server import OpsCache, OpsServer, scrape
from .telemetry import TelemetryCollector, detect_peak_flops_per_chip
from .tracing import FlightRecorder, RequestTracer, StreamingHistogram
