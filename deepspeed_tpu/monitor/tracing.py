"""Request-lifecycle tracing for the v2 serving stack.

Where did *this request's* time go?  The aggregate gauges (ISSUE 1) and the
resilience event stream (ISSUE 4) say how the engine is doing; nothing says
how one request fared.  This module holds the three observability primitives
the serving engine composes (ISSUE 6):

- :class:`RequestTracer` — per-uid span chains across the request lifecycle
  (``queue_wait`` → ``prefill`` → ``decode``, with ``requeue`` spans around
  preemptions and one terminal event that matches the request's
  ``RequestResult`` status) plus the SLO latency histograms every
  continuous-batching system since Orca/vLLM reports: TTFT (time to first
  token), TBT (time between tokens), e2e latency and queue wait, each a
  mergeable log-bucket streaming histogram with p50/p95/p99 snapshots.
  Completed traces export as ``kind: trace`` JSONL records through the
  attached :class:`~..telemetry.TelemetryCollector` and, optionally, as a
  Chrome-trace-event JSON file loadable in Perfetto / ``chrome://tracing``.
- :class:`StreamingHistogram` — the log-bucket histogram itself: O(1) add,
  bounded memory (one int per occupied bucket), exact merge between
  same-shaped histograms, deterministic quantiles (bucket representatives,
  so FakeClock-driven tests assert exact values).
- :class:`FlightRecorder` — an always-on bounded ring of recent engine
  events (dispatch/absorb/flush/burst/preempt/shed/admit/expire/stall) whose
  tail is dumped into ``ServingStalledError`` snapshots and ``health()`` —
  the "what led up to the wedge" history a point-in-time snapshot lacks.

Timing discipline: the tracer consumes the ENGINE's injectable clock and
reads it only at points the host already touches (admission intake, the
per-iteration deadline sweep, token materialization) — tracing adds host
arithmetic and at most a few extra clock reads per step when enabled, and
**zero** device syncs, so the serving fast path's counter invariants (≤1
host sync per steady iteration, zero warm recompiles) hold with tracing on.
When disabled, every span/histogram hook is a cheap early-return and no
extra clock reads happen at all; the flight recorder stays on (it stamps
events with the engine's last already-read clock value via :meth:`tick`).

All host-side; nothing here imports jax.
"""

import collections
import dataclasses
import json
import math
import os
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# span names (the per-request lifecycle chain)
SPAN_QUEUE_WAIT = "queue_wait"
SPAN_PREFILL = "prefill"
SPAN_DECODE = "decode"
SPAN_REQUEUE = "requeue"

# statuses a trace can terminate with mirror admission.REQUEST_STATUSES
# (spelled out here so monitor/ never imports inference/)
TERMINAL_OK = "ok"
TERMINAL_SHED = "shed"


class StreamingHistogram:
    """Mergeable log-bucket streaming histogram with deterministic quantiles.

    Values land in logarithmic buckets: bucket ``i`` covers
    ``[min_value * 10^(i/bpd), min_value * 10^((i+1)/bpd))`` with
    ``bpd = buckets_per_decade``; values below ``min_value`` (including the
    exact-0.0 queue waits FakeClock tests produce) land in a dedicated
    underflow bucket whose representative is 0.0.  Quantiles return the
    geometric midpoint of the answering bucket — a deterministic function of
    the inputs, so fake-clock tests can assert exact percentile values, at a
    bounded relative error of ``10^(1/bpd) - 1`` (~47% per bucket at the
    default 6/decade — tight enough for SLO work where the decade matters).

    Two histograms with the same shape merge by adding counts, which is what
    makes per-worker histograms aggregatable into a fleet view.
    """

    def __init__(self, buckets_per_decade: int = 6, min_value: float = 1e-5):
        if buckets_per_decade < 1:
            raise ValueError(f"buckets_per_decade must be >= 1, got {buckets_per_decade}")
        if min_value <= 0.0:
            raise ValueError(f"min_value must be > 0, got {min_value}")
        self.buckets_per_decade = int(buckets_per_decade)
        self.min_value = float(min_value)
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.max_seen: Optional[float] = None

    def _index(self, value: float) -> int:
        if value < self.min_value:
            return -1  # underflow bucket (includes 0.0 exactly)
        # the epsilon keeps exact bucket edges in the bucket they open
        return int(math.floor(math.log10(value / self.min_value)
                              * self.buckets_per_decade + 1e-9))

    def representative(self, index: int) -> float:
        """Deterministic stand-in value for a bucket (geometric midpoint)."""
        if index < 0:
            return 0.0
        return self.min_value * 10.0 ** ((index + 0.5) / self.buckets_per_decade)

    def add(self, value: float) -> None:
        value = float(value)
        idx = self._index(value)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.count += 1
        self.total += value
        if self.max_seen is None or value > self.max_seen:
            self.max_seen = value

    def merge(self, other: "StreamingHistogram") -> None:
        """Fold ``other`` in; shapes (bpd, min_value) must match exactly."""
        if (other.buckets_per_decade != self.buckets_per_decade
                or other.min_value != self.min_value):
            raise ValueError(
                f"histogram shape mismatch: {self.buckets_per_decade}/decade from "
                f"{self.min_value} vs {other.buckets_per_decade}/decade from "
                f"{other.min_value} — merge requires identical bucket edges")
        for idx, n in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        if other.max_seen is not None and (self.max_seen is None
                                           or other.max_seen > self.max_seen):
            self.max_seen = other.max_seen

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1]; None while empty."""
        # one GIL-atomic copy: health()/scrape threads read these histograms
        # while the serve thread add()s, and iterating the live bucket dict
        # with interleaved bytecode would crash on a concurrent insert (the
        # copied view may lag by an in-flight add; quantiles tolerate that)
        counts = dict(self.counts)
        count = sum(counts.values())
        if not count:
            return None
        rank = max(1, math.ceil(q * count))
        cum = 0
        for idx in sorted(counts):
            cum += counts[idx]
            if cum >= rank:
                return self.representative(idx)
        return self.representative(max(counts))  # q > 1 degrades to max bucket

    def percentiles(self) -> Optional[Dict[str, float]]:
        """{p50, p95, p99} or None while empty."""
        if not self.count:
            return None
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"count": self.count,
                               "mean": (self.total / self.count) if self.count else None,
                               "max": self.max_seen}
        out.update(self.percentiles() or {"p50": None, "p95": None, "p99": None})
        return out

    def reset(self) -> None:
        self.counts.clear()
        self.count = 0
        self.total = 0.0
        self.max_seen = None


class FlightRecorder:
    """Always-on bounded ring of recent engine events.

    Appends are O(1) dict-into-deque; the ring holds the last ``capacity``
    events so a stall/postmortem dump shows the sequence that LED to the
    wedge, not just the wedged state.  Events are stamped with whatever clock
    value the engine last read anyway (see :meth:`RequestTracer.tick`), so an
    always-on recorder costs zero extra clock reads.

    Besides the serve-loop events (dispatch/absorb/flush/burst/preempt/
    shed/admit/expire/finish/failed/stall), the serving fault-tolerance
    layer (ISSUE 8) lands its lifecycle here too: ``restart`` (a supervised
    engine rebuild), ``recovered`` (a request re-admitted with its emitted
    prefix), and ``finalized`` (a terminal the recovery planner wrote
    without re-serving) — so a crash postmortem reads as one ring.  The
    ``ServingSupervisor`` additionally keeps its own instance for the
    process-level view (generation_spawned/worker_failed/hang_detected/
    degraded/run_complete).
    """

    def __init__(self, capacity: int = 256):
        self._ring: collections.deque = collections.deque(maxlen=max(int(capacity), 1))
        self.events_total = 0

    def __len__(self) -> int:
        return len(self._ring)

    def record(self, event: str, *, t: float = 0.0, step: int = 0, **fields) -> None:
        self.events_total += 1
        entry = {"seq": self.events_total, "t": round(float(t), 6),
                 "step": int(step), "event": event}
        if fields:
            entry.update(fields)
        self._ring.append(entry)

    def tail(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The most recent ``n`` events (all buffered events when None)."""
        events = list(self._ring)
        return events if n is None else events[-int(n):]


@dataclasses.dataclass
class Span:
    name: str
    start: float
    end: Optional[float] = None
    meta: Optional[Dict[str, Any]] = None

    def as_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "start": round(self.start, 6),
                               "end": None if self.end is None else round(self.end, 6)}
        if self.meta:
            out["meta"] = self.meta
        return out


@dataclasses.dataclass
class RequestTrace:
    """One request's lifecycle: spans + point events + derived marks."""
    uid: int
    submit_t: Optional[float] = None
    admit_t: Optional[float] = None
    first_sched_t: Optional[float] = None
    first_token_t: Optional[float] = None
    last_token_t: Optional[float] = None
    end_t: Optional[float] = None
    tokens: int = 0
    preemptions: int = 0
    queue_wait_s: float = 0.0
    status: Optional[str] = None
    finish_reason: Optional[str] = None
    reason: Optional[str] = None
    spans: List[Span] = dataclasses.field(default_factory=list)
    events: List[Tuple[str, float, Dict[str, Any]]] = dataclasses.field(default_factory=list)

    def open_span(self, name: str, start: float, **meta) -> Span:
        span = Span(name=name, start=start, meta=meta or None)
        self.spans.append(span)
        return span

    def close_span(self, name: str, end: float) -> Optional[Span]:
        """Close the most recent open span named ``name`` (None if none open)."""
        for span in reversed(self.spans):
            if span.name == name and span.end is None:
                span.end = end
                return span
        return None

    def open_span_names(self) -> List[str]:
        return [s.name for s in self.spans if s.end is None]

    def record(self) -> Dict[str, Any]:
        """The JSONL-exportable per-request trace record (``kind: trace``)."""
        r6 = lambda v: None if v is None else round(v, 6)
        e2e = (self.end_t - self.submit_t
               if self.end_t is not None and self.submit_t is not None else None)
        ttft = (self.first_token_t - self.submit_t
                if self.first_token_t is not None and self.submit_t is not None else None)
        return {
            "uid": self.uid,
            "status": self.status,
            "finish_reason": self.finish_reason,
            "reason": self.reason,
            "submit_t": r6(self.submit_t),
            "admit_t": r6(self.admit_t),
            "first_token_t": r6(self.first_token_t),
            "end_t": r6(self.end_t),
            "queue_wait_s": r6(self.queue_wait_s),
            "ttft_s": r6(ttft),
            "e2e_s": r6(e2e),
            "tokens": self.tokens,
            "preemptions": self.preemptions,
            "spans": [s.as_dict() for s in self.spans],
            "events": [[name, r6(t), fields] for name, t, fields in self.events],
        }


class RequestTracer:
    """Per-request span recorder + SLO histograms + flight recorder.

    The engine owns exactly one tracer and threads it through admission,
    the scheduler and the fast path.  Hook methods come in two families:

    - always-on, zero-clock-read: :meth:`event` (flight recorder, stamped
      with the last :meth:`tick`'ed time) and :meth:`observe_queue_wait`
      (the wait is a float the admission pump already computed);
    - gated on ``enabled``: the span hooks (``on_submit``/``on_admit``/
      ``on_chunks``/``on_tokens``/``on_preempt``/``on_terminal``), which may
      read the injected clock — host-side only, never a device sync.

    ``clock`` is the engine's injectable clock (fault tests drive a fake);
    the tracer NEVER reads any other time source, so traces and percentile
    assertions are deterministic under a FakeClock.
    """

    HISTOGRAMS = ("ttft", "tbt", "e2e", "queue_wait")

    def __init__(self, config=None, *, clock: Optional[Callable[[], float]] = None,
                 telemetry=None):
        from ..runtime.config import ServingTracingConfig
        self.config = config if config is not None else ServingTracingConfig()
        self.enabled = bool(self.config.enabled)
        self.clock = clock if clock is not None else time.monotonic
        self.telemetry = telemetry
        self.recorder = FlightRecorder(self.config.flight_recorder_events)
        self.last_now = 0.0
        hist = lambda: StreamingHistogram(self.config.histogram_buckets_per_decade,
                                          self.config.histogram_min_s)
        self.ttft = hist()
        self.tbt = hist()
        self.e2e = hist()
        self.queue_wait = hist()
        # per-tenant SLO histograms (ISSUE 19): filled only for requests
        # whose intake hook carried a tenant id (the QoS layer supplies it),
        # keyed (tenant, ttft|e2e) — the serving_tenant_* exposition reads
        # these; single-tenant/no-QoS runs never populate the map
        self._hist = hist
        self._tenant_of: Dict[int, str] = {}
        self.tenant_hists: Dict[Tuple[str, str], StreamingHistogram] = {}
        self._live: Dict[int, RequestTrace] = {}
        self.completed_total = 0
        # chrome-trace events accumulate only when an export path is set;
        # bounded so a long-lived server can't grow the buffer unboundedly
        self._chrome: collections.deque = collections.deque(maxlen=100_000)

    # ------------------------------------------------------------ time plumbing
    def tick(self, now: float) -> None:
        """Donate a clock value the engine already read (the per-iteration
        deadline sweep) — keeps the always-on flight recorder stamped without
        any tracer-initiated clock reads."""
        self.last_now = now

    def now(self) -> float:
        """Read the injected clock (enabled paths only)."""
        t = self.clock()
        self.last_now = t
        return t

    # ------------------------------------------------------- always-on hooks
    def event(self, name: str, *, step: int = 0, **fields) -> None:
        """Flight-recorder append (always on; stamped with the last ticked
        time, never a fresh clock read)."""
        self.recorder.record(name, t=self.last_now, step=step, **fields)

    def observe_queue_wait(self, wait_s: float) -> None:
        """Queue-wait histogram sample (always on: the pump already computed
        the wait, this is pure host arithmetic)."""
        self.queue_wait.add(max(0.0, float(wait_s)))

    # ------------------------------------------------------------ span hooks
    def trace(self, uid: int) -> Optional[RequestTrace]:
        return self._live.get(uid)

    def _ensure(self, uid: int) -> RequestTrace:
        tr = self._live.get(uid)
        if tr is None:
            tr = RequestTrace(uid=int(uid))
            self._live[uid] = tr
        return tr

    def _note_tenant(self, uid: int, tenant: Optional[str]) -> None:
        if tenant:
            self._tenant_of[int(uid)] = str(tenant)

    def _tenant_hist(self, tenant: str, name: str) -> StreamingHistogram:
        key = (tenant, name)
        h = self.tenant_hists.get(key)
        if h is None:
            h = self.tenant_hists[key] = self._hist()
        return h

    def on_submit(self, uid: int, t: float, *, prompt_len: int = 0,
                  priority: int = 0, tenant: Optional[str] = None) -> None:
        """Request entered the admission queue (t = the ticket's enqueue_t —
        a clock value the queue already read)."""
        if not self.enabled:
            return
        self._note_tenant(uid, tenant)
        tr = self._ensure(uid)
        tr.submit_t = t
        tr.open_span(SPAN_QUEUE_WAIT, t, prompt_len=int(prompt_len),
                     priority=int(priority))

    def on_shed(self, uid: int, code: str, *, retryable: bool = False,
                detail: str = "") -> None:
        """Terminal at the admission door: the request never owned a trace
        worth of spans — emit a single-event terminal record."""
        if not self.enabled:
            return
        tr = self._live.pop(uid, None) or RequestTrace(uid=int(uid))
        t = self.last_now
        if tr.submit_t is None:
            tr.submit_t = t
        fields: Dict[str, Any] = {"code": code, "retryable": bool(retryable)}
        if detail:
            fields["detail"] = detail
        tr.events.append(("shed", t, fields))
        tr.status = TERMINAL_SHED
        tr.reason = code
        tr.end_t = t
        self._tenant_of.pop(uid, None)
        self._finalize(tr)

    def on_admit(self, uid: int, t: Optional[float] = None, *,
                 queue_wait_s: float = 0.0, prompt_len: int = 0,
                 tenant: Optional[str] = None) -> None:
        """Request left the queue and entered the state manager (or was
        ``put()`` directly, queue_wait 0)."""
        if not self.enabled:
            return
        self._note_tenant(uid, tenant)
        if t is None:
            t = self.now()
        tr = self._ensure(uid)
        tr.admit_t = t
        tr.queue_wait_s = max(0.0, float(queue_wait_s))
        if tr.submit_t is None:
            # direct put(): arrival == admission
            tr.submit_t = t - tr.queue_wait_s
        tr.close_span(SPAN_QUEUE_WAIT, t)
        tr.events.append(("admit", t, {"queue_wait_s": round(tr.queue_wait_s, 6)}))

    def on_chunks(self, chunks: Iterable[Tuple[int, int]], *, step: int = 0) -> None:
        """A scheduled batch was dispatched: ``chunks`` is [(uid, n_tokens)].
        Opens each request's prefill span on its first appearance and closes
        any requeue span a preempted request was waiting in."""
        if not self.enabled:
            return
        t = self.now()
        for uid, n_tokens in chunks:
            tr = self._live.get(uid)
            if tr is None:
                continue
            if tr.close_span(SPAN_REQUEUE, t) is not None:
                tr.events.append(("resumed", t, {"step": int(step)}))
                # the victim re-prefills its rolled-back positions
                tr.open_span(SPAN_PREFILL, t, resumed=True)
            elif tr.first_sched_t is None:
                tr.first_sched_t = t
                tr.open_span(SPAN_PREFILL, t, first_chunk_tokens=int(n_tokens))

    def on_tokens(self, uid: int, n: int, t: float) -> None:
        """``n`` sampled tokens for ``uid`` became host-visible at ``t`` (a
        materialize boundary).  The first observation closes the prefill span,
        opens the decode span and lands the TTFT sample; later observations
        contribute TBT samples — a burst of k tokens fetched in one sync
        contributes k samples of (t - prev)/k, matching the bench convention
        (per-token latency inside a fused burst is not host-observable)."""
        if not self.enabled or n <= 0:
            return
        tr = self._live.get(uid)
        if tr is None:
            return
        if tr.first_token_t is None:
            tr.first_token_t = t
            tr.close_span(SPAN_PREFILL, t)
            tr.open_span(SPAN_DECODE, t)
            base = tr.submit_t if tr.submit_t is not None else t
            self.ttft.add(max(0.0, t - base))
            tenant = self._tenant_of.get(uid)
            if tenant is not None:
                self._tenant_hist(tenant, "ttft").add(max(0.0, t - base))
            n_gap = n - 1
        else:
            n_gap = n
        if n_gap > 0 and tr.last_token_t is not None:
            gap = max(0.0, t - tr.last_token_t) / n_gap
            for _ in range(n_gap):
                self.tbt.add(gap)
        tr.last_token_t = t
        tr.tokens += n

    def on_tokens_map(self, out: Dict[int, int]) -> None:
        """Step-shaped emission: {uid: token} — one token per uid, all
        host-visible at one shared clock read."""
        if not self.enabled or not out:
            return
        t = self.now()
        for uid in out:
            self.on_tokens(uid, 1, t)

    def on_burst_tokens(self, counts: Dict[int, int]) -> None:
        """Burst-shaped emission: {uid: n_tokens} materialized in ONE sync."""
        if not self.enabled or not counts:
            return
        t = self.now()
        for uid, n in counts.items():
            self.on_tokens(uid, int(n), t)

    def on_preempt(self, uid: int, *, freed_blocks: int = 0,
                   rolled_back_to: int = 0, preemptions: int = 0) -> None:
        """KV-pressure preemption: point event + an open requeue span that the
        victim's next scheduled chunk closes."""
        if not self.enabled:
            return
        tr = self._live.get(uid)
        if tr is None:
            return
        t = self.last_now  # the scheduler runs between engine clock reads
        tr.preemptions = max(tr.preemptions + 1, int(preemptions))
        tr.events.append(("preempt", t, {"freed_blocks": int(freed_blocks),
                                         "rolled_back_to": int(rolled_back_to)}))
        tr.close_span(SPAN_PREFILL, t)
        # the requeue span stays open until the victim's next scheduled chunk
        # (on_chunks closes it and reopens prefill for the recomputed positions)
        tr.open_span(SPAN_REQUEUE, t, rolled_back_to=int(rolled_back_to))

    def on_terminal(self, uid: int, status: str, *, finish_reason: Optional[str] = None,
                    reason: Optional[str] = None, t: Optional[float] = None) -> None:
        """Close the trace with its terminal status (matches the request's
        ``RequestResult.status``), land the e2e sample for completed requests,
        and export the trace record."""
        if not self.enabled:
            return
        tr = self._live.pop(uid, None)
        if tr is None:
            return  # already terminal (idempotent across flush()/retire paths)
        if t is None:
            t = self.now()
        for name in tr.open_span_names():
            tr.close_span(name, t)
        tr.end_t = t
        tr.status = status
        tr.finish_reason = finish_reason
        tr.reason = reason
        tr.events.append((status, t, {"finish_reason": finish_reason}
                          if finish_reason else {}))
        if status == TERMINAL_OK and tr.submit_t is not None:
            self.e2e.add(max(0.0, t - tr.submit_t))
            tenant = self._tenant_of.get(uid)
            if tenant is not None:
                self._tenant_hist(tenant, "e2e").add(max(0.0, t - tr.submit_t))
        self._tenant_of.pop(uid, None)
        self._finalize(tr)

    def abort_all(self, uids: Iterable[int], *, reason: str = "aborted") -> None:
        """Strict-mode teardown: close every still-open trace of this call so
        the live-trace map can't leak across generate() calls."""
        if not self.enabled:
            return
        for uid in list(uids):
            if uid in self._live:
                self.on_terminal(uid, "failed", reason=reason, t=self.last_now)

    # ---------------------------------------------------------------- export
    def _finalize(self, tr: RequestTrace) -> None:
        self.completed_total += 1
        record = tr.record()
        if self.telemetry is not None and self.config.trace_jsonl:
            self.telemetry.record_trace(record)
        if self.config.chrome_trace_path:
            self._chrome.extend(self._chrome_events(tr))

    @staticmethod
    def _chrome_events(tr: RequestTrace) -> List[Dict[str, Any]]:
        """Chrome-trace-event (Perfetto-loadable) shapes: one track per uid,
        complete ("X") events per span, instant ("i") events per point."""
        us = lambda t: int(round(t * 1e6))
        events: List[Dict[str, Any]] = []
        for span in tr.spans:
            if span.end is None:
                continue
            ev = {"name": span.name, "ph": "X", "pid": 0, "tid": tr.uid,
                  "ts": us(span.start), "dur": max(0, us(span.end) - us(span.start)),
                  "cat": "request"}
            if span.meta:
                ev["args"] = span.meta
            events.append(ev)
        for name, t, fields in tr.events:
            events.append({"name": name, "ph": "i", "pid": 0, "tid": tr.uid,
                           "ts": us(t), "s": "t", "cat": "request",
                           **({"args": fields} if fields else {})})
        return events

    def counter_track(self, name: str, values: Dict[str, float]) -> None:
        """Append a Chrome-trace COUNTER sample (``ph: "C"`` — rendered as a
        stacked counter track in Perfetto/chrome://tracing) stamped with the
        last ticked engine time.  Used by the KV-pool observability layer for
        free-blocks / fragmentation / steps-to-exhaustion tracks alongside
        the per-request span rows.  A no-op unless a chrome export path is
        configured, so the always-on path costs one attribute check."""
        if not self.config.chrome_trace_path or not values:
            return
        # counter events key on (pid, name); tid rides along so every buffered
        # event carries the same field set as the span/instant shapes
        self._chrome.append({"name": name, "ph": "C", "pid": 0, "tid": 0,
                             "ts": int(round(self.last_now * 1e6)),
                             "args": {k: float(v) for k, v in values.items()}})

    def phase_span(self, name: str, start_s: float, dur_s: float,
                   track: int = 0) -> None:
        """Append a Chrome-trace COMPLETE span (``ph: "X"``) for one serve-loop
        phase (ISSUE 16 — the StepPhaseProfiler's per-phase tracks).  Phase
        rows live under their own pid so Perfetto groups them separately from
        the per-request lifecycle rows; ``track`` (the phase's index) keeps
        each phase on a stable tid/row.  A no-op unless a chrome export path
        is configured — the serve loop pays one attribute check."""
        if not self.config.chrome_trace_path:
            return
        self._chrome.append({"name": name, "ph": "X", "pid": 1, "tid": int(track),
                             "ts": int(round(start_s * 1e6)),
                             "dur": int(round(dur_s * 1e6)), "cat": "phase"})

    def write_chrome_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write buffered chrome events as a trace-event JSON file (load in
        Perfetto or chrome://tracing); returns the path, or None when neither
        an explicit path nor ``config.chrome_trace_path`` is set."""
        path = path or self.config.chrome_trace_path
        if not path or not self._chrome:
            return None
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as fh:
            json.dump({"traceEvents": list(self._chrome),
                       "displayTimeUnit": "ms"}, fh)
        return path

    # ------------------------------------------------------------- snapshots
    def histograms(self) -> Dict[str, StreamingHistogram]:
        return {name: getattr(self, name) for name in self.HISTOGRAMS}

    def percentiles(self) -> Dict[str, Optional[Dict[str, float]]]:
        """{ttft|tbt|e2e|queue_wait: {p50, p95, p99} | None-when-empty}."""
        return {name: h.percentiles() for name, h in self.histograms().items()}

    def tenant_histograms(self) -> Dict[Tuple[str, str], StreamingHistogram]:
        """{(tenant, ttft|e2e): histogram} — the per-tenant SLO view the
        serving_tenant_* Prometheus families export (empty without QoS)."""
        return dict(self.tenant_hists)

    def latency_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """health()-shaped: full snapshots (count/mean/max/p50/p95/p99)."""
        return {name: h.snapshot() for name, h in self.histograms().items()}

    def gauge_fields(self) -> Dict[str, float]:
        """Flat float gauges for the telemetry stream (only non-empty
        histograms contribute; {} when tracing is disabled)."""
        if not self.enabled:
            return {}
        out: Dict[str, float] = {}
        for name, h in self.histograms().items():
            pct = h.percentiles()
            if pct is None:
                continue
            for p, v in pct.items():
                out[f"{name}_{p}_s"] = float(v)
        return out

    def reset_histograms(self) -> None:
        """Drop accumulated samples (bench: isolate the timed pass from the
        warm/compile pass).  Live traces and the flight recorder are kept."""
        for h in self.histograms().values():
            h.reset()

    def live_uids(self) -> List[int]:
        return sorted(self._live)
