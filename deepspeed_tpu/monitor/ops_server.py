"""Pull-based ops endpoints: /metrics, /healthz, /statez.

A stdlib ``ThreadingHTTPServer`` (ephemeral port by default) that serves the
operator-facing face of everything PRs 1-8 measure:

- ``/metrics``  — Prometheus text format 0.0.4 (:mod:`.exposition`)
- ``/healthz``  — the owning component's ``health()`` snapshot as JSON
- ``/statez``   — ``state_snapshot()`` (+ flight-recorder tail) as JSON

**Scrape-safety contract (dslint-enforced).**  Handlers serve ONLY the
pre-rendered byte strings in :class:`OpsCache`; they never call into the
engine, registry, or any collector.  The owning thread (the serve loop, the
train step, the agent/supervisor poll loop) refreshes the cache at points it
already touches the host state — so a scrape is a memory read, can never
trigger a device sync, and can never race a mutating step.  dslint's
host-sync rule scans this whole file (like runtime/heartbeat.py) so an
explicit device fetch here is a static-analysis error.

**Multi-process aggregation.**  Ranks that don't own the endpoint (training
ranks > 0, supervised serving workers) write their registry snapshot — plus
a scrape-ready ``.prom`` textfile for node-exporter-style collection — to a
shared directory via :func:`write_rank_files` (atomic tmp + ``os.replace``,
the heartbeat write protocol).  The elastic agent / ``ServingSupervisor``
read them back with :func:`read_rank_snapshots` (torn/foreign files read as
absent, never as an exception) and merge them through
:class:`~.metrics.FleetAggregator` into one fleet-level endpoint that stays
monotone across worker restarts.

Nothing here imports jax or numpy.
"""

import json
import os
import re
import socket
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from ..utils.logging import logger, warning_once
from .exposition import CONTENT_TYPE, render
from .metrics import MetricsRegistry

# rank exchange files: ops.rank<R>.json (registry snapshot, the exact-merge
# format) + ops.rank<R>.prom (rendered text, for external textfile collectors)
_SNAPSHOT_PREFIX = "ops.rank"
_SNAPSHOT_RE = re.compile(r"^ops\.rank(\d+)\.json$")

JSON_CONTENT_TYPE = "application/json; charset=utf-8"


class OpsCache:
    """The host-side cached snapshots a scrape reads.

    Plain attribute assignment of complete strings — atomic under the GIL,
    so the HTTP threads always see a consistent payload without locking the
    serve loop."""

    def __init__(self):
        self.metrics_text = ""
        self.healthz = "{}"
        self.statez = "{}"
        self.refreshes = 0

    def update(self, *, metrics_text: Optional[str] = None,
               healthz: Optional[str] = None,
               statez: Optional[str] = None) -> None:
        if metrics_text is not None:
            self.metrics_text = metrics_text
        if healthz is not None:
            self.healthz = healthz
        if statez is not None:
            self.statez = statez
        # dslint: disable-next-line=atomic-publish  # update() is only ever called from the publisher's owning thread (single writer); handler threads read the three text attrs but never touch refreshes, so the += cannot interleave with anything
        self.refreshes += 1


class _OpsHandler(BaseHTTPRequestHandler):
    server_version = "dstpu-ops/1"

    def _send(self, body: str, content_type: str, code: int = 200) -> None:
        payload = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 (http.server API)
        cache: OpsCache = self.server.ops_cache  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._send(cache.metrics_text, CONTENT_TYPE)
        elif path == "/healthz":
            self._send(cache.healthz, JSON_CONTENT_TYPE)
        elif path == "/statez":
            self._send(cache.statez, JSON_CONTENT_TYPE)
        elif path == "/":
            self._send('{"endpoints": ["/metrics", "/healthz", "/statez"]}',
                       JSON_CONTENT_TYPE)
        else:
            self._send('{"error": "not found"}', JSON_CONTENT_TYPE, code=404)

    def log_message(self, format, *args):  # scrapes must not spam stderr
        pass


class OpsServer:
    """Threaded HTTP server over an :class:`OpsCache`.

    ``port=0`` (the default) binds an ephemeral port — read ``.port`` after
    construction; ``close()`` shuts the listener down and joins the thread.
    Construction failures (port in use) raise; callers that prefer degraded
    observability over a dead process use :func:`try_start_ops_server`."""

    def __init__(self, cache: Optional[OpsCache] = None, *,
                 host: str = "127.0.0.1", port: int = 0):
        self.cache = cache if cache is not None else OpsCache()
        self._httpd = ThreadingHTTPServer((host, int(port)), _OpsHandler)
        self._httpd.daemon_threads = True
        self._httpd.ops_cache = self.cache  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dstpu-ops-server", daemon=True)
        self._thread.start()
        self.closed = False

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        # ``shutdown()`` blocks until serve_forever acknowledges — which
        # NEVER happens during interpreter finalization (daemon threads are
        # frozen before remaining __del__s run), so a process exiting with a
        # live listener would hang forever on this wait.  At finalization
        # (or with the thread already gone) just close the socket; the
        # daemon thread dies with the process.
        if not sys.is_finalizing() and self._thread.is_alive():
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
        self._httpd.server_close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # dslint: disable=silent-except  # interpreter-shutdown teardown: the socket/thread machinery may already be gone; raising from __del__ only prints noise
            pass


def try_start_ops_server(cache: OpsCache, *, host: str = "127.0.0.1",
                         port: int = 0, owner: str = "ops") -> Optional[OpsServer]:
    """Start a server, degrading to None (with one warning) on bind failure —
    a busy port must degrade observability, never kill training/serving."""
    try:
        server = OpsServer(cache, host=host, port=port)
    except OSError as exc:
        warning_once(f"{owner}: ops server failed to bind {host}:{port} "
                     f"({exc}); /metrics+/healthz disabled for this process")
        return None
    logger.info(f"{owner}: ops endpoints at http://{server.host}:{server.port} "
                f"(/metrics /healthz /statez)")
    return server


class OpsPublisher:
    """One process's ops-plane state, shared by the training and serving
    engines: the registry, the scrape cache, the (optional) HTTP listener,
    and the per-rank exchange files — plus the refresh policy (throttle and
    counter-reset handling) so the two engines cannot drift apart.

    ``refresh`` takes CALLABLES for the payloads so a throttled call costs
    two float compares, not a render.  A ``ValueError`` out of ``populate``
    (a source counter that legally rewound — e.g. a checkpoint rollback
    restoring an older ``global_steps``) is exposed as a standard Prometheus
    COUNTER RESET: fresh registry, SAME generation, so scrapers apply their
    normal reset handling.  A generation bump would instead fold the
    pre-rollback totals into the fleet carry and double-count every counter
    that did NOT rewind (the carry is exact only for real restarts, where
    process counters restart from zero)."""

    def __init__(self, cfg, *, generation: int = 0, ops_dir: Optional[str] = None,
                 rank: int = 0, owner: str = "ops"):
        self.cfg = cfg
        self.registry = MetricsRegistry(namespace=cfg.namespace,
                                        generation=int(generation))
        self.cache = OpsCache()
        self.ops_dir = ops_dir
        self.rank = int(rank)
        self.server = (try_start_ops_server(self.cache, host=cfg.host,
                                            port=cfg.port, owner=owner)
                       if cfg.enabled else None)
        self._last_refresh = -float("inf")

    def refresh(self, populate, *, now: float, force: bool = False,
                healthz=None, statez=None) -> bool:
        """Rebuild the cached snapshots (True when a refresh ran).  ``now``
        is the OWNER's clock (the serving engine donates its injectable
        clock's last read; training uses monotonic wall time) so throttling
        stays deterministic under fake clocks."""
        if not force and now - self._last_refresh < self.cfg.refresh_interval_s:
            return False
        self._last_refresh = now
        try:
            populate(self.registry)
        except ValueError:
            # counter reset (see class docstring): same generation, fresh
            # counts — never let a metrics invariant kill the owning loop
            self.registry = MetricsRegistry(namespace=self.cfg.namespace,
                                            generation=self.registry.generation)
            populate(self.registry)
        text = render(self.registry, collect=False)
        self.cache.update(metrics_text=text,
                          healthz=healthz() if healthz is not None else None,
                          statez=statez() if statez is not None else None)
        if self.ops_dir:
            write_rank_files(self.ops_dir, self.rank, self.registry,
                             metrics_text=text)
        return True

    def close(self) -> None:
        if self.server is not None:
            self.server.close()


def scrape(url: str, timeout: float = 2.0) -> str:
    """Tiny in-tree scraper (tests + smokes; avoids urllib's global state):
    one GET, returns the decoded body, raises on a non-200."""
    from urllib.parse import urlparse
    parsed = urlparse(url)
    with socket.create_connection((parsed.hostname, parsed.port),
                                  timeout=timeout) as sock:
        path = parsed.path or "/"
        sock.sendall(f"GET {path} HTTP/1.1\r\nHost: {parsed.hostname}\r\n"
                     f"Connection: close\r\n\r\n".encode())
        chunks = []
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            chunks.append(chunk)
    raw = b"".join(chunks).decode("utf-8", errors="replace")
    head, _, body = raw.partition("\r\n\r\n")
    status_line = head.splitlines()[0] if head else ""
    parts = status_line.split()
    code = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 0
    if code != 200:
        raise RuntimeError(f"scrape {url}: HTTP {code or status_line!r}")
    return body


# ==========================================================================
# Per-rank exchange files (training ranks > 0, supervised serving workers)
# ==========================================================================

def _atomic_write(path: str, payload: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(payload)
    os.replace(tmp, path)


def snapshot_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"{_SNAPSHOT_PREFIX}{int(rank)}.json")


def textfile_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"{_SNAPSHOT_PREFIX}{int(rank)}.prom")


def write_rank_files(directory: str, rank: int,
                     registry: MetricsRegistry, *,
                     metrics_text: Optional[str] = None) -> bool:
    """Atomically publish this rank's registry: the JSON snapshot (the
    exact-merge format the aggregators read) and the rendered ``.prom``
    textfile.  A broken directory degrades to False with one warning —
    observability export must never fail the work it observes."""
    try:
        os.makedirs(directory, exist_ok=True)
        _atomic_write(snapshot_path(directory, rank),
                      json.dumps(registry.snapshot()))
        _atomic_write(textfile_path(directory, rank),
                      metrics_text if metrics_text is not None
                      else render(registry, collect=False))
    except OSError as exc:
        warning_once(f"ops: cannot write rank {rank} metrics files under "
                     f"{directory!r} ({exc}); per-rank export disabled")
        return False
    return True


def read_rank_snapshots(directory: str) -> Dict[int, Dict[str, Any]]:
    """All parseable per-rank snapshots under ``directory``.  Missing dir,
    torn writes, foreign files and valid-JSON-but-wrong-shape content all
    read as absent (the heartbeat reader's tolerance contract) — the
    aggregator keeps whatever it merged last.  A malformed file must degrade
    one rank's freshness, never crash the supervisor poll loop that every
    worker's lifecycle hangs off."""
    out: Dict[int, Dict[str, Any]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = _SNAPSHOT_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(directory, name)) as fh:
                snap = json.load(fh)
        except (OSError, ValueError):
            continue  # torn write: absent this poll, not fatal
        if not isinstance(snap, dict) or not isinstance(snap.get("families"), dict):
            continue  # foreign/version-skewed writer: shape-invalid, absent
        out[int(m.group(1))] = snap
    return out
