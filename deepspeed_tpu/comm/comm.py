"""Distributed communication facade.

TPU-native analog of ``deepspeed.comm`` (deepspeed/comm/comm.py:222-521 module-level
ops, ``init_distributed:604``).  The reference wraps torch.distributed/NCCL; here
collectives are XLA mesh-axis operations with two calling conventions:

1. **In-graph** (inside jit / shard_map over a Mesh): ``all_reduce(x, axis="data")``
   lowers to ``lax.psum`` and friends — XLA routes them over ICI and overlaps with
   compute.  This is the hot path ZeRO/MoE/Ulysses use.
2. **Host-level** (eager, outside jit): same function names operate on jax.Arrays
   by jitting a trivial collective over the current topology — used for control
   plane work (broadcast of initial params, barriers, scalar consensus) where the
   reference used eager NCCL calls.

Every op is profiled through the CommsLogger (analog of ``timed_op`` comm.py:101).
"""

import functools
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..parallel.mesh import MeshTopology, get_topology
from ..utils.comms_logging import get_comms_logger
from ..utils.logging import logger, warning_once

ReduceOp = type("ReduceOp", (), {"SUM": "sum", "AVG": "avg", "MAX": "max", "MIN": "min", "PRODUCT": "prod"})

_INITIALIZED = False


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     init_method: Optional[str] = None,
                     rank: int = -1,
                     world_size: int = -1,
                     timeout=None,
                     verbose=True):
    """Host control plane init — analog of ``deepspeed.init_distributed``
    (comm/comm.py:604).  Multi-host JAX uses ``jax.distributed.initialize`` (the
    rendezvous analog of the reference's NCCL TCP store); single-host is a no-op.

    Env discovery: honors COORDINATOR_ADDRESS / JAX_COORDINATOR_ADDRESS plus the
    reference's RANK/WORLD_SIZE spellings for familiarity.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    import os
    coord = (init_method or os.environ.get("COORDINATOR_ADDRESS") or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coord:
        nproc = world_size if world_size > 0 else int(os.environ.get("WORLD_SIZE", "1"))
        pid = rank if rank >= 0 else int(os.environ.get("RANK", "0"))
        jax.distributed.initialize(coordinator_address=coord, num_processes=nproc, process_id=pid)
        if verbose:
            logger.info(f"jax.distributed initialized: process {pid}/{nproc} via {coord}")
    from ..utils import logging as _logging
    _logging.set_rank_provider(jax.process_index)
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank(group=None) -> int:
    """Rank within ``group`` (a comm.groups.ProcessGroup) or the process index
    (reference comm.py:547 — group=None means the world group)."""
    if group is not None and hasattr(group, "rank"):
        return group.rank()
    return jax.process_index()

def get_world_size(group=None) -> int:
    """Size of ``group`` (device count over its mesh axes) or the host-process
    world size (device-level parallelism is the mesh's business)."""
    if group is not None and hasattr(group, "size"):
        return group.size()
    return jax.process_count()


def get_local_rank() -> int:
    return 0  # one process per host owns all local chips in JAX


def barrier(group=None):
    """Synchronize all processes/devices (reference comm.py:521)."""
    x = jnp.zeros(())
    x.block_until_ready()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("dstpu_barrier")


# --------------------------------------------------------------------------
# In-graph collectives (usable under shard_map / pjit with named mesh axes)
# --------------------------------------------------------------------------

AxisArg = Union[str, Sequence[str]]  # or a comm.groups.ProcessGroup


def _axes(axis):
    """Unwrap a ProcessGroup into its mesh-axes tuple (lax takes str|tuple)."""
    ax = getattr(axis, "axes", axis)
    return ax if isinstance(ax, str) else tuple(ax)


def _trace_log(op: str, x) -> None:
    cl = get_comms_logger()
    if cl.should_profile(op):
        try:
            cl.record_traced(op, int(np.prod(x.shape)) * x.dtype.itemsize)
        except Exception as exc:  # odd operand (no shape/dtype): skip the sample
            warning_once(f"comms logger: could not size a traced {op} operand "
                         f"({exc!r}); that collective is missing from the summary")


def all_reduce(x, axis: AxisArg, op: str = "sum"):
    """lax.psum/pmax/pmin over a mesh axis (reference comm.py:478 all_reduce)."""
    _trace_log("all_reduce", x)
    axis = _axes(axis)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "avg" or op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: AxisArg, *, tiled: bool = True, gather_dim: int = 0):
    """Gather shards along a mesh axis (reference all_gather_into_tensor comm.py:308).
    tiled=True concatenates along ``gather_dim`` (the flat-bucket layout ZeRO uses)."""
    _trace_log("all_gather", x)
    return lax.all_gather(x, _axes(axis), axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: AxisArg, *, scatter_dim: int = 0, tiled: bool = True):
    """Reduce + scatter shards (reference reduce_scatter_fn comm.py:246)."""
    _trace_log("reduce_scatter", x)
    return lax.psum_scatter(x, _axes(axis), scatter_dimension=scatter_dim, tiled=tiled)


def all_to_all(x, axis: AxisArg, *, split_dim: int, concat_dim: int, tiled: bool = True):
    """All-to-all over a mesh axis (reference all_to_all_single comm.py:334) —
    the Ulysses/MoE dispatch primitive."""
    _trace_log("all_to_all", x)
    return lax.all_to_all(x, _axes(axis), split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


def ppermute(x, axis: AxisArg, perm):
    """Point-to-point ring shift — the TPU-native analog of pipeline p2p send/recv
    (reference runtime/pipe/p2p.py:50,71); perm is [(src, dst), ...]."""
    _trace_log("ppermute", x)
    return lax.ppermute(x, _axes(axis), perm)


def axis_index(axis: AxisArg):
    if hasattr(axis, "axis_index"):
        return axis.axis_index()  # ProcessGroup: linearized over its axes
    return lax.axis_index(axis)


def broadcast(x, axis: AxisArg, src: int = 0):
    """Broadcast the src rank's shard to all ranks on the axis (comm.py:222).
    Implemented as select + psum (ppermute requires unique sources; select rather
    than multiply so non-src NaN/Inf shards cannot poison the sum)."""
    _trace_log("broadcast", x)
    axis = _axes(axis)
    idx = lax.axis_index(axis)
    contribution = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(contribution, axis)


# --------------------------------------------------------------------------
# Host-level (eager) collectives over the global topology
# --------------------------------------------------------------------------


def _timed(op_name):

    def deco(fn):

        @functools.wraps(fn)
        def wrapper(*args, log_name=None, **kwargs):
            cl = get_comms_logger()
            if not cl.should_profile(op_name):
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            x = args[0]
            size = int(np.prod(np.shape(x))) * jnp.asarray(x).dtype.itemsize
            world = get_topology().world_size
            cl.append(op_name, log_name or op_name, dt, size, world)
            return out

        return wrapper

    return deco


_REDUCERS = {
    "sum": jnp.sum,
    "avg": jnp.mean,
    "mean": jnp.mean,
    "max": jnp.max,
    "min": jnp.min,
    "prod": jnp.prod,
}


@functools.lru_cache(maxsize=None)
def _host_reduce_fn(op: str):
    reducer = _REDUCERS[op]
    return jax.jit(lambda v: reducer(v, axis=0))


@_timed("all_reduce")
def host_all_reduce(x, topo: Optional[MeshTopology] = None, op: str = "sum"):
    """Eager reduction over the leading ("per-contributor") axis of a global array.

    In single-controller JAX, arrays are globally consistent — there is no eager
    per-rank value to reduce the way torch.distributed.all_reduce does.  The
    control-plane uses (overflow consensus, loss averaging) stack contributions on
    the leading axis; in-graph consensus belongs inside the jitted step via
    ``all_reduce``.  The jitted reducer is cached per op (no per-call retrace).
    """
    if op not in _REDUCERS:
        raise ValueError(f"unsupported reduce op {op!r}; one of {sorted(_REDUCERS)}")
    if jnp.ndim(x) == 0:
        raise ValueError("host_all_reduce expects a leading contributor axis; got a scalar")
    return _host_reduce_fn(op)(x)


def host_broadcast(x, topo: Optional[MeshTopology] = None):
    """Replicate a host value across all devices (reference _broadcast_model
    engine.py:1052 analog: rank0's value wins; with SPMD jax arrays the host value
    is already consistent, so this is a device_put with replicated sharding)."""
    topo = topo or get_topology()
    return jax.device_put(x, topo.replicated())


def log_summary(show_straggler=False):
    """Reference dist.log_summary (comm/comm.py:422)."""
    return get_comms_logger().log_summary(show_straggler=show_straggler)


def monitor_events(step: int = 0):
    """Comms-logger summary as monitor ``(tag, value, step)`` events, for the
    telemetry collector's event stream (empty when nothing was profiled)."""
    return get_comms_logger().as_events(step)


def configure(comms_config=None):
    if comms_config is not None:
        get_comms_logger().configure(comms_config)
