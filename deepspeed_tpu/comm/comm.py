"""Distributed communication facade.

TPU-native analog of ``deepspeed.comm`` (deepspeed/comm/comm.py:222-521 module-level
ops, ``init_distributed:604``).  The reference wraps torch.distributed/NCCL; here
collectives are XLA mesh-axis operations with two calling conventions:

1. **In-graph** (inside jit / shard_map over a Mesh): ``all_reduce(x, axis="data")``
   lowers to ``lax.psum`` and friends — XLA routes them over ICI and overlaps with
   compute.  This is the hot path ZeRO/MoE/Ulysses use.
2. **Host-level** (eager, outside jit): same function names operate on jax.Arrays
   by jitting a trivial collective over the current topology — used for control
   plane work (broadcast of initial params, barriers, scalar consensus) where the
   reference used eager NCCL calls.

Every op is profiled through the CommsLogger (analog of ``timed_op`` comm.py:101).
"""

import functools
import os
import threading
import time
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..compat import ensure_cpu_multiprocess_collectives
from ..parallel.mesh import MeshTopology, get_topology
from ..runtime.heartbeat import (COLLECTIVE_TIMEOUT_ENV, INIT_RETRIES_ENV,
                                 INIT_RETRY_BACKOFF_ENV, get_heartbeat)
from ..utils.comms_logging import get_comms_logger
from ..utils.env import env_float, env_int
from ..utils.logging import logger, warning_once

ReduceOp = type("ReduceOp", (), {"SUM": "sum", "AVG": "avg", "MAX": "max", "MIN": "min", "PRODUCT": "prod"})

_INITIALIZED = False

# -------------------------------------------------------- bounded collectives
# Default wall-clock bound for HOST-LEVEL collectives (barrier and anything
# routed through bounded_collective).  None = unbounded (the historical
# behavior).  Set from config (fault_tolerance.collective_timeout_s via
# initialize()/the engine), set_default_collective_timeout(), or the env the
# elastic agent exports to its workers (collective_timeout_s agent param /
# launcher --collective_timeout).
_DEFAULT_COLLECTIVE_TIMEOUT_S: Optional[float] = None


class CollectiveTimeoutError(RuntimeError):
    """A host-level collective exceeded its wall-clock bound.

    The whole point of bounding collectives: a rank stuck in (or absent from)
    a collective otherwise deadlocks every peer SILENTLY — the job burns its
    deadline with zero diagnostics.  This error names the collective, this
    process's rank, and the elapsed time, so the supervisor (elastic agent)
    gets a fast, attributable failure to restart from instead of a hang."""

    def __init__(self, collective: str, rank: int, elapsed_s: float, timeout_s: float):
        self.collective = collective
        self.rank = rank
        self.elapsed_s = elapsed_s
        self.timeout_s = timeout_s
        super().__init__(
            f"collective '{collective}' timed out on rank {rank} after "
            f"{elapsed_s:.1f}s (timeout {timeout_s:.1f}s) — a peer likely "
            f"crashed, hung, or entered a different collective; check the "
            f"elastic agent's cross-rank hang snapshot for the stuck ranks")


def set_default_collective_timeout(timeout_s: Optional[float]) -> None:
    global _DEFAULT_COLLECTIVE_TIMEOUT_S
    _DEFAULT_COLLECTIVE_TIMEOUT_S = None if timeout_s is None else float(timeout_s)


def _resolve_timeout(timeout_s) -> Optional[float]:
    if timeout_s is not None:
        return float(timeout_s) if timeout_s > 0 else None
    env_val = env_float(COLLECTIVE_TIMEOUT_ENV)
    if env_val is not None:
        return env_val if env_val > 0 else None
    return _DEFAULT_COLLECTIVE_TIMEOUT_S


def bounded_collective(fn, *args, timeout_s: Optional[float] = None,
                       name: str = "collective", **kwargs):
    """Run a blocking host-level collective with a wall-clock bound.

    Stamps the heartbeat (``enter_collective(name)`` / ``exit_collective``)
    around the wait so the agent's hang dump can NAME the collective each
    rank sat in, then executes ``fn`` on a daemon worker thread and joins
    with the resolved timeout.  On expiry raises
    :class:`CollectiveTimeoutError`; the worker thread stays parked on the
    wedged collective (there is no portable way to cancel it) — the expected
    response is process exit + agent restart, which is exactly what the
    error exists to trigger.  ``timeout_s=None`` falls back to the
    module/env default; no default means a direct (unbounded) call, still
    heartbeat-stamped."""
    timeout = _resolve_timeout(timeout_s)
    hb = get_heartbeat()
    hb.enter_collective(name)
    timed_out = False
    try:
        if timeout is None:
            return fn(*args, **kwargs)
        result: list = []
        failure: list = []

        def _run():
            try:
                result.append(fn(*args, **kwargs))
            except BaseException as exc:  # noqa: BLE001 — re-raised on the caller thread below
                failure.append(exc)

        t0 = time.monotonic()
        worker = threading.Thread(target=_run, name=f"dstpu-{name}", daemon=True)
        worker.start()
        worker.join(timeout)
        if worker.is_alive():
            timed_out = True
            raise CollectiveTimeoutError(name, get_rank(), time.monotonic() - t0, timeout)
        if failure:
            raise failure[0]
        return result[0]
    finally:
        # on timeout the worker thread is STILL wedged inside the collective:
        # keep its name stamped so the agent's hang dump can attribute the
        # deadlock (clearing it would erase exactly that diagnosis and reset
        # the staleness clock on a rank that is not making progress)
        if not timed_out:
            hb.exit_collective()


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     init_method: Optional[str] = None,
                     rank: int = -1,
                     world_size: int = -1,
                     timeout=None,
                     verbose=True):
    """Host control plane init — analog of ``deepspeed.init_distributed``
    (comm/comm.py:604).  Multi-host JAX uses ``jax.distributed.initialize`` (the
    rendezvous analog of the reference's NCCL TCP store); single-host is a no-op.

    Env discovery: honors COORDINATOR_ADDRESS / JAX_COORDINATOR_ADDRESS plus the
    reference's RANK/WORLD_SIZE spellings for familiarity.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coord = (init_method or os.environ.get("COORDINATOR_ADDRESS") or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    if coord:
        nproc = world_size if world_size > 0 else int(os.environ.get("WORLD_SIZE", "1"))
        pid = rank if rank >= 0 else int(os.environ.get("RANK", "0"))
        # pre-0.5 jax defaults CPU collectives to 'none', so a multiprocess
        # CPU job dies on its first collective; align with the new default
        # BEFORE the client exists (no-op where the option is gone/explicit)
        if nproc > 1 and not ensure_cpu_multiprocess_collectives():
            warning_once("init_distributed: no cross-process CPU collectives "
                         "implementation could be selected on this jax — "
                         "multiprocess CPU programs will fail")
        _initialize_with_retries(coord, nproc, pid, timeout)
        if verbose:
            logger.info(f"jax.distributed initialized: process {pid}/{nproc} via {coord}")
    from ..utils import logging as _logging
    _logging.set_rank_provider(jax.process_index)
    _INITIALIZED = True


# Module defaults for the process-group setup retry loop.  Set from config
# (fault_tolerance.init_retries/init_retry_backoff_s, applied by
# deepspeed_tpu.initialize() BEFORE init_distributed runs) via
# set_init_retry_defaults(); the agent-exported env wins over both.
_DEFAULT_INIT_RETRIES = 3
_DEFAULT_INIT_RETRY_BACKOFF_S = 0.5


def set_init_retry_defaults(retries: Optional[int] = None,
                            backoff_s: Optional[float] = None) -> None:
    """Default attempts/backoff for ``_initialize_with_retries`` (None keeps
    the current value for that knob)."""
    global _DEFAULT_INIT_RETRIES, _DEFAULT_INIT_RETRY_BACKOFF_S
    if retries is not None:
        _DEFAULT_INIT_RETRIES = max(int(retries), 0)
    if backoff_s is not None:
        _DEFAULT_INIT_RETRY_BACKOFF_S = max(float(backoff_s), 0.0)


def _initialize_with_retries(coord: str, nproc: int, pid: int, timeout=None) -> None:
    """``jax.distributed.initialize`` under bounded exponential-backoff
    retries — process-group setup fails transiently in exactly the situations
    elastic training creates (restarted coordinator not listening yet, a peer
    of the previous generation still holding the port).  Attempts/backoff
    come from the env the elastic agent exports (``DSTPU_INIT_RETRIES`` /
    ``DSTPU_INIT_RETRY_BACKOFF_S``), falling back to the module defaults
    config set via :func:`set_init_retry_defaults`; the last failure
    propagates unchanged."""
    retries = max(env_int(INIT_RETRIES_ENV, _DEFAULT_INIT_RETRIES), 0)
    backoff = max(env_float(INIT_RETRY_BACKOFF_ENV, _DEFAULT_INIT_RETRY_BACKOFF_S), 0.0)
    kwargs = {} if timeout is None else {"initialization_timeout": timeout}
    for attempt in range(retries + 1):
        try:
            jax.distributed.initialize(coordinator_address=coord, num_processes=nproc,
                                       process_id=pid, **kwargs)
            return
        except Exception as exc:
            if attempt >= retries:
                raise
            # a failed initialize leaves jax's global distributed state
            # assigned (client, and on rank 0 the coordinator service), so
            # without a reset every later attempt would die on 'distributed
            # .initialize should only be called once' instead of retrying
            try:
                jax.distributed.shutdown()
            except Exception as reset_exc:
                logger.debug(f"init_distributed: state reset between retries "
                             f"raised {reset_exc!r} (continuing)")
            delay = backoff * (2 ** attempt)
            logger.warning(f"init_distributed: attempt {attempt + 1}/{retries + 1} "
                           f"failed ({exc!r}); retrying in {delay:.2f}s")
            if delay > 0:
                time.sleep(delay)


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank(group=None) -> int:
    """Rank within ``group`` (a comm.groups.ProcessGroup) or the process index
    (reference comm.py:547 — group=None means the world group)."""
    if group is not None and hasattr(group, "rank"):
        return group.rank()
    return jax.process_index()

def get_world_size(group=None) -> int:
    """Size of ``group`` (device count over its mesh axes) or the host-process
    world size (device-level parallelism is the mesh's business)."""
    if group is not None and hasattr(group, "size"):
        return group.size()
    return jax.process_count()


def get_local_rank() -> int:
    return 0  # one process per host owns all local chips in JAX


def barrier(group=None, timeout_s: Optional[float] = None):
    """Synchronize all processes/devices (reference comm.py:521).

    Bounded: with a resolved timeout (arg > config/env default) a barrier a
    peer never reaches raises :class:`CollectiveTimeoutError` instead of
    blocking forever; the heartbeat records 'in barrier' either way."""

    def _sync():
        x = jnp.zeros(())
        x.block_until_ready()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("dstpu_barrier")

    return bounded_collective(_sync, timeout_s=timeout_s, name="barrier")


# --------------------------------------------------------------------------
# In-graph collectives (usable under shard_map / pjit with named mesh axes)
# --------------------------------------------------------------------------

AxisArg = Union[str, Sequence[str]]  # or a comm.groups.ProcessGroup


def _axes(axis):
    """Unwrap a ProcessGroup into its mesh-axes tuple (lax takes str|tuple)."""
    ax = getattr(axis, "axes", axis)
    return ax if isinstance(ax, str) else tuple(ax)


def _trace_log(op: str, x) -> None:
    cl = get_comms_logger()
    if cl.should_profile(op):
        try:
            cl.record_traced(op, int(np.prod(x.shape)) * x.dtype.itemsize)
        except Exception as exc:  # odd operand (no shape/dtype): skip the sample
            warning_once(f"comms logger: could not size a traced {op} operand "
                         f"({exc!r}); that collective is missing from the summary")


def all_reduce(x, axis: AxisArg, op: str = "sum"):
    """lax.psum/pmax/pmin over a mesh axis (reference comm.py:478 all_reduce)."""
    _trace_log("all_reduce", x)
    axis = _axes(axis)
    if op == "sum":
        return lax.psum(x, axis)
    if op == "avg" or op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(x, axis: AxisArg, *, tiled: bool = True, gather_dim: int = 0):
    """Gather shards along a mesh axis (reference all_gather_into_tensor comm.py:308).
    tiled=True concatenates along ``gather_dim`` (the flat-bucket layout ZeRO uses)."""
    _trace_log("all_gather", x)
    return lax.all_gather(x, _axes(axis), axis=gather_dim, tiled=tiled)


def reduce_scatter(x, axis: AxisArg, *, scatter_dim: int = 0, tiled: bool = True):
    """Reduce + scatter shards (reference reduce_scatter_fn comm.py:246)."""
    _trace_log("reduce_scatter", x)
    return lax.psum_scatter(x, _axes(axis), scatter_dimension=scatter_dim, tiled=tiled)


def all_to_all(x, axis: AxisArg, *, split_dim: int, concat_dim: int, tiled: bool = True):
    """All-to-all over a mesh axis (reference all_to_all_single comm.py:334) —
    the Ulysses/MoE dispatch primitive."""
    _trace_log("all_to_all", x)
    return lax.all_to_all(x, _axes(axis), split_axis=split_dim, concat_axis=concat_dim, tiled=tiled)


def ppermute(x, axis: AxisArg, perm):
    """Point-to-point ring shift — the TPU-native analog of pipeline p2p send/recv
    (reference runtime/pipe/p2p.py:50,71); perm is [(src, dst), ...]."""
    _trace_log("ppermute", x)
    return lax.ppermute(x, _axes(axis), perm)


def axis_index(axis: AxisArg):
    if hasattr(axis, "axis_index"):
        return axis.axis_index()  # ProcessGroup: linearized over its axes
    return lax.axis_index(axis)


def broadcast(x, axis: AxisArg, src: int = 0):
    """Broadcast the src rank's shard to all ranks on the axis (comm.py:222).
    Implemented as select + psum (ppermute requires unique sources; select rather
    than multiply so non-src NaN/Inf shards cannot poison the sum)."""
    _trace_log("broadcast", x)
    axis = _axes(axis)
    idx = lax.axis_index(axis)
    contribution = jnp.where(idx == src, x, jnp.zeros_like(x))
    return lax.psum(contribution, axis)


# --------------------------------------------------------------------------
# Host-level (eager) collectives over the global topology
# --------------------------------------------------------------------------


def _timed(op_name):

    def deco(fn):

        @functools.wraps(fn)
        def wrapper(*args, log_name=None, **kwargs):
            cl = get_comms_logger()
            if not cl.should_profile(op_name):
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            x = args[0]
            size = int(np.prod(np.shape(x))) * jnp.asarray(x).dtype.itemsize
            world = get_topology().world_size
            cl.append(op_name, log_name or op_name, dt, size, world)
            return out

        return wrapper

    return deco


_REDUCERS = {
    "sum": jnp.sum,
    "avg": jnp.mean,
    "mean": jnp.mean,
    "max": jnp.max,
    "min": jnp.min,
    "prod": jnp.prod,
}


@functools.lru_cache(maxsize=None)
def _host_reduce_fn(op: str):
    reducer = _REDUCERS[op]
    return jax.jit(lambda v: reducer(v, axis=0))


@_timed("all_reduce")
def host_all_reduce(x, topo: Optional[MeshTopology] = None, op: str = "sum"):
    """Eager reduction over the leading ("per-contributor") axis of a global array.

    In single-controller JAX, arrays are globally consistent — there is no eager
    per-rank value to reduce the way torch.distributed.all_reduce does.  The
    control-plane uses (overflow consensus, loss averaging) stack contributions on
    the leading axis; in-graph consensus belongs inside the jitted step via
    ``all_reduce``.  The jitted reducer is cached per op (no per-call retrace).
    """
    if op not in _REDUCERS:
        raise ValueError(f"unsupported reduce op {op!r}; one of {sorted(_REDUCERS)}")
    if jnp.ndim(x) == 0:
        raise ValueError("host_all_reduce expects a leading contributor axis; got a scalar")
    return _host_reduce_fn(op)(x)


def host_broadcast(x, topo: Optional[MeshTopology] = None):
    """Replicate a host value across all devices (reference _broadcast_model
    engine.py:1052 analog: rank0's value wins; with SPMD jax arrays the host value
    is already consistent, so this is a device_put with replicated sharding)."""
    topo = topo or get_topology()
    return jax.device_put(x, topo.replicated())


def log_summary(show_straggler=False):
    """Reference dist.log_summary (comm/comm.py:422)."""
    return get_comms_logger().log_summary(show_straggler=show_straggler)


def monitor_events(step: int = 0):
    """Comms-logger summary as monitor ``(tag, value, step)`` events, for the
    telemetry collector's event stream (empty when nothing was profiled)."""
    return get_comms_logger().as_events(step)


def configure(comms_config=None):
    if comms_config is not None:
        get_comms_logger().configure(comms_config)
