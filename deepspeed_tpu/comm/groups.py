"""Process-group abstraction over mesh axes.

Analog of the reference's group machinery (deepspeed/utils/groups.py factory
functions + torch.distributed ``new_group``, comm.py:181): where the reference
builds NCCL communicators from explicit rank lists, the TPU-native "group" is
a SCOPE OVER NAMED MESH AXES — every collective in this codebase already takes
axis names, so a ProcessGroup is a first-class handle bundling axes with
rank/size queries usable both eagerly (host planning) and in-graph
(lax.axis_index).

Arbitrary rank subsets are intentionally unsupported: GSPMD collectives ride
the mesh's factorization, and every reference use-case (dp/tp/ep/sp/pp
subgroups, hpZ secondary shards, local all-to-all groups) is an axis — or an
axis factoring, which MeshTopology owns.  ``new_group(ranks=...)`` therefore
raises with guidance instead of silently doing something slow.
"""

from typing import Optional, Sequence, Tuple, Union

import numpy as np

from ..parallel.mesh import (DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, PIPE_AXIS,
                             SEQUENCE_AXIS, TENSOR_AXIS, MeshTopology, get_topology)


class ProcessGroup:
    """A communication scope = an ordered tuple of mesh axes."""

    def __init__(self, axes: Union[str, Sequence[str]], topology: Optional[MeshTopology] = None):
        self.axes: Tuple[str, ...] = (axes,) if isinstance(axes, str) else tuple(axes)
        self._topo = topology
        for a in self.axes:
            if a not in self.topology.mesh.axis_names:
                raise ValueError(f"unknown mesh axis {a!r}; mesh has {self.topology.mesh.axis_names}")

    @property
    def topology(self) -> MeshTopology:
        return self._topo or get_topology()

    # ------------------------------------------------------------------ sizes
    def size(self) -> int:
        s = 1
        for a in self.axes:
            s *= self.topology.axis_size(a)
        return s

    # ------------------------------------------------------------------ ranks
    def axis_index(self):
        """In-graph rank along this group (call inside shard_map/jit):
        linearized over the group's axes, first axis slowest."""
        from jax import lax
        idx = 0
        for a in self.axes:
            idx = idx * self.topology.axis_size(a) + lax.axis_index(a)
        return idx

    def rank(self) -> int:
        """Eager rank: the group-coordinate of this process's FIRST addressable
        device in the mesh (host-side planning; in-graph code uses
        axis_index())."""
        import jax
        mesh = self.topology.mesh
        dev = jax.local_devices()[0]
        coords = np.argwhere(mesh.devices == dev)
        if coords.size == 0:  # device not in mesh (e.g. cpu fallback): rank 0
            return 0
        coord = coords[0]
        names = mesh.axis_names
        r = 0
        for a in self.axes:
            r = r * self.topology.axis_size(a) + int(coord[names.index(a)])
        return r

    def __repr__(self):
        return f"ProcessGroup(axes={self.axes}, size={self.size()})"


def new_group(axes: Union[str, Sequence[str], None] = None, ranks=None,
              topology: Optional[MeshTopology] = None) -> ProcessGroup:
    """Create a group scope (reference comm.new_group:181).

    Pass ``axes`` (a mesh axis name or tuple).  Passing torch-style ``ranks``
    raises: arbitrary subsets don't map to GSPMD — re-factor the mesh instead
    (MeshTopology.from_axis_dict), which is how hpZ/qgZ/MoE groups are built.
    """
    if ranks is not None:
        raise NotImplementedError(
            "rank-list groups don't exist under GSPMD — declare a mesh axis for "
            "the scope (MeshTopology.from_axis_dict) and pass axes=...; every "
            "reference subgroup (dp/tp/ep/sp, hpZ secondary, local a2a) is an "
            "axis or an axis factoring")
    if axes is None:
        # torch.distributed.new_group() with no args means ALL ranks
        return get_world_group(topology)
    return ProcessGroup(axes, topology)


# ---------------------------------------------------------- named accessors
# Reference utils/groups.py surface (``_get_data_parallel_group`` etc.)
def get_world_group(topology: Optional[MeshTopology] = None) -> ProcessGroup:
    topo = topology or get_topology()
    return ProcessGroup(tuple(topo.mesh.axis_names), topo)


def get_data_parallel_group(topology: Optional[MeshTopology] = None) -> ProcessGroup:
    """dp = data x fsdp (the ZeRO sharding scope, reference seq_data_parallel)."""
    return ProcessGroup((DATA_AXIS, FSDP_AXIS), topology)


def get_model_parallel_group(topology: Optional[MeshTopology] = None) -> ProcessGroup:
    return ProcessGroup((TENSOR_AXIS,), topology)


def get_expert_parallel_group(topology: Optional[MeshTopology] = None) -> ProcessGroup:
    return ProcessGroup((EXPERT_AXIS,), topology)


def get_sequence_parallel_group(topology: Optional[MeshTopology] = None) -> ProcessGroup:
    return ProcessGroup((SEQUENCE_AXIS,), topology)


def get_pipe_parallel_group(topology: Optional[MeshTopology] = None) -> ProcessGroup:
    return ProcessGroup((PIPE_AXIS,), topology)
