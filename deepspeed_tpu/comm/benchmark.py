"""Collective bandwidth microbenchmarks — the ``ds_bench`` analog.

The reference ships ``bin/ds_bench`` (driving DeepSpeedExamples' comm sweep) and
tracks allgather bucket bandwidth as a tuning signal (allgather_bucket_size 5e8,
runtime/zero/config.py:105,124).  Here each op is timed as a jitted shard_map
collective over the live topology: the reported **algbw** is message_bytes/time
and **busbw** applies the standard ring-correction factor ((n-1)/n for
allgather/reduce-scatter, 2(n-1)/n for allreduce) so numbers are comparable to
NCCL-tests / the reference's CommsLogger accounting (utils/comms_logging.py:67).
"""

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..compat import shard_map
from ..parallel.mesh import MeshTopology, get_topology
from . import comm


def _sync(x):
    # value fetch: the only true sync on remote-relay backends
    jax.block_until_ready(x)
    leaf = jax.tree_util.tree_leaves(x)[0]
    np.asarray(jax.device_get(leaf.ravel()[0]))


def _time_op(fn, x, iters: int) -> float:
    # always re-feed the ORIGINAL input: the output's sharding generally differs
    # from in_specs, and feeding it back would hide a reshard+recompile inside
    # the timed region. Dispatch is async, so iterations still pipeline.
    out = fn(x)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def collective_bandwidth(op: str = "all_gather",
                         elems: int = int(5e8 // 2),
                         dtype=jnp.bfloat16,
                         axis: str = "data",
                         topology: Optional[MeshTopology] = None,
                         iters: int = 10,
                         compiled_loop: bool = False) -> Dict[str, float]:
    """Measure one collective's bandwidth over a mesh axis.

    ``elems`` is the GLOBAL bucket element count (default = the reference's
    5e8-element allgather bucket in bf16 bytes).  Returns {time_ms, algbw_gbps,
    busbw_gbps, world, bytes}.

    ``compiled_loop`` runs all ``iters`` inside ONE jitted fori_loop with a
    chained carry — use it on relay transports (axon), where per-call dispatch
    round-trips would otherwise dominate the timing.
    """
    topo = topology or get_topology()
    world = topo.axis_size(axis)
    mesh = topo.mesh
    elems = int(elems) // (world * 128) * (world * 128) or world * 128
    itemsize = jnp.dtype(dtype).itemsize
    spec_sharded = PartitionSpec(axis)
    spec_rep = PartitionSpec()

    if op == "all_gather":
        in_spec, out_spec = spec_sharded, spec_rep
        body = lambda x: comm.all_gather(x, axis)
        factor = (world - 1) / world
    elif op == "reduce_scatter":
        in_spec, out_spec = spec_rep, spec_sharded
        body = lambda x: comm.reduce_scatter(x, axis)
        factor = (world - 1) / world
    elif op == "all_reduce":
        in_spec, out_spec = spec_rep, spec_rep
        body = lambda x: comm.all_reduce(x, axis)
        factor = 2 * (world - 1) / world
    elif op == "all_to_all":
        in_spec, out_spec = spec_sharded, spec_sharded
        body = lambda x: comm.all_to_all(x, axis, split_dim=0, concat_dim=0)
        factor = (world - 1) / world
    else:
        raise ValueError(f"unknown op {op!r}")

    if compiled_loop:
        # the whole iteration loop in one program: the per-shard input is fed
        # through the collective, and a slice of each result perturbs the next
        # input so XLA cannot elide the repeats
        from jax import lax

        def looped(x):
            def step(i, acc):
                out = body(acc)
                return acc + out.ravel()[0] * 0.0  # depend on this iteration
            return lax.fori_loop(0, iters, step, x)

        shard_fn = jax.jit(
            shard_map(looped, mesh=mesh, in_specs=in_spec, out_specs=in_spec,
                      check_vma=False))
        x = jax.device_put(jnp.zeros((elems,), dtype), NamedSharding(mesh, in_spec))
        _sync(shard_fn(x))  # compile + settle
        t0 = time.perf_counter()
        _sync(shard_fn(x))
        dt = (time.perf_counter() - t0) / iters
    else:
        shard_fn = jax.jit(
            shard_map(body, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                      check_vma=False))
        x = jax.device_put(jnp.zeros((elems,), dtype),
                           NamedSharding(mesh, in_spec))
        dt = _time_op(shard_fn, x, iters)
    nbytes = elems * itemsize
    algbw = nbytes / dt / 1e9
    return {
        "op": op,
        "time_ms": dt * 1e3,
        "algbw_gbps": algbw,
        "busbw_gbps": algbw * factor,
        "world": world,
        "bytes": nbytes,
    }


def run_sweep(ops=("all_gather", "all_reduce", "reduce_scatter", "all_to_all"),
              elems: int = int(5e8 // 2), axis: str = "data",
              topology: Optional[MeshTopology] = None, iters: int = 10):
    """Sweep the standard ops at the reference bucket size; returns a list of
    result dicts (and prints a table when run as a CLI via bin/dstpu_bench)."""
    topo = topology or get_topology()
    if topo.axis_size(axis) <= 1:
        return []
    return [collective_bandwidth(op, elems=elems, axis=axis, topology=topo, iters=iters)
            for op in ops]


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(description="dstpu collective microbench (ds_bench analog)")
    parser.add_argument("--elems", type=float, default=5e8 / 2)
    parser.add_argument("--axis", default="data")
    parser.add_argument("--iters", type=int, default=10)
    parser.add_argument("--ops", nargs="*", default=["all_gather", "all_reduce", "reduce_scatter", "all_to_all"])
    args = parser.parse_args(argv)
    from ..parallel.mesh import set_topology
    try:
        topo = get_topology()
    except Exception:
        topo = MeshTopology.from_axis_dict({args.axis: jax.device_count()})
        set_topology(topo)
    results = run_sweep(args.ops, elems=int(args.elems), axis=args.axis, topology=topo, iters=args.iters)
    if not results:
        print(f"axis {args.axis!r} has world size 1 — nothing to measure")
        return
    print(f"{'op':<16}{'bytes':>14}{'time_ms':>10}{'algbw GB/s':>12}{'busbw GB/s':>12}")
    for r in results:
        print(f"{r['op']:<16}{r['bytes']:>14}{r['time_ms']:>10.2f}{r['algbw_gbps']:>12.2f}{r['busbw_gbps']:>12.2f}")


if __name__ == "__main__":
    main()
