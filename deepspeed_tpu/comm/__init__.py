from .comm import (ReduceOp, all_gather, all_reduce, all_to_all, axis_index, barrier, broadcast, configure,
                   get_local_rank, get_rank, get_world_size, host_all_reduce, host_broadcast, init_distributed,
                   is_initialized, log_summary, ppermute, reduce_scatter)
