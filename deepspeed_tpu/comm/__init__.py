from .comm import (CollectiveTimeoutError, ReduceOp, all_gather, all_reduce, all_to_all, axis_index, barrier,
                   bounded_collective, broadcast, configure, get_local_rank, get_rank, get_world_size,
                   host_all_reduce, host_broadcast, init_distributed, is_initialized, log_summary, ppermute,
                   reduce_scatter, set_default_collective_timeout, set_init_retry_defaults)
from .groups import (ProcessGroup, get_data_parallel_group, get_expert_parallel_group,
                     get_model_parallel_group, get_pipe_parallel_group,
                     get_sequence_parallel_group, get_world_group, new_group)
