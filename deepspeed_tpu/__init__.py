"""deepspeed_tpu — a TPU-native large-model training & inference framework.

Public API analog of deepspeed/__init__.py: ``initialize()`` returns
(engine, optimizer, dataloader, lr_scheduler); ``init_distributed`` is re-exported
from comm (reference __init__.py:64,263).
"""

__version__ = "0.1.0"

from typing import Any, Callable, Optional, Tuple

from . import comm
from .comm import init_distributed
from .runtime import zero
from .parallel.mesh import MeshTopology
from .runtime.config import TrainingConfig, load_config
from .runtime.checkpointing import CheckpointError
from .runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader


def initialize(args=None,
               model: Optional[Callable] = None,
               loss_fn: Optional[Callable] = None,
               model_parameters: Any = None,
               training_data=None,
               config=None,
               topology: Optional[MeshTopology] = None,
               dist_init_required: Optional[bool] = None,
               collate_fn=None,
               tp_rules=None,
               param_init_fn: Optional[Callable] = None,
               **kwargs):
    """Build a training engine (reference deepspeed.initialize, __init__.py:64).

    TPU-native contract: the model is a pure loss function
    ``loss_fn(params, batch, rng) -> loss`` (or ``(loss, aux)``) over a params
    pytree — pass it as ``loss_fn`` (or as ``model`` if it's callable; objects
    exposing ``.loss_fn`` — e.g. deepspeed_tpu.models — are unwrapped).

    Returns (engine, optimizer, training_dataloader, lr_scheduler) like the
    reference; optimizer/lr_scheduler live inside the engine (functional state)
    and are surfaced for API parity.
    """
    from .runtime.engine import Engine

    cfg = load_config(config)
    if args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config and config is None:
        cfg = load_config(args.deepspeed_config)

    # fault-tolerance knobs must land BEFORE process-group setup: the retry
    # loop they bound runs inside init_distributed() (agent-exported env
    # still wins over these defaults)
    comm.set_init_retry_defaults(cfg.fault_tolerance.init_retries,
                                 cfg.fault_tolerance.init_retry_backoff_s)
    comm.set_default_collective_timeout(cfg.fault_tolerance.collective_timeout_s)

    if dist_init_required is None or dist_init_required:
        init_distributed()

    fn = loss_fn
    if fn is None and model is not None:
        fn = getattr(model, "loss_fn", model if callable(model) else None)
    if fn is None:
        raise ValueError("initialize() needs loss_fn (or a callable/loss_fn-bearing model)")
    if model_parameters is None:
        model_parameters = getattr(model, "params", None)
    if model_parameters is None:
        raise ValueError("initialize() needs model_parameters (the params pytree)")

    if tp_rules is None and model is not None:
        tp_rules = getattr(model, "tp_rules", None)

    from .models import transformer as _transformer
    # The reference swaps attention modules for SparseSelfAttention when the
    # JSON's sparse_attention section is set (sparse_self_attention.py:99).
    # Functionally: this engine's loss_fn is wrapped so the configured kernel
    # (or explicitly None) is the default attention DURING ITS OWN TRACING —
    # per-engine scoping, so a second initialize() in the same process can
    # neither inherit nor clobber another engine's attention math.
    sparse_fn = None
    if cfg.sparse_attention is not None:
        from .ops.sparse_attention.attention import make_config_attention_fn
        from .utils.logging import log_dist
        sparse_fn = make_config_attention_fn(cfg.sparse_attention)
        log_dist(f"sparse_attention: blocksparse kernel "
                 f"(mode={cfg.sparse_attention.mode}, block={cfg.sparse_attention.block}) "
                 f"is this engine's default attention_fn for models routed "
                 f"through models.transformer.attention_block", ranks=[0])
    fn = _transformer.scoped_default_attention(fn, sparse_fn)

    # Random-LTD (reference convert_to_random_ltd rewrites modules from config
    # alone, data_routing/helper.py:11): scope an LTD state around the loss_fn
    # the same way sparse attention is scoped.  Model forwards that support
    # token dropping (the in-repo zoo routes through transformer.random_ltd_scan)
    # read it at trace time; the engine ramps state["keep"] on the reference
    # schedule and re-jits at each budget step.  Opaque loss_fns that ignore
    # the state still get the loud warning below.
    ltd_state = None
    if cfg.data_efficiency.enabled and cfg.data_efficiency.data_routing.enabled:
        from .runtime.data_pipeline.random_ltd import RandomLTDScheduler
        from .utils.logging import log_dist
        scheduler = RandomLTDScheduler(cfg.data_efficiency.data_routing.random_ltd)
        ltd_state = {"keep": scheduler.current_tokens, "scheduler": scheduler}
        fn = _transformer.scoped_random_ltd(fn, ltd_state)
        log_dist(f"data_routing: random-LTD scoped as this engine's token-drop "
                 f"state (keep ramps {scheduler.min_tokens}->{scheduler.max_tokens} "
                 f"over {scheduler.total_steps} steps).  Engages for forwards that "
                 f"read configured_ltd() and take rng (llama-family zoo models); "
                 f"the engine warns after step 1 if the traced loss_fn never "
                 f"engaged it", ranks=[0])

    engine = Engine(loss_fn=fn, params=model_parameters, config=cfg, topology=topology, tp_rules=tp_rules,
                    param_init_fn=param_init_fn,
                    ltd_state=ltd_state,
                    layer_fn=kwargs.pop("layer_fn", None), head_fn=kwargs.pop("head_fn", None),
                    stem_fn=kwargs.pop("stem_fn", None))

    dataloader = None
    if training_data is not None:
        curriculum = cfg.effective_curriculum()
        if curriculum is not None:
            from .runtime.dataloader import CurriculumDataLoader
            from .utils.logging import log_dist
            dataloader = CurriculumDataLoader(
                training_data,
                batch_size=engine.train_batch_size,
                gradient_accumulation_steps=engine.gradient_accumulation_steps,
                curriculum=curriculum,
                seed=cfg.data_efficiency.seed if cfg.data_efficiency.enabled else cfg.seed,
                collate_fn=collate_fn)
            log_dist(f"data_efficiency: curriculum data sampler active "
                     f"(schedule={curriculum.get('schedule_type', curriculum.get('curriculum_type'))}, "
                     f"min={curriculum.get('min_difficulty')}, max={curriculum.get('max_difficulty')})",
                     ranks=[0])
        else:
            dataloader = DeepSpeedDataLoader(training_data,
                                             batch_size=engine.train_batch_size,
                                             seed=cfg.seed,
                                             collate_fn=collate_fn)
    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def init_inference(*args, **kwargs):
    """Reference deepspeed.init_inference (__init__.py:263) — see inference.engine."""
    from .inference import init_inference as _init
    return _init(*args, **kwargs)


def add_config_arguments(parser):
    """Reference add_config_arguments (__init__.py:240)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true")
    group.add_argument("--deepspeed_config", default=None, type=str)
    return parser
