"""BLOOM causal LM (bigscience/bloom family).

Parity: reference module_inject/containers/bloom.py + replace_policy BLOOM —
the reference serves BLOOM via kernel injection; here it's a first-class
family.  Architecture: embedding LayerNorm after the word embeddings, ALiBi
positional biases (no rotary/learned positions), per-head-interleaved fused
QKV with biases, sequential residuals, tanh-gelu 4x MLP with biases, tied
unembedding.

ALiBi: each head h adds slope_h * key_index to its attention scores — the
key-only form is softmax-equivalent to the relative-distance form (each query
row differs by a constant), which is exactly how HF builds the bias
(modeling_bloom.build_alibi_tensor).  Attention runs through a local
biased-sdpa in training (the generic attention_fn hook has no bias slot);
serving goes through ``forward_with_cache`` (v1) or ``forward_paged`` (v2
ragged serving — the paged kernel's ``alibi_slopes`` operand carries the
key-only bias, ops/attention/paged.py).
"""

import dataclasses
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import causal_lm_batch, count_params, cross_entropy_loss, layer_norm


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 4096
    num_layers: int = 30
    num_heads: int = 32
    max_seq_len: int = 2048
    ln_eps: float = 1e-5
    remat: bool = True

    @staticmethod
    def bloom_7b1():
        return BloomConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, seq=64):
        return BloomConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                           num_heads=heads, max_seq_len=seq)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """The ALiBi slope schedule (train-short-test-long paper; HF
    build_alibi_tensor): powers of 2^(-8/n) for the nearest power-of-two head
    count, interleaved extras for the rest."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return np.asarray(pow2_slopes(num_heads), np.float32)
    closest = 2 ** math.floor(math.log2(num_heads))
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


def _biased_sdpa(q, k, v, slopes, kpos, causal_mask):
    """sdpa with per-head ALiBi key bias.  q/k/v [B, S(q/k), H, D];
    kpos [Sk] absolute key positions; causal_mask [Sq, Sk] bool."""
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    bias = slopes[None, :, None, None] * kpos[None, None, None, :].astype(jnp.float32)
    scores = scores + bias
    scores = jnp.where(causal_mask[None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def init_params(config: BloomConfig, key, dtype=jnp.float32):
    D, L, V = config.hidden_size, config.num_layers, config.vocab_size
    ks = jax.random.split(key, 5)
    s = D ** -0.5

    def stack(k, shape):
        return jax.random.normal(k, (L, *shape), dtype) * s

    return {
        "embed": jax.random.normal(ks[0], (V, D), dtype) * 0.02,
        "embed_ln_w": jnp.ones((D,), dtype), "embed_ln_b": jnp.zeros((D,), dtype),
        "layers": {
            "ln1_w": jnp.ones((L, D), dtype), "ln1_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), dtype), "ln2_b": jnp.zeros((L, D), dtype),
            # fused per-head-interleaved qkv: [D, 3D] with rows grouped (q,k,v)
            # per head (the HF layout, split in _split_qkv)
            "w_qkv": stack(ks[1], (D, 3 * D)), "b_qkv": jnp.zeros((L, 3 * D), dtype),
            "wo": stack(ks[2], (D, D)), "bo": jnp.zeros((L, D), dtype),
            "fc1": stack(ks[3], (D, 4 * D)), "b_fc1": jnp.zeros((L, 4 * D), dtype),
            "fc2": stack(ks[4], (4 * D, D)), "b_fc2": jnp.zeros((L, D), dtype),
        },
        "final_ln_w": jnp.ones((D,), dtype), "final_ln_b": jnp.zeros((D,), dtype),
    }


def num_params(config: BloomConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _split_qkv(config: BloomConfig, fused, b, s):
    """[B, S, 3D] per-head-interleaved -> q/k/v [B, S, H, Dh] each."""
    H = config.num_heads
    Dh = config.hidden_size // H
    fused = fused.reshape(b, s, H, 3, Dh)
    return fused[..., 0, :], fused[..., 1, :], fused[..., 2, :]


def _block(config: BloomConfig, lp, x, slopes, kpos, causal_mask):
    b, s, D = x.shape
    h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], config.ln_eps)
    qkv = h @ lp["w_qkv"].astype(x.dtype) + lp["b_qkv"].astype(x.dtype)
    q, k, v = _split_qkv(config, qkv, b, s)
    attn = _biased_sdpa(q, k, v, slopes, kpos, causal_mask)
    x = x + attn.reshape(b, s, D) @ lp["wo"].astype(x.dtype) + lp["bo"].astype(x.dtype)
    h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], config.ln_eps)
    h = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype),
                    approximate=True)
    return x + h @ lp["fc2"].astype(x.dtype) + lp["b_fc2"].astype(x.dtype)


def forward(config: BloomConfig, params, input_ids, attention_fn=None):
    del attention_fn  # ALiBi needs the biased local attention
    b, s = input_ids.shape
    slopes = jnp.asarray(alibi_slopes(config.num_heads))
    kpos = jnp.arange(s)
    causal_mask = kpos[None, :] <= kpos[:, None]
    x = params["embed"][input_ids]
    x = layer_norm(x, params["embed_ln_w"], params["embed_ln_b"], config.ln_eps)

    def body(h, lp):
        return _block(config, lp, h, slopes, kpos, causal_mask), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    return x @ params["embed"].T.astype(x.dtype)  # tied unembed


def make_loss_fn(config: BloomConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"])
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


def tp_rules(path: str, shape) -> "int | None":
    """Fused qkv is per-HEAD interleaved, so column-sharding dim 2 splits on
    head boundaries exactly (heads/tp per shard); its bias rides along.
    wo/fc2 row-parallel with replicated biases."""
    if path.endswith(("bo", "b_fc2")):
        return None
    if path.endswith(("b_qkv", "b_fc1")):
        return 1
    if path.endswith(("w_qkv", "fc1")):
        return 2
    if path.endswith(("wo", "fc2")):
        return 1
    return None


# ------------------------------------------------------------------ inference
def init_cache(config: BloomConfig, batch: int, max_seq: Optional[int] = None,
               dtype=jnp.bfloat16):
    """Dense KV cache for v1 incremental decoding (llama.init_cache layout)."""
    S = max_seq or config.max_seq_len
    L, H = config.num_layers, config.num_heads
    Dh = config.hidden_size // H
    return {
        "k": jnp.zeros((L, batch, S, H, Dh), dtype),
        "v": jnp.zeros((L, batch, S, H, Dh), dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def forward_with_cache(config: BloomConfig, params, input_ids, cache, attention_fn=None):
    """Incremental forward with ALiBi over absolute key positions."""
    del attention_fn
    b, s = input_ids.shape
    start = cache["len"]
    S_max = cache["k"].shape[2]
    slopes = jnp.asarray(alibi_slopes(config.num_heads))
    kpos = jnp.arange(S_max)
    qpos = start + jnp.arange(s)
    valid = kpos[None, :] < (start + s)
    causal_mask = jnp.logical_and(kpos[None, :] <= qpos[:, None], valid)
    x = params["embed"][input_ids].astype(cache["k"].dtype)
    x = layer_norm(x, params["embed_ln_w"], params["embed_ln_b"], config.ln_eps)

    def layer(x, inp):
        lp, kc, vc = inp
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], config.ln_eps)
        qkv = h @ lp["w_qkv"].astype(x.dtype) + lp["b_qkv"].astype(x.dtype)
        q, k, v = _split_qkv(config, qkv, b, s)
        kc = jax.lax.dynamic_update_slice_in_dim(kc, k, start, axis=1)
        vc = jax.lax.dynamic_update_slice_in_dim(vc, v, start, axis=1)
        attn = _biased_sdpa(q, kc, vc, slopes, kpos, causal_mask)
        x = x + attn.reshape(b, s, -1) @ lp["wo"].astype(x.dtype) + lp["bo"].astype(x.dtype)
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], config.ln_eps)
        h = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype),
                        approximate=True)
        x = x + h @ lp["fc2"].astype(x.dtype) + lp["b_fc2"].astype(x.dtype)
        return x, (kc, vc)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], cache["k"], cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["embed"].T.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v, "len": start + s}


def init_paged_cache(config: BloomConfig, num_blocks: int, block_size: int,
                     dtype=jnp.bfloat16):
    from .transformer import init_paged_kv_pool
    return init_paged_kv_pool(config.num_layers, config.num_heads,
                              config.hidden_size // config.num_heads,
                              num_blocks, block_size, dtype)


def forward_paged(config: BloomConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked BLOOM forward — ALiBi rides the paged kernel's
    ``alibi_slopes`` operand (key-only form, absolute key index), making BLOOM
    the 9th paged family (the reference's v2 zoo doesn't serve BLOOM at all;
    its v1 path injects ALiBi through the softmax op binding,
    ops/transformer/inference/op_binding/softmax.py).

    TP: fused per-head-interleaved qkv is column-sharded on head boundaries
    (tp_rules), so the local shard holds H/tp whole heads; each shard slices
    its own run of the slope schedule by mesh position.  The tied unembedding
    uses the replicated embedding, so logits come out full-vocab on every
    shard (no gather needed)."""
    from ..ops.attention.paged import paged_attention
    from .transformer import paged_chunk_indices

    b, tchunk = tokens.shape
    Dh = config.hidden_size // config.num_heads
    H = params["layers"]["w_qkv"].shape[-1] // (3 * Dh)  # local heads
    scale = 1.0 / np.sqrt(Dh)
    slopes = jnp.asarray(alibi_slopes(config.num_heads))
    if tp_axis is not None and H < config.num_heads:
        off = jax.lax.axis_index(tp_axis).astype(jnp.int32) * H
        slopes = jax.lax.dynamic_slice(slopes, (off,), (H,))
    safe_pos, valid, lengths, blk, off_tok = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    x = layer_norm(x, params["embed_ln_w"], params["embed_ln_b"], config.ln_eps)
    head_idx = jnp.arange(H)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], config.ln_eps)
        qkv = h @ lp["w_qkv"].astype(x.dtype) + lp["b_qkv"].astype(x.dtype)
        fused = qkv.reshape(b, tchunk, H, 3, Dh)
        q, k, v = fused[..., 0, :], fused[..., 1, :], fused[..., 2, :]
        kpool = kpool.at[blk[:, :, None], head_idx, off_tok[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off_tok[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale,
                              alibi_slopes=slopes)
        x = x + preduce(out.reshape(b, tchunk, H * Dh) @ lp["wo"].astype(x.dtype)) \
              + lp["bo"].astype(x.dtype)
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], config.ln_eps)
        h = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype),
                        approximate=True)
        x = x + preduce(h @ lp["fc2"].astype(x.dtype)) + lp["b_fc2"].astype(x.dtype)
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["embed"].T.astype(x.dtype)
    del gather_logits  # tied head is replicated: logits are already full-vocab
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------------------------- HF import
def config_from_hf(hf_config) -> BloomConfig:
    return BloomConfig(vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
                       num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
                       ln_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5))


def from_hf_state_dict(config: BloomConfig, state_dict, dtype=jnp.float32):
    """Convert a BloomForCausalLM state dict.  The fused query_key_value keeps
    HF's per-head (q, k, v) interleaving — _split_qkv consumes it directly."""
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    L = config.num_layers
    pre = "transformer.h.{}"
    stack = lambda fmt, transpose=True: hf_stack(state_dict, fmt, L, dtype, transpose)

    return {
        "embed": jnp.asarray(t("transformer.word_embeddings.weight"), dtype),
        "embed_ln_w": jnp.asarray(t("transformer.word_embeddings_layernorm.weight"), dtype),
        "embed_ln_b": jnp.asarray(t("transformer.word_embeddings_layernorm.bias"), dtype),
        "layers": {
            "ln1_w": stack(pre + ".input_layernorm.weight", False),
            "ln1_b": stack(pre + ".input_layernorm.bias", False),
            "ln2_w": stack(pre + ".post_attention_layernorm.weight", False),
            "ln2_b": stack(pre + ".post_attention_layernorm.bias", False),
            "w_qkv": stack(pre + ".self_attention.query_key_value.weight"),
            "b_qkv": stack(pre + ".self_attention.query_key_value.bias", False),
            "wo": stack(pre + ".self_attention.dense.weight"),
            "bo": stack(pre + ".self_attention.dense.bias", False),
            "fc1": stack(pre + ".mlp.dense_h_to_4h.weight"),
            "b_fc1": stack(pre + ".mlp.dense_h_to_4h.bias", False),
            "fc2": stack(pre + ".mlp.dense_4h_to_h.weight"),
            "b_fc2": stack(pre + ".mlp.dense_4h_to_h.bias", False),
        },
        "final_ln_w": jnp.asarray(t("transformer.ln_f.weight"), dtype),
        "final_ln_b": jnp.asarray(t("transformer.ln_f.bias"), dtype),
    }
