"""OPT causal LM (facebook/opt family).

Parity: reference inference/v2/model_implementations/opt (container + policy
serving OPT with blocked flash).  Architecture vs Llama: learned positional
embeddings (with OPT's +2 offset quirk), pre-LayerNorm blocks with biases,
standard MHA (no GQA), ReLU fc1/fc2 MLP, tied unembedding.

Training forward is a scan over stacked layers (ZeRO-3-friendly like
models/llama.py); ``forward_paged`` serves ragged batches through the Pallas
paged kernel (ops/attention/paged.py).
"""

import dataclasses
import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (causal_lm_batch, count_params, cross_entropy_loss,
                          init_paged_kv_pool, layer_norm, paged_chunk_indices, sdpa)

POS_OFFSET = 2  # OPT reserves the first two position slots (HF modeling_opt)


@dataclasses.dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 2048
    ln_eps: float = 1e-5
    remat: bool = True

    @staticmethod
    def opt_125m():
        return OPTConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, seq=64):
        return OPTConfig(vocab_size=vocab, hidden_size=hidden, ffn_dim=hidden * 4,
                         num_layers=layers, num_heads=heads, max_seq_len=seq)


def init_params(config: OPTConfig, key, dtype=jnp.float32):
    D, F, L = config.hidden_size, config.ffn_dim, config.num_layers
    ks = jax.random.split(key, 8)
    s = D ** -0.5

    def stack(k, shape):
        return jax.random.normal(k, (L, *shape), dtype) * s

    return {
        "embed": jax.random.normal(ks[0], (config.vocab_size, D), dtype) * 0.02,
        "pos_embed": jax.random.normal(ks[1], (config.max_seq_len + POS_OFFSET, D), dtype) * 0.02,
        "layers": {
            "ln1_w": jnp.ones((L, D), dtype), "ln1_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), dtype), "ln2_b": jnp.zeros((L, D), dtype),
            "wq": stack(ks[2], (D, D)), "wk": stack(ks[3], (D, D)),
            "wv": stack(ks[4], (D, D)), "wo": stack(ks[5], (D, D)),
            "bq": jnp.zeros((L, D), dtype), "bk": jnp.zeros((L, D), dtype),
            "bv": jnp.zeros((L, D), dtype), "bo": jnp.zeros((L, D), dtype),
            "fc1": stack(ks[6], (D, F)), "b_fc1": jnp.zeros((L, F), dtype),
            "fc2": stack(ks[7], (F, D)), "b_fc2": jnp.zeros((L, D), dtype),
        },
        "final_ln_w": jnp.ones((D,), dtype), "final_ln_b": jnp.zeros((D,), dtype),
    }


def num_params(config: OPTConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _block(config: OPTConfig, lp, x, attention_fn=None):
    b, s, D = x.shape
    H = config.num_heads
    Dh = D // H
    h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], config.ln_eps)
    q = (h @ lp["wq"].astype(x.dtype) + lp["bq"].astype(x.dtype)).reshape(b, s, H, Dh)
    k = (h @ lp["wk"].astype(x.dtype) + lp["bk"].astype(x.dtype)).reshape(b, s, H, Dh)
    v = (h @ lp["wv"].astype(x.dtype) + lp["bv"].astype(x.dtype)).reshape(b, s, H, Dh)
    attn = (attention_fn or sdpa)(q, k, v, causal=True)
    x = x + attn.reshape(b, s, D) @ lp["wo"].astype(x.dtype) + lp["bo"].astype(x.dtype)
    h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], config.ln_eps)
    h = jax.nn.relu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype))
    return x + h @ lp["fc2"].astype(x.dtype) + lp["b_fc2"].astype(x.dtype)


def forward(config: OPTConfig, params, input_ids, attention_fn=None):
    s = input_ids.shape[1]
    x = params["embed"][input_ids]
    x = x + params["pos_embed"][POS_OFFSET:POS_OFFSET + s][None].astype(x.dtype)

    def body(h, lp):
        return _block(config, lp, h, attention_fn), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    return x @ params["embed"].T.astype(x.dtype)  # tied unembed


def make_loss_fn(config: OPTConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


# --------------------------------------------------------- paged (ragged) serve
def init_paged_cache(config: OPTConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    return init_paged_kv_pool(config.num_layers, config.num_heads,
                              config.hidden_size // config.num_heads,
                              num_blocks, block_size, dtype)


def tp_rules(path: str, shape) -> "int | None":
    """v2 TP layout (reference inference/v2/model_implementations/sharding/):
    qkv + fc1 column-parallel WITH their biases; wo/fc2 row-parallel with
    replicated biases (added once, after the psum); embeddings/norms replicated
    (tied unembed keeps full-vocab logits on every shard)."""
    if path.endswith(("bo", "b_fc2")):
        return None  # row-parallel biases replicate (added once, post-psum)
    if path.endswith(("bq", "bk", "bv", "b_fc1")):
        return 1  # [L, out] -> shard with the matching column weight
    # bias checks precede weights: "b_fc1"/"b_fc2" suffix-match "fc1"/"fc2"
    if path.endswith(("wq", "wk", "wv", "fc1")):
        return 2  # [L, in, out] -> shard out
    if path.endswith(("wo", "fc2")):
        return 1  # [L, in, out] -> shard in
    return None


def forward_paged(config: OPTConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked OPT forward (learned positions — no rotary on K/Q).

    ``tp_axis``: inside shard_map with params sharded per tp_rules, names the
    mesh axis to psum row-parallel partials over.  Row-parallel biases (bo,
    b_fc2) are replicated and added AFTER the psum so they count once.  Local
    head counts derive from the shard shapes; the tied unembedding is
    replicated, so logits are always full-vocab (gather_logits is a no-op,
    accepted for the engine's uniform calling convention)."""
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    Dh = config.hidden_size // config.num_heads  # TP-invariant head dim
    H = params["layers"]["wq"].shape[-1] // Dh   # local (per-shard) heads
    scale = 1.0 / np.sqrt(Dh)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    x = x + params["pos_embed"][safe_pos + POS_OFFSET].astype(x.dtype)
    head_idx = jnp.arange(H)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], config.ln_eps)
        q = (h @ lp["wq"].astype(x.dtype) + lp["bq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (h @ lp["wk"].astype(x.dtype) + lp["bk"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        v = (h @ lp["wv"].astype(x.dtype) + lp["bv"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        x = x + preduce(out.reshape(b, tchunk, H * Dh) @ lp["wo"].astype(x.dtype)) \
            + lp["bo"].astype(x.dtype)
        h = layer_norm(x, lp["ln2_w"], lp["ln2_b"], config.ln_eps)
        h = jax.nn.relu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype))
        x = x + preduce(h @ lp["fc2"].astype(x.dtype)) + lp["b_fc2"].astype(x.dtype)
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["embed"].T.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------------------------- HF import
def config_from_hf(hf_config) -> OPTConfig:
    if not getattr(hf_config, "do_layer_norm_before", True):
        raise NotImplementedError(
            "post-LN OPT variants (do_layer_norm_before=False, e.g. opt-350m) "
            "are not supported — this implementation is pre-LN")
    if getattr(hf_config, "word_embed_proj_dim", hf_config.hidden_size) != hf_config.hidden_size:
        raise NotImplementedError(
            "OPT variants with word_embed_proj_dim != hidden_size (project_in/out "
            "layers, e.g. opt-350m) are not supported")
    return OPTConfig(vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
                     ffn_dim=hf_config.ffn_dim, num_layers=hf_config.num_hidden_layers,
                     num_heads=hf_config.num_attention_heads,
                     max_seq_len=hf_config.max_position_embeddings)


def from_hf_state_dict(config: OPTConfig, state_dict, dtype=jnp.float32):
    """Convert an OPTForCausalLM state dict (module_inject/load_checkpoint.py
    analog).  HF's learned positional table already contains the +2 offset
    rows; torch Linear [out, in] transposes to our [in, out]."""
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    L = config.num_layers
    pre = "model.decoder.layers.{}"
    stack = lambda fmt, transpose=True: hf_stack(state_dict, fmt, L, dtype, transpose)

    return {
        "embed": jnp.asarray(t("model.decoder.embed_tokens.weight"), dtype),
        "pos_embed": jnp.asarray(t("model.decoder.embed_positions.weight"), dtype),
        "layers": {
            "ln1_w": stack(pre + ".self_attn_layer_norm.weight", False),
            "ln1_b": stack(pre + ".self_attn_layer_norm.bias", False),
            "ln2_w": stack(pre + ".final_layer_norm.weight", False),
            "ln2_b": stack(pre + ".final_layer_norm.bias", False),
            "wq": stack(pre + ".self_attn.q_proj.weight"),
            "wk": stack(pre + ".self_attn.k_proj.weight"),
            "wv": stack(pre + ".self_attn.v_proj.weight"),
            "wo": stack(pre + ".self_attn.out_proj.weight"),
            "bq": stack(pre + ".self_attn.q_proj.bias", False),
            "bk": stack(pre + ".self_attn.k_proj.bias", False),
            "bv": stack(pre + ".self_attn.v_proj.bias", False),
            "bo": stack(pre + ".self_attn.out_proj.bias", False),
            "fc1": stack(pre + ".fc1.weight"),
            "b_fc1": stack(pre + ".fc1.bias", False),
            "fc2": stack(pre + ".fc2.weight"),
            "b_fc2": stack(pre + ".fc2.bias", False),
        },
        "final_ln_w": jnp.asarray(t("model.decoder.final_layer_norm.weight"), dtype),
        "final_ln_b": jnp.asarray(t("model.decoder.final_layer_norm.bias"), dtype),
    }
