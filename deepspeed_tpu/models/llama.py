"""Llama-family causal LM — the flagship training model.

Parity target: the reference trains Llama-2 via HF + ZeRO-3 (BASELINE.md config
ladder) and serves it via inference/v2/model_implementations/llama_v2.  This is
a TPU-first implementation: stacked-layer params swept by ``lax.scan`` (one
compiled block; per-layer ZeRO-3 gather), per-layer ``jax.checkpoint`` remat,
bf16 compute with fp32 reductions, rotary + GQA attention.
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (apply_rotary, attention_block, cross_entropy_loss, init_linear,
                          kv_projection_shardable, paged_chunk_indices, rms_norm,
                          rotary_tables, sdpa, swiglu_mlp)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    remat: bool = True
    # dots_with_no_batch_dims_saveable keeps per-layer matmul outputs (cheap to
    # store, expensive to recompute) and recomputes the rest — measured ~1.5x
    # faster than nothing_saveable at 438M/seq2048 on v5e (53% vs 35% MFU)
    remat_policy: Optional[str] = "dots_with_no_batch_dims_saveable"

    @staticmethod
    def llama2_7b():
        return LlamaConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, seq=64):
        return LlamaConfig(vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 2,
                           num_layers=layers, num_heads=heads, num_kv_heads=kv_heads, max_seq_len=seq)


def init_params(config: LlamaConfig, key, dtype=jnp.float32):
    """Params pytree: per-layer leaves STACKED on dim 0 (num_layers) for scan."""
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    L, D, F = config.num_layers, config.hidden_size, config.intermediate_size
    H, KV = config.num_heads, config.num_kv_heads
    head_dim = D // H
    lk = jax.random.split(k_layers, 7)

    def stack(key, in_dim, out_dim):
        keys = jax.random.split(key, L)
        return jnp.stack([init_linear(k, in_dim, out_dim, dtype=dtype) for k in keys])

    params = {
        "embed": jax.random.normal(k_emb, (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "attn": {
                "wq": stack(lk[0], D, H * head_dim),
                "wk": stack(lk[1], D, KV * head_dim),
                "wv": stack(lk[2], D, KV * head_dim),
                "wo": stack(lk[3], H * head_dim, D),
            },
            "mlp": {
                "w_gate": stack(lk[4], D, F),
                "w_up": stack(lk[5], D, F),
                "w_down": stack(lk[6], F, D),
            },
            "attn_norm": jnp.ones((L, D), dtype),
            "mlp_norm": jnp.ones((L, D), dtype),
        },
        "final_norm": jnp.ones((D, ), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = init_linear(k_out, D, config.vocab_size, dtype=dtype)
    return params


def _layer_fn(config: LlamaConfig, cos, sin, attention_fn=None):
    from ..runtime.activation_checkpointing import checkpoint_name

    def layer(x, layer_params, positions=None):
        attn_in = rms_norm(x, layer_params["attn_norm"], config.rms_eps)
        attn_out, _ = attention_block(layer_params["attn"], attn_in,
                                      n_heads=config.num_heads, n_kv_heads=config.num_kv_heads,
                                      cos=cos, sin=sin, causal=True, attention_fn=attention_fn,
                                      positions=positions)
        # residual-stream names: identity unless an offload/naming remat policy
        # targets them (runtime/activation_checkpointing.py RESIDUAL_NAMES)
        x = checkpoint_name(x + attn_out, "attn_resid")
        mlp_in = rms_norm(x, layer_params["mlp_norm"], config.rms_eps)
        x = checkpoint_name(x + swiglu_mlp(layer_params["mlp"], mlp_in), "mlp_resid")
        return x, None

    return layer


def forward(config: LlamaConfig, params, input_ids, attention_fn=None, rng=None):
    """input_ids [B, S] -> logits [B, S, V].  When an engine-scoped random-LTD
    state is configured (initialize() with data_efficiency.data_routing) and an
    ``rng`` is provided, middle layers process a random token subset
    (transformer.random_ltd_scan)."""
    from .transformer import configured_ltd, random_ltd_scan
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]  # keep embed dtype (engine casts params)
    layer = _layer_fn(config, cos, sin, attention_fn)
    if config.remat:
        if config.remat_policy in ("offload_inputs", "cpu_checkpointing"):
            # real host-offloaded checkpointing (the policy-based offload
            # silently degrades to recompute — activation_checkpointing.py)
            from ..runtime.activation_checkpointing import offload_checkpoint
            layer = offload_checkpoint(layer)
        else:
            from ..runtime.activation_checkpointing import resolve_policy
            layer = jax.checkpoint(layer, policy=resolve_policy(config.remat_policy))
    ltd = configured_ltd()
    if ltd is not None and rng is not None:
        x = random_ltd_scan(layer, x, params["layers"], rng, int(ltd["keep"]))
    else:
        x, _ = jax.lax.scan(layer, x, params["layers"])
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    return x @ head.astype(x.dtype)


def make_loss_fn(config: LlamaConfig, attention_fn=None) -> Callable:
    """loss_fn(params, batch, rng) for the engine; batch: {input_ids, labels}
    (labels = input_ids shifted; -100 = ignore)."""

    def loss_fn(params, batch, rng):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn,
                         rng=rng)
        return cross_entropy_loss(logits, batch["labels"])

    return loss_fn


def causal_lm_batch(input_ids: np.ndarray):
    """Build {input_ids, labels} with next-token labels from raw token rows."""
    labels = np.full_like(input_ids, -100)
    labels[:, :-1] = input_ids[:, 1:]
    return {"input_ids": input_ids, "labels": labels}


def tp_rules(path: str, shape) -> "int | None":
    """Tensor-parallel sharding rules — the Megatron-style column/row-parallel
    layout the reference receives via the external mpu (deepspeed/__init__.py:95)
    and that AutoTP autodetects for inference (module_inject/auto_tp.py:188).

    Column-parallel (shard output dim): wq/wk/wv, w_gate/w_up, lm_head.
    Row-parallel (shard input dim): wo, w_down.  Stacked layer leaves carry a
    leading L dim, so dims shift by one.
    """
    if path.endswith(("attn.wq", "mlp.w_gate", "mlp.w_up")):
        return 2  # [L, in, out] -> shard out
    if path.endswith(("attn.wk", "attn.wv")):
        # GQA/MQA kv projections replicate (see kv_projection_shardable)
        return 2 if kv_projection_shardable(shape) else None
    if path.endswith(("attn.wo", "mlp.w_down")):
        return 1  # [L, in, out] -> shard in
    if path == "lm_head":
        return 1  # [D, V] -> vocab-parallel logits
    return None


def make_tp_rules(config: LlamaConfig):
    """Config-aware v2 serving rules (inference/v2/tp.resolve_rules prefers
    these over the static ``tp_rules``): GQA kv projections shard
    head-aligned here — the v2 engine validates ``num_kv_heads % tp == 0``
    before sharding — while MQA (one kv head) REPLICATES, honoring
    validate_model's make_tp_rules escape hatch (same contract as falcon).
    The static rules keep GQA kv replicated instead: GSPMD auto layouts can
    be asked for sub-head kv shards (tp > kv_heads), which is both the wrong
    layout and an XLA miscompile (transformer.kv_projection_shardable)."""
    kv = config.num_kv_heads

    def rules(path: str, shape) -> "int | None":
        if path.endswith(("attn.wk", "attn.wv")):
            return 2 if kv > 1 else None
        return tp_rules(path, shape)

    return rules

def num_params(config: LlamaConfig) -> int:
    D, F, L, V = config.hidden_size, config.intermediate_size, config.num_layers, config.vocab_size
    H, KV = config.num_heads, config.num_kv_heads
    head_dim = D // H
    per_layer = (D * (H * head_dim) + 2 * D * (KV * head_dim) + (H * head_dim) * D
                 + D * F * 2 + F * D + 2 * D)
    total = V * D + L * per_layer + D
    if not config.tie_embeddings:
        total += D * V
    return total


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (6N + attention terms) for MFU accounting."""
    n = num_params(config)
    attn = 12 * config.num_layers * config.hidden_size * seq_len  # qk+av fwd+bwd
    return 6.0 * n + attn


# ------------------------------------------------------------------ inference
def init_cache(config: LlamaConfig, batch: int, max_seq: Optional[int] = None, dtype=jnp.bfloat16):
    """Dense KV cache pytree for incremental decoding: stacked per-layer
    [L, B, S_max, KV, Dh] k/v buffers (the v1-engine analog of the reference's
    inference_context workspace, csrc/transformer/inference/includes)."""
    S = max_seq or config.max_seq_len
    L, KV = config.num_layers, config.num_kv_heads
    Dh = config.hidden_size // config.num_heads
    return {
        "k": jnp.zeros((L, batch, S, KV, Dh), dtype),
        "v": jnp.zeros((L, batch, S, KV, Dh), dtype),
        "len": jnp.zeros((), jnp.int32),
    }


def forward_with_cache(config: LlamaConfig, params, input_ids, cache, attention_fn=None):
    """Incremental forward: consumes/extends the KV cache.

    input_ids [B, S] (prompt at prefill, 1 token at decode); returns
    (logits [B, S, V], new_cache).
    """
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len, config.rope_theta)
    b, s = input_ids.shape
    start = cache["len"]
    positions = start + jnp.arange(s)[None, :].repeat(b, axis=0)
    x = params["embed"][input_ids].astype(cache["k"].dtype)

    def layer(x, inp):
        lp, kc, vc = inp
        attn_in = rms_norm(x, lp["attn_norm"], config.rms_eps)
        attn_out, new_kv = attention_block(lp["attn"], attn_in,
                                           n_heads=config.num_heads, n_kv_heads=config.num_kv_heads,
                                           cos=cos, sin=sin, causal=True, attention_fn=attention_fn,
                                           positions=positions, kv_cache=(kc, vc, start))
        x = x + attn_out
        mlp_in = rms_norm(x, lp["mlp_norm"], config.rms_eps)
        x = x + swiglu_mlp(lp["mlp"], mlp_in)
        return x, (new_kv[0], new_kv[1])

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    logits = x @ head.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v, "len": start + s}


def from_hf_state_dict(config: LlamaConfig, state_dict, dtype=jnp.float32):
    """Convert a HuggingFace LlamaForCausalLM state dict to our params pytree
    (the checkpoint-loading analog of module_inject/load_checkpoint.py).

    torch Linear stores [out, in]; ours is [in, out] — transposed here.
    """
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    L = config.num_layers
    stack = lambda fmt, transpose=True: hf_stack(state_dict, fmt, L, dtype, transpose)

    params = {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), dtype),
        "layers": {
            "attn": {
                "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
                "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
                "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
                "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            },
            "mlp": {
                "w_gate": stack("model.layers.{}.mlp.gate_proj.weight"),
                "w_up": stack("model.layers.{}.mlp.up_proj.weight"),
                "w_down": stack("model.layers.{}.mlp.down_proj.weight"),
            },
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", transpose=False),
            "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight", transpose=False),
        },
        "final_norm": jnp.asarray(t("model.norm.weight"), dtype),
    }
    if not config.tie_embeddings:
        key = "lm_head.weight" if "lm_head.weight" in state_dict else "model.embed_tokens.weight"
        params["lm_head"] = jnp.asarray(t(key).T, dtype)
    return params


def abstract_params(config: LlamaConfig, dtype=jnp.float32):
    """Meta-device skeleton (zero bytes): the OnDevice/zero.Init abstract half
    (ref utils/init_on_device.py:12)."""
    return jax.eval_shape(lambda: init_params(config, jax.random.PRNGKey(0), dtype=dtype))


def hf_streaming_loader(config: LlamaConfig, get_tensor: Callable[[str], Any]):
    """Build a ``get_leaf`` for zero.Init.materialize_from_loader that streams a
    HuggingFace Llama checkpoint **one layer-tensor at a time** — the analog of
    shard-by-shard checkpoint loading into ZeRO-3 (module_inject/load_checkpoint.py).

    ``get_tensor(hf_name) -> array-like`` (e.g. a safetensors lazy handle or a
    torch state_dict lookup).  Stacked per-layer leaves are returned as slice
    callbacks, so a device owning layers [a:b) of wq only ever pulls those
    layers' tensors; peak host memory is O(one layer tensor), not O(leaf).
    """

    def t(name):
        w = get_tensor(name)
        w = w.float().numpy() if hasattr(w, "float") else np.asarray(w, dtype=np.float32)
        return w

    fmt = {
        "layers.attn.wq": ("model.layers.{}.self_attn.q_proj.weight", True),
        "layers.attn.wk": ("model.layers.{}.self_attn.k_proj.weight", True),
        "layers.attn.wv": ("model.layers.{}.self_attn.v_proj.weight", True),
        "layers.attn.wo": ("model.layers.{}.self_attn.o_proj.weight", True),
        "layers.mlp.w_gate": ("model.layers.{}.mlp.gate_proj.weight", True),
        "layers.mlp.w_up": ("model.layers.{}.mlp.up_proj.weight", True),
        "layers.mlp.w_down": ("model.layers.{}.mlp.down_proj.weight", True),
        "layers.attn_norm": ("model.layers.{}.input_layernorm.weight", False),
        "layers.mlp_norm": ("model.layers.{}.post_attention_layernorm.weight", False),
    }

    def get_leaf(path, leaf):
        if path == "embed":
            return t("model.embed_tokens.weight")
        if path == "final_norm":
            return t("model.norm.weight")
        if path == "lm_head":
            name = "lm_head.weight" if _has(get_tensor, "lm_head.weight") else "model.embed_tokens.weight"
            return t(name).T
        name_fmt, transpose = fmt[path]

        def slice_cb(idx):
            layers = range(*idx[0].indices(config.num_layers))
            parts = []
            for i in layers:
                w = t(name_fmt.format(i))
                if transpose:
                    w = w.T
                parts.append(w[idx[1:]] if len(idx) > 1 else w)
            return np.stack(parts)

        return slice_cb

    return get_leaf


def _has(get_tensor, name) -> bool:
    try:
        return get_tensor(name) is not None
    except Exception:
        return False


def config_from_hf(hf_config) -> LlamaConfig:
    """Build a LlamaConfig from a transformers LlamaConfig/MistralConfig."""
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        intermediate_size=hf_config.intermediate_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        num_kv_heads=getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads),
        max_seq_len=getattr(hf_config, "max_position_embeddings", 4096),
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=getattr(hf_config, "rms_norm_eps", 1e-5),
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )


# --------------------------------------------------------- paged (ragged) serve
def init_paged_cache(config: LlamaConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    """Paged KV pool (reference inference/v2/ragged blocked KV layout):
    [L, num_blocks, KV, block_size, Dh] — heads-major so the Pallas paged
    kernel's trailing (block_size, Dh) tile satisfies TPU tiling.  The last
    block is reserved as a trash target for padded-token writes."""
    L, KV = config.num_layers, config.num_kv_heads
    Dh = config.hidden_size // config.num_heads
    return {
        "k": jnp.zeros((L, num_blocks, KV, block_size, Dh), dtype),
        "v": jnp.zeros((L, num_blocks, KV, block_size, Dh), dtype),
    }


def forward_paged(config: LlamaConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, window: Optional[int] = None,
                  tp_axis: Optional[str] = None, gather_logits: bool = True):
    """Ragged chunked forward over the paged KV pool (FastGen model-forward
    analog, inference/v2/model_implementations/llama_v2 + blocked flash).

    tokens [N, T] (right-padded chunks), n_tokens [N] valid counts,
    start_pos [N] absolute start of this chunk, block_tables [N, MAXB]
    (padded entries point at the trash block).  ``window`` enables Mistral-style
    sliding-window attention.  Returns (logits [N, T, V], new kv_cache).

    Attention runs in the Pallas paged kernel (ops/attention/paged.py) on TPU —
    only live blocks are read via scalar-prefetched table indices; off-TPU the
    identical-math dense-gather fallback runs.

    ``tp_axis``: when called inside shard_map with params column/row-sharded per
    tp_rules and the KV pool sharded on its head dim, names the mesh axis to
    psum row-parallel partial outputs over (the TPU analog of the reference's
    v2 sharding helpers, inference/v2/model_implementations/sharding/qkv.py +
    attn.py + mlp.py + unembed.py).  Head counts are derived from the (local)
    param shapes, so the same code serves single-chip and TP-sharded.
    """
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len, config.rope_theta)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    Dh = config.hidden_size // config.num_heads  # true head dim: TP-invariant
    H = params["layers"]["attn"]["wq"].shape[-1] // Dh   # local (per-shard) heads
    KV = params["layers"]["attn"]["wk"].shape[-1] // Dh
    scale = 1.0 / np.sqrt(Dh)
    head_idx = jnp.arange(KV)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        attn_in = rms_norm(x, lp["attn_norm"], config.rms_eps)
        q = (attn_in @ lp["attn"]["wq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (attn_in @ lp["attn"]["wk"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        v = (attn_in @ lp["attn"]["wv"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        q = apply_rotary(q, cos, sin, safe_pos)
        k = apply_rotary(k, cos, sin, safe_pos)
        # pool [NB, KV, bs, Dh]: pool[blk, h, off] = k[n, t, h]
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale, window=window)
        x = x + preduce(out.reshape(b, tchunk, H * Dh) @ lp["attn"]["wo"].astype(x.dtype))
        mlp_in = rms_norm(x, lp["mlp_norm"], config.rms_eps)
        x = x + preduce(swiglu_mlp(lp["mlp"], mlp_in))
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    logits = x @ head.astype(x.dtype)
    if tp_axis is not None and gather_logits and not config.tie_embeddings:
        # lm_head is vocab-parallel (tp_rules: lm_head dim 1): gather shards.
        # Greedy decode skips this (gather_logits=False) and argmaxes the
        # vocab-local shard instead — O(1) scalars over ICI per token, not O(V).
        logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    return logits, {"k": new_k, "v": new_v}
