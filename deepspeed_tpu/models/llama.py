"""Llama-family causal LM — the flagship training model.

Parity target: the reference trains Llama-2 via HF + ZeRO-3 (BASELINE.md config
ladder) and serves it via inference/v2/model_implementations/llama_v2.  This is
a TPU-first implementation: stacked-layer params swept by ``lax.scan`` (one
compiled block; per-layer ZeRO-3 gather), per-layer ``jax.checkpoint`` remat,
bf16 compute with fp32 reductions, rotary + GQA attention.
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (apply_rotary, attention_block, cross_entropy_loss, init_linear, rms_norm, rotary_tables,
                          sdpa, swiglu_mlp)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    remat: bool = True
    remat_policy: Optional[str] = "nothing_saveable"

    @staticmethod
    def llama2_7b():
        return LlamaConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, seq=64):
        return LlamaConfig(vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 2,
                           num_layers=layers, num_heads=heads, num_kv_heads=kv_heads, max_seq_len=seq)


def init_params(config: LlamaConfig, key, dtype=jnp.float32):
    """Params pytree: per-layer leaves STACKED on dim 0 (num_layers) for scan."""
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    L, D, F = config.num_layers, config.hidden_size, config.intermediate_size
    H, KV = config.num_heads, config.num_kv_heads
    head_dim = D // H
    lk = jax.random.split(k_layers, 7)

    def stack(key, in_dim, out_dim):
        keys = jax.random.split(key, L)
        return jnp.stack([init_linear(k, in_dim, out_dim, dtype=dtype) for k in keys])

    params = {
        "embed": jax.random.normal(k_emb, (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "attn": {
                "wq": stack(lk[0], D, H * head_dim),
                "wk": stack(lk[1], D, KV * head_dim),
                "wv": stack(lk[2], D, KV * head_dim),
                "wo": stack(lk[3], H * head_dim, D),
            },
            "mlp": {
                "w_gate": stack(lk[4], D, F),
                "w_up": stack(lk[5], D, F),
                "w_down": stack(lk[6], F, D),
            },
            "attn_norm": jnp.ones((L, D), dtype),
            "mlp_norm": jnp.ones((L, D), dtype),
        },
        "final_norm": jnp.ones((D, ), dtype),
    }
    if not config.tie_embeddings:
        params["lm_head"] = init_linear(k_out, D, config.vocab_size, dtype=dtype)
    return params


def _layer_fn(config: LlamaConfig, cos, sin, attention_fn=None):

    def layer(x, layer_params):
        attn_in = rms_norm(x, layer_params["attn_norm"], config.rms_eps)
        attn_out, _ = attention_block(layer_params["attn"], attn_in,
                                      n_heads=config.num_heads, n_kv_heads=config.num_kv_heads,
                                      cos=cos, sin=sin, causal=True, attention_fn=attention_fn)
        x = x + attn_out
        mlp_in = rms_norm(x, layer_params["mlp_norm"], config.rms_eps)
        x = x + swiglu_mlp(layer_params["mlp"], mlp_in)
        return x, None

    return layer


def forward(config: LlamaConfig, params, input_ids, attention_fn=None):
    """input_ids [B, S] -> logits [B, S, V]."""
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]  # keep embed dtype (engine casts params)
    layer = _layer_fn(config, cos, sin, attention_fn)
    if config.remat:
        policy = getattr(jax.checkpoint_policies, config.remat_policy, None) if config.remat_policy else None
        layer = jax.checkpoint(layer, policy=policy)
    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    return x @ head.astype(x.dtype)


def make_loss_fn(config: LlamaConfig, attention_fn=None) -> Callable:
    """loss_fn(params, batch, rng) for the engine; batch: {input_ids, labels}
    (labels = input_ids shifted; -100 = ignore)."""

    def loss_fn(params, batch, rng):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])

    return loss_fn


def causal_lm_batch(input_ids: np.ndarray):
    """Build {input_ids, labels} with next-token labels from raw token rows."""
    labels = np.full_like(input_ids, -100)
    labels[:, :-1] = input_ids[:, 1:]
    return {"input_ids": input_ids, "labels": labels}


def tp_rules(path: str, shape) -> "int | None":
    """Tensor-parallel sharding rules — the Megatron-style column/row-parallel
    layout the reference receives via the external mpu (deepspeed/__init__.py:95)
    and that AutoTP autodetects for inference (module_inject/auto_tp.py:188).

    Column-parallel (shard output dim): wq/wk/wv, w_gate/w_up, lm_head.
    Row-parallel (shard input dim): wo, w_down.  Stacked layer leaves carry a
    leading L dim, so dims shift by one.
    """
    if path.endswith(("attn.wq", "attn.wk", "attn.wv", "mlp.w_gate", "mlp.w_up")):
        return 2  # [L, in, out] -> shard out
    if path.endswith(("attn.wo", "mlp.w_down")):
        return 1  # [L, in, out] -> shard in
    if path == "lm_head":
        return 1  # [D, V] -> vocab-parallel logits
    return None


def num_params(config: LlamaConfig) -> int:
    D, F, L, V = config.hidden_size, config.intermediate_size, config.num_layers, config.vocab_size
    H, KV = config.num_heads, config.num_kv_heads
    head_dim = D // H
    per_layer = (D * (H * head_dim) + 2 * D * (KV * head_dim) + (H * head_dim) * D
                 + D * F * 2 + F * D + 2 * D)
    total = V * D + L * per_layer + D
    if not config.tie_embeddings:
        total += D * V
    return total


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (6N + attention terms) for MFU accounting."""
    n = num_params(config)
    attn = 12 * config.num_layers * config.hidden_size * seq_len  # qk+av fwd+bwd
    return 6.0 * n + attn
