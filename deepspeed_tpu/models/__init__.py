from . import bert, gpt2, llama, transformer
from .bert import BertConfig
from .gpt2 import GPT2Config
from .llama import LlamaConfig
from . import mixtral
from .mixtral import MixtralConfig
