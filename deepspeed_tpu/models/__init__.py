from . import (bert, bloom, falcon, gpt2, gptj, llama, mistral, mixtral, opt,
               phi, qwen, transformer)
from .bert import BertConfig
from .bloom import BloomConfig
from .falcon import FalconConfig
from .gpt2 import GPT2Config
from .gptj import GPTJConfig
from .llama import LlamaConfig
from .mistral import MistralConfig
from .mixtral import MixtralConfig
from .opt import OPTConfig
from .phi import PhiConfig
from .qwen import QwenConfig
