from . import bert, bloom, gpt2, gptj, llama, transformer
from .bert import BertConfig
from .bloom import BloomConfig
from .gpt2 import GPT2Config
from .gptj import GPTJConfig
from .llama import LlamaConfig
from . import mixtral
from .mixtral import MixtralConfig
