"""GPT-2 style causal LM (learned positions, LayerNorm, GeLU MLP).

Parity: the reference's config ladder step 2 (GPT2-350M + ZeRO-2 + FusedAdam,
BASELINE.md) and module_inject's gpt2 policies.
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import cross_entropy_loss, default_attention, gelu_mlp, init_linear, layer_norm, sdpa


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    max_seq_len: int = 1024
    ln_eps: float = 1e-5
    remat: bool = True

    @staticmethod
    def gpt2_350m():
        return GPT2Config()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, seq=64):
        return GPT2Config(vocab_size=vocab, hidden_size=hidden, num_layers=layers, num_heads=heads, max_seq_len=seq)


def init_params(config: GPT2Config, key, dtype=jnp.float32):
    L, D, V = config.num_layers, config.hidden_size, config.vocab_size
    keys = jax.random.split(key, 8)

    def stack(key, in_dim, out_dim):
        ks = jax.random.split(key, L)
        return jnp.stack([init_linear(k, in_dim, out_dim, dtype=dtype) for k in ks])

    return {
        "wte": jax.random.normal(keys[0], (V, D), dtype) * 0.02,
        "wpe": jax.random.normal(keys[1], (config.max_seq_len, D), dtype) * 0.01,
        "layers": {
            "ln1_w": jnp.ones((L, D), dtype), "ln1_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), dtype), "ln2_b": jnp.zeros((L, D), dtype),
            "attn": {
                "w_qkv": stack(keys[2], D, 3 * D),
                "b_qkv": jnp.zeros((L, 3 * D), dtype),
                "w_proj": stack(keys[3], D, D),
                "b_proj": jnp.zeros((L, D), dtype),
            },
            "mlp": {
                "w_fc1": stack(keys[4], D, 4 * D),
                "b_fc1": jnp.zeros((L, 4 * D), dtype),
                "w_fc2": stack(keys[5], 4 * D, D),
                "b_fc2": jnp.zeros((L, D), dtype),
            },
        },
        "lnf_w": jnp.ones((D, ), dtype),
        "lnf_b": jnp.zeros((D, ), dtype),
    }


def forward(config: GPT2Config, params, input_ids, attention_fn=None):
    b, s = input_ids.shape
    x = params["wte"][input_ids] + params["wpe"][:s][None]
    H = config.num_heads
    attn_fn = attention_fn or default_attention()

    def layer(x, lp):
        h = layer_norm(x, lp["ln1_w"], lp["ln1_b"], config.ln_eps)
        qkv = h @ lp["attn"]["w_qkv"].astype(h.dtype) + lp["attn"]["b_qkv"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        d = q.shape[-1] // H
        q = q.reshape(b, s, H, d)
        k = k.reshape(b, s, H, d)
        v = v.reshape(b, s, H, d)
        att = attn_fn(q, k, v, causal=True).reshape(b, s, H * d)
        x = x + att @ lp["attn"]["w_proj"].astype(h.dtype) + lp["attn"]["b_proj"].astype(h.dtype)
        h2 = layer_norm(x, lp["ln2_w"], lp["ln2_b"], config.ln_eps)
        x = x + gelu_mlp(lp["mlp"], h2)
        return x, None

    if config.remat:
        layer = jax.checkpoint(layer)
    x, _ = jax.lax.scan(layer, x, params["layers"])
    x = layer_norm(x, params["lnf_w"], params["lnf_b"], config.ln_eps)
    return x @ params["wte"].T.astype(x.dtype)  # tied head


def make_loss_fn(config: GPT2Config, attention_fn=None) -> Callable:

    def loss_fn(params, batch, rng):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])

    return loss_fn
