"""Falcon causal LM (tiiuae/falcon family).

Parity: reference inference/v2/model_implementations/falcon.  Architecture vs
Llama: PARALLEL attention+MLP off one shared input LayerNorm
(x + attn(ln(x)) + mlp(ln(x))), multi-query attention (1 KV head on 7B; GQA
on 40B), rotary embeddings, GELU 4x MLP, no projection biases, tied unembed.
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (apply_rotary, causal_lm_batch, count_params,
                          cross_entropy_loss, init_paged_kv_pool, layer_norm,
                          paged_chunk_indices, rotary_tables, sdpa)


@dataclasses.dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_layers: int = 32
    num_heads: int = 71
    num_kv_heads: int = 1          # MQA on falcon-7b
    max_seq_len: int = 2048
    ln_eps: float = 1e-5
    rope_theta: float = 10000.0
    remat: bool = True

    @staticmethod
    def falcon_7b():
        return FalconConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=1, seq=64):
        return FalconConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                            num_heads=heads, num_kv_heads=kv_heads, max_seq_len=seq)


def init_params(config: FalconConfig, key, dtype=jnp.float32):
    D, L, H, KV = config.hidden_size, config.num_layers, config.num_heads, config.num_kv_heads
    Dh = D // H
    ks = jax.random.split(key, 7)
    s = D ** -0.5

    def stack(k, shape):
        return jax.random.normal(k, (L, *shape), dtype) * s

    return {
        "embed": jax.random.normal(ks[0], (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "ln_w": jnp.ones((L, D), dtype), "ln_b": jnp.zeros((L, D), dtype),
            "wq": stack(ks[1], (D, H * Dh)), "wk": stack(ks[2], (D, KV * Dh)),
            "wv": stack(ks[3], (D, KV * Dh)), "wo": stack(ks[4], (H * Dh, D)),
            "fc1": stack(ks[5], (D, 4 * D)), "fc2": stack(ks[6], (4 * D, D)),
        },
        "final_ln_w": jnp.ones((D,), dtype), "final_ln_b": jnp.zeros((D,), dtype),
    }


def num_params(config: FalconConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _block(config: FalconConfig, lp, x, cos, sin, attention_fn=None):
    b, s, D = x.shape
    H, KV = config.num_heads, config.num_kv_heads
    Dh = D // H
    h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
    q = (h @ lp["wq"].astype(x.dtype)).reshape(b, s, H, Dh)
    k = (h @ lp["wk"].astype(x.dtype)).reshape(b, s, KV, Dh)
    v = (h @ lp["wv"].astype(x.dtype)).reshape(b, s, KV, Dh)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    attn = (attention_fn or sdpa)(q, k, v, causal=True)
    attn_out = attn.reshape(b, s, H * Dh) @ lp["wo"].astype(x.dtype)
    # HF Falcon's 'gelu' is the exact erf form, not tanh (phi's gelu_new IS tanh)
    mlp_out = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype), approximate=False) @ lp["fc2"].astype(x.dtype)
    return x + attn_out + mlp_out  # parallel residual


def forward(config: FalconConfig, params, input_ids, attention_fn=None):
    Dh = config.hidden_size // config.num_heads
    cos, sin = rotary_tables(Dh, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]

    def body(h, lp):
        return _block(config, lp, h, cos, sin, attention_fn), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    return x @ params["embed"].T.astype(x.dtype)


def make_loss_fn(config: FalconConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


# --------------------------------------------------------- paged (ragged) serve
def init_paged_cache(config: FalconConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    return init_paged_kv_pool(config.num_layers, config.num_kv_heads,
                              config.hidden_size // config.num_heads,
                              num_blocks, block_size, dtype)


def make_tp_rules(config: FalconConfig):
    """v2 TP layout (reference inference/v2/model_implementations/sharding/
    used by the falcon containers): wq/fc1 column-parallel, wo/fc2
    row-parallel, norms/embed replicated.  MQA (num_kv_heads == 1, falcon-7b):
    wk/wv and the KV pool REPLICATE — every shard computes the same single KV
    head (the reference's KV-replication fallback in sharding/qkv.py); GQA
    40B-style (kv > 1) shards them when divisible."""
    kv = config.num_kv_heads

    def rules(path: str, shape) -> "int | None":
        if path.endswith(("wq", "fc1")):
            return 2
        if path.endswith(("wk", "wv")):
            return 2 if kv > 1 else None
        if path.endswith(("wo", "fc2")):
            return 1
        return None

    return rules


def forward_paged(config: FalconConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked Falcon forward — MQA KV pool (1 KV head) through the
    Pallas paged kernel's GQA head mapping.

    ``tp_axis``: q heads shard; MQA's single KV head (and its pool) replicates
    across shards — each computes the identical k/v, the GQA mapping folds all
    local q heads onto it.  The parallel-residual psum covers attn+mlp in ONE
    reduction (attn_out + mlp_out summed before the psum).  Tied unembed keeps
    full-vocab logits (gather_logits accepted for the engine's convention)."""
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    Dh = config.hidden_size // config.num_heads  # TP-invariant
    H = params["layers"]["wq"].shape[-1] // Dh   # local q heads
    KV = kv_cache["k"].shape[2]                  # local kv heads (replicated MQA: full)
    scale = 1.0 / np.sqrt(Dh)
    cos, sin = rotary_tables(Dh, config.max_seq_len, config.rope_theta)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    head_idx = jnp.arange(KV)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
        q = (h @ lp["wq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (h @ lp["wk"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        v = (h @ lp["wv"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        q = apply_rotary(q, cos, sin, safe_pos)
        k = apply_rotary(k, cos, sin, safe_pos)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        attn_out = out.reshape(b, tchunk, H * Dh) @ lp["wo"].astype(x.dtype)
        mlp_out = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype),
                              approximate=False) @ lp["fc2"].astype(x.dtype)
        return x + preduce(attn_out + mlp_out), (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["embed"].T.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------------------------- HF import
def config_from_hf(hf_config) -> FalconConfig:
    if getattr(hf_config, "new_decoder_architecture", False):
        raise NotImplementedError(
            "new-decoder-architecture Falcon (40B/180B: ln_attn/ln_mlp split "
            "norms) is not supported by this importer")
    if getattr(hf_config, "alibi", False):
        raise NotImplementedError("alibi Falcon variants (falcon-rw) are not "
                                  "supported — this implementation is rotary")
    if getattr(hf_config, "bias", False):
        raise NotImplementedError("bias=True Falcon variants are not supported")
    if not getattr(hf_config, "parallel_attn", True):
        raise NotImplementedError("sequential-attention Falcon variants "
                                  "(parallel_attn=False) are not supported")
    # old decoder architecture: multi-query -> 1 kv head, else full MHA
    kv = 1 if getattr(hf_config, "multi_query", True) else hf_config.num_attention_heads
    return FalconConfig(vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
                        num_layers=hf_config.num_hidden_layers,
                        num_heads=hf_config.num_attention_heads, num_kv_heads=kv,
                        max_seq_len=getattr(hf_config, "max_position_embeddings", 2048),
                        ln_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5),
                        rope_theta=getattr(hf_config, "rope_theta", 10000.0))


def from_hf_state_dict(config: FalconConfig, state_dict, dtype=jnp.float32):
    """Convert a FalconForCausalLM state dict.  HF stores one FUSED
    query_key_value projection [ (H + 2*KV) * Dh, D ] laid out q-then-k-then-v
    (multi-query: all H query slices first); split into our wq/wk/wv."""
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    H, KV = config.num_heads, config.num_kv_heads
    Dh = config.hidden_size // H
    L = config.num_layers
    pre = "transformer.h.{}"

    wq, wk, wv = [], [], []
    for i in range(L):
        qkv = t(f"transformer.h.{i}.self_attention.query_key_value.weight")  # [(H+2KV)Dh, D]
        if KV == 1:  # multi-query: [q x H, k, v]
            q, k, v = qkv[:H * Dh], qkv[H * Dh:(H + 1) * Dh], qkv[(H + 1) * Dh:]
        else:  # grouped: interleaved per-group [q x (H/KV), k, v]
            grp = H // KV
            blocks = qkv.reshape(KV, (grp + 2) * Dh, -1)
            q = blocks[:, :grp * Dh].reshape(H * Dh, -1)
            k = blocks[:, grp * Dh:(grp + 1) * Dh].reshape(KV * Dh, -1)
            v = blocks[:, (grp + 1) * Dh:].reshape(KV * Dh, -1)
        wq.append(q.T)
        wk.append(k.T)
        wv.append(v.T)

    stack = lambda fmt, transpose=True: hf_stack(state_dict, fmt, L, dtype, transpose)

    return {
        "embed": jnp.asarray(t("transformer.word_embeddings.weight"), dtype),
        "layers": {
            "ln_w": stack(pre + ".input_layernorm.weight", False),
            "ln_b": stack(pre + ".input_layernorm.bias", False),
            "wq": jnp.asarray(np.stack(wq), dtype),
            "wk": jnp.asarray(np.stack(wk), dtype),
            "wv": jnp.asarray(np.stack(wv), dtype),
            "wo": stack(pre + ".self_attention.dense.weight"),
            "fc1": stack(pre + ".mlp.dense_h_to_4h.weight"),
            "fc2": stack(pre + ".mlp.dense_4h_to_h.weight"),
        },
        "final_ln_w": jnp.asarray(t("transformer.ln_f.weight"), dtype),
        "final_ln_b": jnp.asarray(t("transformer.ln_f.bias"), dtype),
    }
