"""Falcon causal LM (tiiuae/falcon family).

Parity: reference inference/v2/model_implementations/falcon.  Architecture vs
Llama: PARALLEL attention+MLP off one shared input LayerNorm
(x + attn(ln(x)) + mlp(ln(x))), multi-query attention (1 KV head on 7B; GQA
on 40B), rotary embeddings, GELU 4x MLP, no projection biases, tied unembed.
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (apply_rotary, causal_lm_batch, count_params,
                          cross_entropy_loss, init_paged_kv_pool, layer_norm,
                          paged_chunk_indices, rotary_tables, sdpa)


@dataclasses.dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_layers: int = 32
    num_heads: int = 71
    num_kv_heads: int = 1          # MQA on falcon-7b
    max_seq_len: int = 2048
    ln_eps: float = 1e-5
    rope_theta: float = 10000.0
    remat: bool = True

    @staticmethod
    def falcon_7b():
        return FalconConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=1, seq=64):
        return FalconConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                            num_heads=heads, num_kv_heads=kv_heads, max_seq_len=seq)


def init_params(config: FalconConfig, key, dtype=jnp.float32):
    D, L, H, KV = config.hidden_size, config.num_layers, config.num_heads, config.num_kv_heads
    Dh = D // H
    ks = jax.random.split(key, 7)
    s = D ** -0.5

    def stack(k, shape):
        return jax.random.normal(k, (L, *shape), dtype) * s

    return {
        "embed": jax.random.normal(ks[0], (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "ln_w": jnp.ones((L, D), dtype), "ln_b": jnp.zeros((L, D), dtype),
            "wq": stack(ks[1], (D, H * Dh)), "wk": stack(ks[2], (D, KV * Dh)),
            "wv": stack(ks[3], (D, KV * Dh)), "wo": stack(ks[4], (H * Dh, D)),
            "fc1": stack(ks[5], (D, 4 * D)), "fc2": stack(ks[6], (4 * D, D)),
        },
        "final_ln_w": jnp.ones((D,), dtype), "final_ln_b": jnp.zeros((D,), dtype),
    }


def num_params(config: FalconConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _block(config: FalconConfig, lp, x, cos, sin, attention_fn=None):
    b, s, D = x.shape
    H, KV = config.num_heads, config.num_kv_heads
    Dh = D // H
    h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
    q = (h @ lp["wq"].astype(x.dtype)).reshape(b, s, H, Dh)
    k = (h @ lp["wk"].astype(x.dtype)).reshape(b, s, KV, Dh)
    v = (h @ lp["wv"].astype(x.dtype)).reshape(b, s, KV, Dh)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    attn = (attention_fn or sdpa)(q, k, v, causal=True)
    attn_out = attn.reshape(b, s, H * Dh) @ lp["wo"].astype(x.dtype)
    mlp_out = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype), approximate=True) @ lp["fc2"].astype(x.dtype)
    return x + attn_out + mlp_out  # parallel residual


def forward(config: FalconConfig, params, input_ids, attention_fn=None):
    Dh = config.hidden_size // config.num_heads
    cos, sin = rotary_tables(Dh, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]

    def body(h, lp):
        return _block(config, lp, h, cos, sin, attention_fn), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    return x @ params["embed"].T.astype(x.dtype)


def make_loss_fn(config: FalconConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


# --------------------------------------------------------- paged (ragged) serve
def init_paged_cache(config: FalconConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    return init_paged_kv_pool(config.num_layers, config.num_kv_heads,
                              config.hidden_size // config.num_heads,
                              num_blocks, block_size, dtype)


def forward_paged(config: FalconConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int):
    """Ragged chunked Falcon forward — MQA KV pool (1 KV head) through the
    Pallas paged kernel's GQA head mapping."""
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    H, KV = config.num_heads, config.num_kv_heads
    Dh = config.hidden_size // H
    scale = 1.0 / np.sqrt(Dh)
    cos, sin = rotary_tables(Dh, config.max_seq_len, config.rope_theta)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    head_idx = jnp.arange(KV)[None, None, :]

    def layer(x, inp):
        lp, kpool, vpool = inp
        h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
        q = (h @ lp["wq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (h @ lp["wk"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        v = (h @ lp["wv"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        q = apply_rotary(q, cos, sin, safe_pos)
        k = apply_rotary(k, cos, sin, safe_pos)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        attn_out = out.reshape(b, tchunk, H * Dh) @ lp["wo"].astype(x.dtype)
        mlp_out = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype),
                              approximate=True) @ lp["fc2"].astype(x.dtype)
        return x + attn_out + mlp_out, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["embed"].T.astype(x.dtype)
    return logits, {"k": new_k, "v": new_v}
