"""GPT-J causal LM (EleutherAI/gpt-j-6B family).

Parity: reference module_inject/containers/gptj.py + replace_policy GPTJ
(module_inject/replace_policy.py) — the reference serves GPT-J through kernel
injection; here it's a first-class family.  Architecture: PARALLEL
attention+MLP off one shared LayerNorm (like Falcon), partial rotary with
GPT-J's INTERLEAVED convention (rotate_every_two — not the half-split used by
Llama/NeoX), no attention biases, biased fc_in/fc_out MLP with gelu_new,
untied lm_head WITH bias.
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (causal_lm_batch, count_params, cross_entropy_loss,
                          init_paged_kv_pool, layer_norm, paged_chunk_indices, sdpa)


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 4096
    ffn_dim: int = 16384
    num_layers: int = 28
    num_heads: int = 16
    rotary_dim: int = 64
    max_seq_len: int = 2048
    ln_eps: float = 1e-5
    remat: bool = True

    @staticmethod
    def gptj_6b():
        return GPTJConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, seq=64, rotary_dim=8):
        return GPTJConfig(vocab_size=vocab, hidden_size=hidden, ffn_dim=hidden * 4,
                          num_layers=layers, num_heads=heads, rotary_dim=rotary_dim,
                          max_seq_len=seq)


def interleaved_rotary_tables(rotary_dim: int, max_seq: int, base: float = 10000.0):
    """GPT-J's sincos tables with duplicate-interleave: each frequency's value
    repeats at dims (2i, 2i+1) — pairs rotate together (HF modeling_gptj
    ``create_sinusoidal_positions`` + ``duplicate_interleave``)."""
    inv_freq = 1.0 / (base ** (np.arange(0, rotary_dim, 2) / rotary_dim))
    freqs = np.einsum("i,j->ij", np.arange(max_seq), inv_freq)
    return (jnp.asarray(np.repeat(np.cos(freqs), 2, axis=1), jnp.float32),
            jnp.asarray(np.repeat(np.sin(freqs), 2, axis=1), jnp.float32))


def _rotate_every_two(x):
    x1, x2 = x[..., ::2], x[..., 1::2]
    return jnp.stack([-x2, x1], axis=-1).reshape(x.shape)


def apply_rotary_interleaved(x, cos, sin, positions=None):
    """x [B, S, H, rotary_dim]; GPT-J pairwise rotation."""
    if positions is None:
        s = x.shape[1]
        c, sn = cos[:s][None, :, None, :], sin[:s][None, :, None, :]
    else:
        c, sn = cos[positions][:, :, None, :], sin[positions][:, :, None, :]
    c, sn = c.astype(x.dtype), sn.astype(x.dtype)
    return x * c + _rotate_every_two(x) * sn


def init_params(config: GPTJConfig, key, dtype=jnp.float32):
    D, F, L, V = config.hidden_size, config.ffn_dim, config.num_layers, config.vocab_size
    ks = jax.random.split(key, 8)
    s = D ** -0.5

    def stack(k, shape):
        return jax.random.normal(k, (L, *shape), dtype) * s

    return {
        "embed": jax.random.normal(ks[0], (V, D), dtype) * 0.02,
        "layers": {
            "ln_w": jnp.ones((L, D), dtype), "ln_b": jnp.zeros((L, D), dtype),
            "wq": stack(ks[1], (D, D)), "wk": stack(ks[2], (D, D)),
            "wv": stack(ks[3], (D, D)), "wo": stack(ks[4], (D, D)),
            "fc_in": stack(ks[5], (D, F)), "b_fc_in": jnp.zeros((L, F), dtype),
            "fc_out": stack(ks[6], (F, D)), "b_fc_out": jnp.zeros((L, D), dtype),
        },
        "final_ln_w": jnp.ones((D,), dtype), "final_ln_b": jnp.zeros((D,), dtype),
        "lm_head": jax.random.normal(ks[7], (D, V), dtype) * s,
        "lm_head_b": jnp.zeros((V,), dtype),
    }


def num_params(config: GPTJConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _rotate_qk(config: GPTJConfig, q, k, cos, sin, positions=None):
    rd = config.rotary_dim
    q = jnp.concatenate([apply_rotary_interleaved(q[..., :rd], cos, sin, positions),
                         q[..., rd:]], axis=-1)
    k = jnp.concatenate([apply_rotary_interleaved(k[..., :rd], cos, sin, positions),
                         k[..., rd:]], axis=-1)
    return q, k


def _block(config: GPTJConfig, lp, x, cos, sin, attention_fn=None):
    b, s, D = x.shape
    H = config.num_heads
    Dh = D // H
    h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
    q = (h @ lp["wq"].astype(x.dtype)).reshape(b, s, H, Dh)
    k = (h @ lp["wk"].astype(x.dtype)).reshape(b, s, H, Dh)
    v = (h @ lp["wv"].astype(x.dtype)).reshape(b, s, H, Dh)
    q, k = _rotate_qk(config, q, k, cos, sin)
    attn = (attention_fn or sdpa)(q, k, v, causal=True)
    attn_out = attn.reshape(b, s, D) @ lp["wo"].astype(x.dtype)
    mlp = jax.nn.gelu(h @ lp["fc_in"].astype(x.dtype) + lp["b_fc_in"].astype(x.dtype),
                      approximate=True)
    mlp_out = mlp @ lp["fc_out"].astype(x.dtype) + lp["b_fc_out"].astype(x.dtype)
    return x + attn_out + mlp_out  # parallel residual


def forward(config: GPTJConfig, params, input_ids, attention_fn=None):
    cos, sin = interleaved_rotary_tables(config.rotary_dim, config.max_seq_len)
    x = params["embed"][input_ids]

    def body(h, lp):
        return _block(config, lp, h, cos, sin, attention_fn), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    return x @ params["lm_head"].astype(x.dtype) + params["lm_head_b"].astype(x.dtype)


def make_loss_fn(config: GPTJConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


def tp_rules(path: str, shape) -> "int | None":
    """Column: qkv + fc_in (+ its bias); row: wo/fc_out (bias replicated,
    added once post-psum); vocab-parallel lm_head + bias."""
    if path.endswith("b_fc_out"):
        return None
    if path.endswith("b_fc_in"):
        return 1
    if path.endswith(("wq", "wk", "wv", "fc_in")):
        return 2
    if path.endswith(("wo", "fc_out")):
        return 1
    if path == "lm_head":
        return 1
    if path == "lm_head_b":
        return 0
    return None


# --------------------------------------------------------- paged (ragged) serve
def init_paged_cache(config: GPTJConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    return init_paged_kv_pool(config.num_layers, config.num_heads,
                              config.hidden_size // config.num_heads,
                              num_blocks, block_size, dtype)


def forward_paged(config: GPTJConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked GPT-J forward — interleaved partial rotary feeds the
    paged kernel; the parallel residual reduces attn+mlp in one psum under TP;
    vocab-parallel biased head like phi."""
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    Dh = config.hidden_size // config.num_heads  # TP-invariant
    H = params["layers"]["wq"].shape[-1] // Dh   # local heads
    scale = 1.0 / np.sqrt(Dh)
    cos, sin = interleaved_rotary_tables(config.rotary_dim, config.max_seq_len)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    head_idx = jnp.arange(H)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
        q = (h @ lp["wq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (h @ lp["wk"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        v = (h @ lp["wv"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        q, k = _rotate_qk(config, q, k, cos, sin, safe_pos)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        attn_out = out.reshape(b, tchunk, H * Dh) @ lp["wo"].astype(x.dtype)
        mlp = jax.nn.gelu(h @ lp["fc_in"].astype(x.dtype) + lp["b_fc_in"].astype(x.dtype),
                          approximate=True)
        mlp_out = mlp @ lp["fc_out"].astype(x.dtype)
        x = x + preduce(attn_out + mlp_out) + lp["b_fc_out"].astype(x.dtype)
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["lm_head"].astype(x.dtype) + params["lm_head_b"].astype(x.dtype)
    if tp_axis is not None and gather_logits:
        logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------------------------- HF import
def config_from_hf(hf_config) -> GPTJConfig:
    return GPTJConfig(vocab_size=hf_config.vocab_size, hidden_size=hf_config.n_embd,
                      ffn_dim=hf_config.n_inner or 4 * hf_config.n_embd,
                      num_layers=hf_config.n_layer, num_heads=hf_config.n_head,
                      rotary_dim=hf_config.rotary_dim or hf_config.n_embd // hf_config.n_head,
                      max_seq_len=hf_config.n_positions,
                      ln_eps=getattr(hf_config, "layer_norm_epsilon", 1e-5))


def from_hf_state_dict(config: GPTJConfig, state_dict, dtype=jnp.float32):
    """Convert a GPTJForCausalLM state dict (no attention biases; biased
    fc_in/fc_out and lm_head; torch Linear [out, in] -> ours [in, out])."""
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    L = config.num_layers
    pre = "transformer.h.{}"
    stack = lambda fmt, transpose=True: hf_stack(state_dict, fmt, L, dtype, transpose)

    return {
        "embed": jnp.asarray(t("transformer.wte.weight"), dtype),
        "layers": {
            "ln_w": stack(pre + ".ln_1.weight", False),
            "ln_b": stack(pre + ".ln_1.bias", False),
            "wq": stack(pre + ".attn.q_proj.weight"),
            "wk": stack(pre + ".attn.k_proj.weight"),
            "wv": stack(pre + ".attn.v_proj.weight"),
            "wo": stack(pre + ".attn.out_proj.weight"),
            "fc_in": stack(pre + ".mlp.fc_in.weight"),
            "b_fc_in": stack(pre + ".mlp.fc_in.bias", False),
            "fc_out": stack(pre + ".mlp.fc_out.weight"),
            "b_fc_out": stack(pre + ".mlp.fc_out.bias", False),
        },
        "final_ln_w": jnp.asarray(t("transformer.ln_f.weight"), dtype),
        "final_ln_b": jnp.asarray(t("transformer.ln_f.bias"), dtype),
        "lm_head": jnp.asarray(t("lm_head.weight").T, dtype),
        "lm_head_b": jnp.asarray(t("lm_head.bias"), dtype),
    }
