"""Qwen2 causal LM (Qwen/Qwen2 family).

Parity: reference inference/v2/model_implementations/qwen.  Qwen2 is the
Llama architecture with BIASES on the Q/K/V projections (output projection
and MLP stay bias-free) — so everything delegates to models/llama with the
bias terms folded in by pre-adding them through a wrapped forward.

Implementation note: rather than forking llama's scan, the qkv biases are
threaded as extra per-layer params and applied via a custom block that calls
the same building blocks (transformer.attention_block has no bias slot, so
the block is written out here; the paged path mirrors llama.forward_paged
with the three bias adds).
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import llama
from .llama import LlamaConfig
from .transformer import (apply_rotary, count_params, cross_entropy_loss,
                          paged_chunk_indices, rms_norm, rotary_tables, sdpa, swiglu_mlp)


@dataclasses.dataclass(frozen=True)
class QwenConfig(LlamaConfig):

    @staticmethod
    def qwen2_7b():
        return QwenConfig(vocab_size=152064, hidden_size=3584, intermediate_size=18944,
                          num_layers=28, num_heads=28, num_kv_heads=4,
                          max_seq_len=32768, rope_theta=1000000.0)

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, seq=64):
        return QwenConfig(vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 2,
                          num_layers=layers, num_heads=heads, num_kv_heads=kv_heads,
                          max_seq_len=seq)


def init_params(config: QwenConfig, key, dtype=jnp.float32):
    """Llama params + per-layer q/k/v biases."""
    params = llama.init_params(config, key, dtype)
    L = config.num_layers
    H, KV = config.num_heads, config.num_kv_heads
    Dh = config.hidden_size // H
    params["layers"]["attn"]["bq"] = jnp.zeros((L, H * Dh), dtype)
    params["layers"]["attn"]["bk"] = jnp.zeros((L, KV * Dh), dtype)
    params["layers"]["attn"]["bv"] = jnp.zeros((L, KV * Dh), dtype)
    return params


def num_params(config: QwenConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _block(config: QwenConfig, lp, x, cos, sin, attention_fn=None):
    b, s, D = x.shape
    H, KV = config.num_heads, config.num_kv_heads
    Dh = D // H
    a = lp["attn"]
    attn_in = rms_norm(x, lp["attn_norm"], config.rms_eps)
    q = (attn_in @ a["wq"].astype(x.dtype) + a["bq"].astype(x.dtype)).reshape(b, s, H, Dh)
    k = (attn_in @ a["wk"].astype(x.dtype) + a["bk"].astype(x.dtype)).reshape(b, s, KV, Dh)
    v = (attn_in @ a["wv"].astype(x.dtype) + a["bv"].astype(x.dtype)).reshape(b, s, KV, Dh)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    out = (attention_fn or sdpa)(q, k, v, causal=True)
    x = x + out.reshape(b, s, H * Dh) @ a["wo"].astype(x.dtype)
    mlp_in = rms_norm(x, lp["mlp_norm"], config.rms_eps)
    return x + swiglu_mlp(lp["mlp"], mlp_in)


def forward(config: QwenConfig, params, input_ids, attention_fn=None):
    Dh = config.hidden_size // config.num_heads
    cos, sin = rotary_tables(Dh, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]

    def body(h, lp):
        return _block(config, lp, h, cos, sin, attention_fn), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    return x @ head.astype(x.dtype)


def make_loss_fn(config: QwenConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


causal_lm_batch = llama.causal_lm_batch
init_paged_cache = llama.init_paged_cache


def tp_rules(path: str, shape) -> "int | None":
    """Llama's column/row layout + qwen's qkv biases sharded with their
    column-parallel weights ([L, out] -> dim 1)."""
    if path.endswith("attn.bq"):
        return 1
    if path.endswith(("attn.bk", "attn.bv")):
        # kv biases must follow their weights: the static rules replicate GQA
        # kv projections (transformer.kv_projection_shardable — a bias's
        # [L, out] shape can't even distinguish GQA), so a sharded bias here
        # would hint the sub-head kv layout the weight rule exists to prevent;
        # make_tp_rules restores head-aligned sharding where config is known
        return None
    return llama.tp_rules(path, shape)


def make_tp_rules(config: QwenConfig):
    """v2 serving rules: GQA kv (weights AND their biases) shards
    head-aligned (the v2 engine validates kv % tp == 0 first), MQA
    replicates (validate_model's make_tp_rules contract); static tp_rules
    keep GQA kv replicated for GSPMD layouts
    (transformer.kv_projection_shardable)."""
    kv = config.num_kv_heads

    def rules(path: str, shape) -> "int | None":
        if path.endswith(("attn.wk", "attn.wv")):
            return 2 if kv > 1 else None
        if path.endswith(("attn.bk", "attn.bv")):
            return 1 if kv > 1 else None
        return tp_rules(path, shape)

    return rules

def forward_paged(config: QwenConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked Qwen2 forward: llama's paged layer + qkv bias adds.

    ``tp_axis`` threads TP exactly like llama.forward_paged (head-sharded
    KV pool, psum after row-parallel wo/w_down, vocab-parallel lm_head);
    the qkv biases ride their column-parallel weights' shards."""
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    cos, sin = rotary_tables(config.hidden_size // config.num_heads,
                             config.max_seq_len, config.rope_theta)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    Dh = config.hidden_size // config.num_heads            # TP-invariant
    H = params["layers"]["attn"]["wq"].shape[-1] // Dh     # local heads
    KV = params["layers"]["attn"]["wk"].shape[-1] // Dh
    scale = 1.0 / np.sqrt(Dh)
    head_idx = jnp.arange(KV)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        a = lp["attn"]
        attn_in = rms_norm(x, lp["attn_norm"], config.rms_eps)
        q = (attn_in @ a["wq"].astype(x.dtype) + a["bq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (attn_in @ a["wk"].astype(x.dtype) + a["bk"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        v = (attn_in @ a["wv"].astype(x.dtype) + a["bv"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        q = apply_rotary(q, cos, sin, safe_pos)
        k = apply_rotary(k, cos, sin, safe_pos)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        x = x + preduce(out.reshape(b, tchunk, H * Dh) @ a["wo"].astype(x.dtype))
        mlp_in = rms_norm(x, lp["mlp_norm"], config.rms_eps)
        x = x + preduce(swiglu_mlp(lp["mlp"], mlp_in))
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    logits = x @ head.astype(x.dtype)
    if tp_axis is not None and gather_logits and not config.tie_embeddings:
        logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------------------------- HF import
def config_from_hf(hf_config) -> QwenConfig:
    base = llama.config_from_hf(hf_config)
    return QwenConfig(**dataclasses.asdict(base))


def from_hf_state_dict(config: QwenConfig, state_dict, dtype=jnp.float32):
    """Qwen2ForCausalLM = llama layout + q/k/v biases."""
    params = llama.from_hf_state_dict(config, state_dict, dtype)

    from .transformer import hf_stack
    L = config.num_layers
    stack_bias = lambda fmt: hf_stack(state_dict, fmt, L, dtype, transpose=False)

    params["layers"]["attn"]["bq"] = stack_bias("model.layers.{}.self_attn.q_proj.bias")
    params["layers"]["attn"]["bk"] = stack_bias("model.layers.{}.self_attn.k_proj.bias")
    params["layers"]["attn"]["bv"] = stack_bias("model.layers.{}.self_attn.v_proj.bias")
    return params
