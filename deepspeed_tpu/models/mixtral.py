"""Mixtral-style MoE causal LM.

Parity: the reference serves mixtral via inference/v2/model_implementations/
mixtral and trains MoE via deepspeed/moe; BASELINE.md config ladder step 5 is
Mixtral-8x7B EP+Ulysses SP.  Llama backbone with the FFN replaced by a top-k
gated expert layer; aux losses summed across layers and added to the LM loss
(reference MoE aux-loss pattern, sharded_moe.py top2gating usage).
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..moe.experts import init_swiglu_experts, swiglu_experts
from ..moe.sharded_moe import TopKGate, moe_layer
from ..parallel.mesh import EXPERT_AXIS
from .transformer import (attention_block, cross_entropy_loss, init_linear,
                          paged_chunk_indices, rms_norm, rotary_tables)


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.02
    max_seq_len: int = 4096
    rope_theta: float = 1e6
    rms_eps: float = 1e-5
    remat: bool = True

    @staticmethod
    def mixtral_8x7b():
        return MixtralConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, experts=4, seq=64):
        return MixtralConfig(vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 2,
                             num_layers=layers, num_heads=heads, num_kv_heads=kv_heads,
                             num_experts=experts, max_seq_len=seq)


def init_params(config: MixtralConfig, key, dtype=jnp.float32):
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    L, D = config.num_layers, config.hidden_size
    H, KV = config.num_heads, config.num_kv_heads
    head_dim = D // H
    lk = jax.random.split(k_layers, 6)

    def stack(key, in_dim, out_dim):
        keys = jax.random.split(key, L)
        return jnp.stack([init_linear(k, in_dim, out_dim, dtype=dtype) for k in keys])

    def stack_experts(key):
        keys = jax.random.split(key, L)
        per_layer = [init_swiglu_experts(k, config.num_experts, D, config.intermediate_size, dtype=dtype)
                     for k in keys]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_layer)

    gate_keys = jax.random.split(lk[4], L)
    return {
        "embed": jax.random.normal(k_emb, (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "attn": {
                "wq": stack(lk[0], D, H * head_dim),
                "wk": stack(lk[1], D, KV * head_dim),
                "wv": stack(lk[2], D, KV * head_dim),
                "wo": stack(lk[3], H * head_dim, D),
            },
            "moe": {
                "gate": {"wg": jnp.stack([jax.random.normal(k, (D, config.num_experts), dtype) * 0.02
                                          for k in gate_keys])},
                "experts": stack_experts(lk[5]),
            },
            "attn_norm": jnp.ones((L, D), dtype),
            "mlp_norm": jnp.ones((L, D), dtype),
        },
        "final_norm": jnp.ones((D, ), dtype),
        "lm_head": init_linear(k_out, D, config.vocab_size, dtype=dtype),
    }


def forward(config: MixtralConfig, params, input_ids, attention_fn=None, train=True, topo=None):
    """-> (logits, total_aux_loss)."""
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]
    gate = TopKGate(config.hidden_size, config.num_experts, k=config.top_k,
                    capacity_factor=config.capacity_factor,
                    eval_capacity_factor=config.capacity_factor)

    def layer(carry, layer_params):
        x, aux = carry
        attn_in = rms_norm(x, layer_params["attn_norm"], config.rms_eps)
        attn_out, _ = attention_block(layer_params["attn"], attn_in,
                                      n_heads=config.num_heads, n_kv_heads=config.num_kv_heads,
                                      cos=cos, sin=sin, causal=True, attention_fn=attention_fn)
        x = x + attn_out
        moe_in = rms_norm(x, layer_params["mlp_norm"], config.rms_eps)
        moe_out, l_aux = moe_layer(gate, layer_params["moe"], moe_in,
                                   expert_fn=swiglu_experts, train=train, topo=topo)
        return (x + moe_out, aux + l_aux), None

    if config.remat:
        layer = jax.checkpoint(layer)
    (x, aux), _ = jax.lax.scan(layer, (x, jnp.float32(0.0)), params["layers"])
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    logits = x @ params["lm_head"].astype(x.dtype)
    return logits, aux


def make_loss_fn(config: MixtralConfig, attention_fn=None, topo=None) -> Callable:

    def loss_fn(params, batch, rng):
        logits, aux = forward(config, params, batch["input_ids"], attention_fn=attention_fn, topo=topo)
        lm = cross_entropy_loss(logits, batch["labels"])
        return lm + config.aux_loss_coef * aux, {"aux_loss": aux}

    return loss_fn


# --------------------------------------------------------- paged (ragged) serve
def dense_moe_ffn(moe_params, x, top_k: int):
    """Serving-time MoE FFN: top-k routing with NO capacity dropping (the
    reference's ragged moe_gather/moe_scatter semantics,
    inference/v2/kernels/ragged_ops/moe_*): every token reaches its k experts.

    Dense formulation: compute all experts, combine with the (renormalized)
    top-k gate weights — exact at any batch size; a megablox-style grouped GEMM
    is the later perf upgrade for many-expert configs.
    """
    ex = moe_params["experts"]
    gate_logits = x @ moe_params["gate"]["wg"].astype(x.dtype)  # [.., E]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, top_k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    combine = jnp.zeros_like(probs).at[
        jnp.arange(probs.shape[0])[:, None], top_idx].set(top_p)  # [T, E]

    def one_expert(wg, wu, wd):
        h = jax.nn.silu(x @ wg.astype(x.dtype)) * (x @ wu.astype(x.dtype))
        return h @ wd.astype(x.dtype)

    all_out = jax.vmap(one_expert)(ex["w_gate"], ex["w_up"], ex["w_down"])  # [E, T, D]
    return jnp.einsum("te,etd->td", combine.astype(x.dtype), all_out)


def from_hf_state_dict(config: MixtralConfig, state_dict, dtype=jnp.float32):
    """Convert a HF MixtralForCausalLM state dict (block_sparse_moe naming:
    w1=gate, w3=up, w2=down) to our stacked pytree."""
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    L, E = config.num_layers, config.num_experts
    stack = lambda fmt, tr=True: hf_stack(state_dict, fmt, L, dtype, tr)

    def stack_expert(which):
        return jnp.asarray(np.stack([
            np.stack([t(f"model.layers.{i}.block_sparse_moe.experts.{e}.{which}.weight").T
                      for e in range(E)]) for i in range(L)]), dtype)

    return {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), dtype),
        "layers": {
            "attn": {
                "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
                "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
                "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
                "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
            },
            "moe": {
                "gate": {"wg": stack("model.layers.{}.block_sparse_moe.gate.weight")},
                "experts": {"w_gate": stack_expert("w1"), "w_up": stack_expert("w3"),
                            "w_down": stack_expert("w2")},
            },
            "attn_norm": stack("model.layers.{}.input_layernorm.weight", tr=False),
            "mlp_norm": stack("model.layers.{}.post_attention_layernorm.weight", tr=False),
        },
        "final_norm": jnp.asarray(t("model.norm.weight"), dtype),
        "lm_head": jnp.asarray(t("lm_head.weight").T, dtype),
    }


def _llama_view(config: MixtralConfig):
    from .llama import LlamaConfig
    return LlamaConfig(vocab_size=config.vocab_size, hidden_size=config.hidden_size,
                       intermediate_size=config.intermediate_size, num_layers=config.num_layers,
                       num_heads=config.num_heads, num_kv_heads=config.num_kv_heads,
                       max_seq_len=config.max_seq_len, rope_theta=config.rope_theta,
                       rms_eps=config.rms_eps)


def init_paged_cache(config: MixtralConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    from . import llama
    return llama.init_paged_cache(_llama_view(config), num_blocks, block_size, dtype=dtype)


def tp_rules(path: str, shape) -> "int | None":
    """Tensor-parallel layout (reference v2 sharding helpers for mixtral:
    inference/v2/model_implementations/sharding/ + mixtral container): attention
    column/row split like llama; experts sharded on the intermediate dim
    (w1/w3 column, w2 row per expert); router gate replicated."""
    if path.endswith("attn.wq"):
        return 2  # [L, in, out] -> shard out (heads)
    if path.endswith(("attn.wk", "attn.wv")):
        # GQA kv projections replicate (transformer.kv_projection_shardable)
        from .transformer import kv_projection_shardable
        return 2 if kv_projection_shardable(shape) else None
    if path.endswith("attn.wo"):
        return 1
    if path.endswith(("experts.w_gate", "experts.w_up")):
        return 3  # [L, E, D, F] -> shard F
    if path.endswith("experts.w_down"):
        return 2  # [L, E, F, D] -> shard F
    if path == "lm_head":
        return 1  # vocab-parallel logits
    return None


def make_tp_rules(config: MixtralConfig):
    """v2 serving rules: GQA kv shards head-aligned (the v2 engine validates
    kv % tp == 0 first), MQA replicates (validate_model's make_tp_rules
    contract); static tp_rules keep GQA kv replicated for GSPMD layouts
    (transformer.kv_projection_shardable)."""
    kv = config.num_kv_heads

    def rules(path: str, shape) -> "int | None":
        if path.endswith(("attn.wk", "attn.wv")):
            return 2 if kv > 1 else None
        return tp_rules(path, shape)

    return rules

def forward_paged(config: MixtralConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked forward (reference inference/v2/model_implementations/
    mixtral): llama-style paged attention + no-drop top-k MoE FFN per layer.

    ``tp_axis``: see models/llama.py forward_paged — head counts come from the
    local param shapes, row-parallel partials (wo, expert w_down) are psum'd."""
    from ..ops.attention.paged import paged_attention
    from .transformer import apply_rotary

    b, tchunk = tokens.shape
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len,
                             config.rope_theta)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    Dh = config.hidden_size // config.num_heads  # true head dim: TP-invariant
    H = params["layers"]["attn"]["wq"].shape[-1] // Dh   # local (per-shard) heads
    KV = params["layers"]["attn"]["wk"].shape[-1] // Dh
    scale = 1.0 / np.sqrt(Dh)
    head_idx = jnp.arange(KV)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        attn_in = rms_norm(x, lp["attn_norm"], config.rms_eps)
        q = (attn_in @ lp["attn"]["wq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (attn_in @ lp["attn"]["wk"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        v = (attn_in @ lp["attn"]["wv"].astype(x.dtype)).reshape(b, tchunk, KV, Dh)
        q = apply_rotary(q, cos, sin, safe_pos)
        k = apply_rotary(k, cos, sin, safe_pos)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        x = x + preduce(out.reshape(b, tchunk, H * Dh) @ lp["attn"]["wo"].astype(x.dtype))
        moe_in = rms_norm(x, lp["mlp_norm"], config.rms_eps)
        flat = moe_in.reshape(b * tchunk, config.hidden_size)
        moe_out = preduce(dense_moe_ffn(lp["moe"], flat, config.top_k))
        x = x + moe_out.reshape(b, tchunk, config.hidden_size)
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    logits = x @ params["lm_head"].astype(x.dtype)
    if tp_axis is not None and gather_logits:
        logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    return logits, {"k": new_k, "v": new_v}
