"""Mixtral-style MoE causal LM.

Parity: the reference serves mixtral via inference/v2/model_implementations/
mixtral and trains MoE via deepspeed/moe; BASELINE.md config ladder step 5 is
Mixtral-8x7B EP+Ulysses SP.  Llama backbone with the FFN replaced by a top-k
gated expert layer; aux losses summed across layers and added to the LM loss
(reference MoE aux-loss pattern, sharded_moe.py top2gating usage).
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..moe.experts import init_swiglu_experts, swiglu_experts
from ..moe.sharded_moe import TopKGate, moe_layer
from ..parallel.mesh import EXPERT_AXIS
from .transformer import attention_block, cross_entropy_loss, init_linear, rms_norm, rotary_tables


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_coef: float = 0.02
    max_seq_len: int = 4096
    rope_theta: float = 1e6
    rms_eps: float = 1e-5
    remat: bool = True

    @staticmethod
    def mixtral_8x7b():
        return MixtralConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, experts=4, seq=64):
        return MixtralConfig(vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 2,
                             num_layers=layers, num_heads=heads, num_kv_heads=kv_heads,
                             num_experts=experts, max_seq_len=seq)


def init_params(config: MixtralConfig, key, dtype=jnp.float32):
    k_emb, k_layers, k_out = jax.random.split(key, 3)
    L, D = config.num_layers, config.hidden_size
    H, KV = config.num_heads, config.num_kv_heads
    head_dim = D // H
    lk = jax.random.split(k_layers, 6)

    def stack(key, in_dim, out_dim):
        keys = jax.random.split(key, L)
        return jnp.stack([init_linear(k, in_dim, out_dim, dtype=dtype) for k in keys])

    def stack_experts(key):
        keys = jax.random.split(key, L)
        per_layer = [init_swiglu_experts(k, config.num_experts, D, config.intermediate_size, dtype=dtype)
                     for k in keys]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_layer)

    gate_keys = jax.random.split(lk[4], L)
    return {
        "embed": jax.random.normal(k_emb, (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "attn": {
                "wq": stack(lk[0], D, H * head_dim),
                "wk": stack(lk[1], D, KV * head_dim),
                "wv": stack(lk[2], D, KV * head_dim),
                "wo": stack(lk[3], H * head_dim, D),
            },
            "moe": {
                "gate": {"wg": jnp.stack([jax.random.normal(k, (D, config.num_experts), dtype) * 0.02
                                          for k in gate_keys])},
                "experts": stack_experts(lk[5]),
            },
            "attn_norm": jnp.ones((L, D), dtype),
            "mlp_norm": jnp.ones((L, D), dtype),
        },
        "final_norm": jnp.ones((D, ), dtype),
        "lm_head": init_linear(k_out, D, config.vocab_size, dtype=dtype),
    }


def forward(config: MixtralConfig, params, input_ids, attention_fn=None, train=True, topo=None):
    """-> (logits, total_aux_loss)."""
    cos, sin = rotary_tables(config.hidden_size // config.num_heads, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]
    gate = TopKGate(config.hidden_size, config.num_experts, k=config.top_k,
                    capacity_factor=config.capacity_factor,
                    eval_capacity_factor=config.capacity_factor)

    def layer(carry, layer_params):
        x, aux = carry
        attn_in = rms_norm(x, layer_params["attn_norm"], config.rms_eps)
        attn_out, _ = attention_block(layer_params["attn"], attn_in,
                                      n_heads=config.num_heads, n_kv_heads=config.num_kv_heads,
                                      cos=cos, sin=sin, causal=True, attention_fn=attention_fn)
        x = x + attn_out
        moe_in = rms_norm(x, layer_params["mlp_norm"], config.rms_eps)
        moe_out, l_aux = moe_layer(gate, layer_params["moe"], moe_in,
                                   expert_fn=swiglu_experts, train=train, topo=topo)
        return (x + moe_out, aux + l_aux), None

    if config.remat:
        layer = jax.checkpoint(layer)
    (x, aux), _ = jax.lax.scan(layer, (x, jnp.float32(0.0)), params["layers"])
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    logits = x @ params["lm_head"].astype(x.dtype)
    return logits, aux


def make_loss_fn(config: MixtralConfig, attention_fn=None, topo=None) -> Callable:

    def loss_fn(params, batch, rng):
        logits, aux = forward(config, params, batch["input_ids"], attention_fn=attention_fn, topo=topo)
        lm = cross_entropy_loss(logits, batch["labels"])
        return lm + config.aux_loss_coef * aux, {"aux_loss": aux}

    return loss_fn
