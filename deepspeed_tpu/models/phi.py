"""Phi causal LM (microsoft/phi-2 family).

Parity: reference inference/v2/model_implementations/phi.  Architecture:
parallel attention+MLP like Falcon but with biases everywhere, PARTIAL rotary
(only the first ``rotary_dim`` of each head rotates — phi-2's
partial_rotary_factor 0.4), GELU fc1/fc2 MLP, untied lm_head with bias.
"""

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (apply_rotary, causal_lm_batch, count_params,
                          cross_entropy_loss, init_paged_kv_pool, layer_norm,
                          paged_chunk_indices, rotary_tables, sdpa)


@dataclasses.dataclass(frozen=True)
class PhiConfig:
    vocab_size: int = 51200
    hidden_size: int = 2560
    ffn_dim: int = 10240
    num_layers: int = 32
    num_heads: int = 32
    max_seq_len: int = 2048
    partial_rotary_factor: float = 0.4
    ln_eps: float = 1e-5
    rope_theta: float = 10000.0
    remat: bool = True

    @property
    def rotary_dim(self) -> int:
        dh = self.hidden_size // self.num_heads
        # HF phi rounds the rotary slice to an even size
        return int(dh * self.partial_rotary_factor) // 2 * 2

    @staticmethod
    def phi_2():
        return PhiConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, seq=64):
        return PhiConfig(vocab_size=vocab, hidden_size=hidden, ffn_dim=hidden * 4,
                         num_layers=layers, num_heads=heads, max_seq_len=seq,
                         partial_rotary_factor=0.5)


def partial_rotary(x, cos, sin, rotary_dim: int, positions=None):
    """Rotate only the leading ``rotary_dim`` of the head dim; rest passes."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    rot = apply_rotary(rot, cos, sin, positions)
    return jnp.concatenate([rot, rest], axis=-1)


def init_params(config: PhiConfig, key, dtype=jnp.float32):
    D, F, L = config.hidden_size, config.ffn_dim, config.num_layers
    ks = jax.random.split(key, 8)
    s = D ** -0.5

    def stack(k, shape):
        return jax.random.normal(k, (L, *shape), dtype) * s

    return {
        "embed": jax.random.normal(ks[0], (config.vocab_size, D), dtype) * 0.02,
        "layers": {
            "ln_w": jnp.ones((L, D), dtype), "ln_b": jnp.zeros((L, D), dtype),
            "wq": stack(ks[1], (D, D)), "bq": jnp.zeros((L, D), dtype),
            "wk": stack(ks[2], (D, D)), "bk": jnp.zeros((L, D), dtype),
            "wv": stack(ks[3], (D, D)), "bv": jnp.zeros((L, D), dtype),
            "wo": stack(ks[4], (D, D)), "bo": jnp.zeros((L, D), dtype),
            "fc1": stack(ks[5], (D, F)), "b_fc1": jnp.zeros((L, F), dtype),
            "fc2": stack(ks[6], (F, D)), "b_fc2": jnp.zeros((L, D), dtype),
        },
        "final_ln_w": jnp.ones((D,), dtype), "final_ln_b": jnp.zeros((D,), dtype),
        "lm_head": jax.random.normal(ks[7], (D, config.vocab_size), dtype) * s,
        "lm_head_b": jnp.zeros((config.vocab_size,), dtype),
    }


def num_params(config: PhiConfig) -> int:
    return count_params(lambda: init_params(config, jax.random.PRNGKey(0)))


def _block(config: PhiConfig, lp, x, cos, sin, attention_fn=None):
    b, s, D = x.shape
    H = config.num_heads
    Dh = D // H
    h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
    q = (h @ lp["wq"].astype(x.dtype) + lp["bq"].astype(x.dtype)).reshape(b, s, H, Dh)
    k = (h @ lp["wk"].astype(x.dtype) + lp["bk"].astype(x.dtype)).reshape(b, s, H, Dh)
    v = (h @ lp["wv"].astype(x.dtype) + lp["bv"].astype(x.dtype)).reshape(b, s, H, Dh)
    q = partial_rotary(q, cos, sin, config.rotary_dim)
    k = partial_rotary(k, cos, sin, config.rotary_dim)
    attn = (attention_fn or sdpa)(q, k, v, causal=True)
    attn_out = attn.reshape(b, s, D) @ lp["wo"].astype(x.dtype) + lp["bo"].astype(x.dtype)
    mlp = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype),
                      approximate=True)
    mlp_out = mlp @ lp["fc2"].astype(x.dtype) + lp["b_fc2"].astype(x.dtype)
    return x + attn_out + mlp_out  # parallel residual


def forward(config: PhiConfig, params, input_ids, attention_fn=None):
    cos, sin = rotary_tables(config.rotary_dim, config.max_seq_len, config.rope_theta)
    x = params["embed"][input_ids]

    def body(h, lp):
        return _block(config, lp, h, cos, sin, attention_fn), None

    if config.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    return x @ params["lm_head"].astype(x.dtype) + params["lm_head_b"].astype(x.dtype)


def make_loss_fn(config: PhiConfig, attention_fn=None) -> Callable:
    def loss_fn(params, batch, rng=None):
        logits = forward(config, params, batch["input_ids"], attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])
    return loss_fn


# --------------------------------------------------------- paged (ragged) serve
def init_paged_cache(config: PhiConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    return init_paged_kv_pool(config.num_layers, config.num_heads,
                              config.hidden_size // config.num_heads,
                              num_blocks, block_size, dtype)


def tp_rules(path: str, shape) -> "int | None":
    """v2 TP layout (reference inference/v2/model_implementations/sharding/
    used by the phi containers): qkv + fc1 column-parallel with their biases;
    wo/fc2 row-parallel with replicated biases (added once after the psum);
    untied lm_head vocab-parallel with its bias sharded alongside."""
    if path.endswith(("bo", "b_fc2")):
        return None  # row-parallel biases replicate (added once, post-psum)
    if path.endswith(("bq", "bk", "bv", "b_fc1")):
        return 1
    # bias checks precede weights: "b_fc1"/"b_fc2" suffix-match "fc1"/"fc2"
    if path.endswith(("wq", "fc1")):
        return 2
    if path.endswith(("wk", "wv")):
        from .transformer import kv_projection_shardable
        return 2 if kv_projection_shardable(shape) else None
    if path.endswith(("wo", "fc2")):
        return 1
    if path == "lm_head":
        return 1  # [D, V] vocab-parallel
    if path == "lm_head_b":
        return 0  # [V] sharded with its vocab slice
    return None


def forward_paged(config: PhiConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """Ragged chunked Phi forward — partial rotary feeds the paged kernel.

    ``tp_axis``: heads shard; the parallel residual's attn+mlp partials reduce
    in ONE psum with the replicated bo/b_fc2 added after it.  The untied
    lm_head is vocab-parallel: the local bias slice lands on local logits
    before the (optional) gather, so greedy decode can argmax the local shard
    (gather_logits=False) without moving O(V) over ICI."""
    from ..ops.attention.paged import paged_attention

    b, tchunk = tokens.shape
    Dh = config.hidden_size // config.num_heads  # TP-invariant
    H = params["layers"]["wq"].shape[-1] // Dh   # local heads
    scale = 1.0 / np.sqrt(Dh)
    cos, sin = rotary_tables(config.rotary_dim, config.max_seq_len, config.rope_theta)
    safe_pos, valid, lengths, blk, off = paged_chunk_indices(
        tokens, n_tokens, start_pos, block_tables, kv_cache["k"].shape[1], block_size)
    x = params["embed"][tokens].astype(kv_cache["k"].dtype)
    head_idx = jnp.arange(H)[None, None, :]
    preduce = (lambda y: jax.lax.psum(y, tp_axis)) if tp_axis else (lambda y: y)

    def layer(x, inp):
        lp, kpool, vpool = inp
        h = layer_norm(x, lp["ln_w"], lp["ln_b"], config.ln_eps)
        q = (h @ lp["wq"].astype(x.dtype) + lp["bq"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        k = (h @ lp["wk"].astype(x.dtype) + lp["bk"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        v = (h @ lp["wv"].astype(x.dtype) + lp["bv"].astype(x.dtype)).reshape(b, tchunk, H, Dh)
        q = partial_rotary(q, cos, sin, config.rotary_dim, safe_pos)
        k = partial_rotary(k, cos, sin, config.rotary_dim, safe_pos)
        kpool = kpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(k)
        vpool = vpool.at[blk[:, :, None], head_idx, off[:, :, None]].set(v)
        out = paged_attention(q, kpool, vpool, block_tables, lengths, start_pos, n_tokens,
                              block_size=block_size, softmax_scale=scale)
        attn_out = out.reshape(b, tchunk, H * Dh) @ lp["wo"].astype(x.dtype)
        mlp = jax.nn.gelu(h @ lp["fc1"].astype(x.dtype) + lp["b_fc1"].astype(x.dtype),
                          approximate=True)
        mlp_out = mlp @ lp["fc2"].astype(x.dtype)
        x = x + preduce(attn_out + mlp_out) \
            + lp["bo"].astype(x.dtype) + lp["b_fc2"].astype(x.dtype)
        return x, (kpool, vpool)

    x, (new_k, new_v) = jax.lax.scan(layer, x, (params["layers"], kv_cache["k"], kv_cache["v"]))
    x = layer_norm(x, params["final_ln_w"], params["final_ln_b"], config.ln_eps)
    logits = x @ params["lm_head"].astype(x.dtype) + params["lm_head_b"].astype(x.dtype)
    if tp_axis is not None and gather_logits:
        logits = jax.lax.all_gather(logits, tp_axis, axis=-1, tiled=True)
    return logits, {"k": new_k, "v": new_v}


# ----------------------------------------------------------------- HF import
def config_from_hf(hf_config) -> PhiConfig:
    if getattr(hf_config, "qk_layernorm", False):
        raise NotImplementedError("qk_layernorm Phi variants are not supported")
    kv = getattr(hf_config, "num_key_value_heads", None)
    if kv is not None and kv != hf_config.num_attention_heads:
        raise NotImplementedError("GQA Phi variants (num_key_value_heads < "
                                  "num_attention_heads) are not supported")
    return PhiConfig(vocab_size=hf_config.vocab_size, hidden_size=hf_config.hidden_size,
                     ffn_dim=hf_config.intermediate_size,
                     num_layers=hf_config.num_hidden_layers,
                     num_heads=hf_config.num_attention_heads,
                     max_seq_len=hf_config.max_position_embeddings,
                     partial_rotary_factor=getattr(hf_config, "partial_rotary_factor", 0.4),
                     ln_eps=getattr(hf_config, "layer_norm_eps", 1e-5),
                     rope_theta=getattr(hf_config, "rope_theta", 10000.0))


def from_hf_state_dict(config: PhiConfig, state_dict, dtype=jnp.float32):
    """Convert a PhiForCausalLM state dict (biases everywhere, untied head)."""
    from .transformer import hf_stack, hf_tensor
    t = lambda name: hf_tensor(state_dict, name)
    L = config.num_layers
    pre = "model.layers.{}"
    stack = lambda fmt, transpose=True: hf_stack(state_dict, fmt, L, dtype, transpose)

    return {
        "embed": jnp.asarray(t("model.embed_tokens.weight"), dtype),
        "layers": {
            "ln_w": stack(pre + ".input_layernorm.weight", False),
            "ln_b": stack(pre + ".input_layernorm.bias", False),
            "wq": stack(pre + ".self_attn.q_proj.weight"),
            "bq": stack(pre + ".self_attn.q_proj.bias", False),
            "wk": stack(pre + ".self_attn.k_proj.weight"),
            "bk": stack(pre + ".self_attn.k_proj.bias", False),
            "wv": stack(pre + ".self_attn.v_proj.weight"),
            "bv": stack(pre + ".self_attn.v_proj.bias", False),
            "wo": stack(pre + ".self_attn.dense.weight"),
            "bo": stack(pre + ".self_attn.dense.bias", False),
            "fc1": stack(pre + ".mlp.fc1.weight"),
            "b_fc1": stack(pre + ".mlp.fc1.bias", False),
            "fc2": stack(pre + ".mlp.fc2.weight"),
            "b_fc2": stack(pre + ".mlp.fc2.bias", False),
        },
        "final_ln_w": jnp.asarray(t("model.final_layernorm.weight"), dtype),
        "final_ln_b": jnp.asarray(t("model.final_layernorm.bias"), dtype),
        "lm_head": jnp.asarray(t("lm_head.weight").T, dtype),
        "lm_head_b": jnp.asarray(t("lm_head.bias"), dtype),
    }
