"""Mistral causal LM — Llama architecture + sliding-window attention.

Parity: reference inference/v2/model_implementations/mistral (the reference
serves Mistral with windowed blocked flash).  The backbone is byte-identical to
Llama, so everything delegates to models/llama with ``sliding_window`` threaded
through: training masks the window inside sdpa; v2 serving passes it to the
Pallas paged kernel (ops/attention/paged.py window arg).
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import llama
from .llama import LlamaConfig
from .transformer import cross_entropy_loss, sdpa


@dataclasses.dataclass(frozen=True)
class MistralConfig(LlamaConfig):
    sliding_window: Optional[int] = 4096

    @staticmethod
    def mistral_7b():
        return MistralConfig(vocab_size=32000, hidden_size=4096, intermediate_size=14336,
                             num_layers=32, num_heads=32, num_kv_heads=8,
                             max_seq_len=32768, rope_theta=10000.0, sliding_window=4096)

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, kv_heads=2, seq=64, window=16):
        return MistralConfig(vocab_size=vocab, hidden_size=hidden, intermediate_size=hidden * 2,
                             num_layers=layers, num_heads=heads, num_kv_heads=kv_heads,
                             max_seq_len=seq, sliding_window=window)


def windowed_attention(window: Optional[int]):
    """attention_fn applying the sliding-window causal mask (training path)."""
    if window is None:
        return None

    def attn(q, k, v, causal=True, mask=None, softmax_scale=None):
        sq, sk = q.shape[1], k.shape[1]
        qp = jnp.arange(sq)[:, None] + (sk - sq)
        kp = jnp.arange(sk)[None, :]
        wmask = (kp <= qp) & (kp > qp - window)
        if mask is not None:
            wmask = jnp.logical_and(mask, wmask[None, None])
        else:
            wmask = wmask[None, None]
        return sdpa(q, k, v, causal=False, mask=wmask, softmax_scale=softmax_scale)

    return attn


init_params = llama.init_params
num_params = llama.num_params
flops_per_token = llama.flops_per_token
tp_rules = llama.tp_rules
make_tp_rules = llama.make_tp_rules
abstract_params = llama.abstract_params
from_hf_state_dict = llama.from_hf_state_dict
hf_streaming_loader = llama.hf_streaming_loader
init_cache = llama.init_cache
init_paged_cache = llama.init_paged_cache
causal_lm_batch = llama.causal_lm_batch


def forward(config: MistralConfig, params, input_ids, attention_fn=None):
    fn = attention_fn or windowed_attention(config.sliding_window)
    return llama.forward(config, params, input_ids, attention_fn=fn)


def make_loss_fn(config: MistralConfig, attention_fn=None) -> Callable:
    fn = attention_fn or windowed_attention(config.sliding_window)
    return llama.make_loss_fn(config, attention_fn=fn)


def forward_paged(config: MistralConfig, params, tokens, n_tokens, start_pos, block_tables,
                  kv_cache, *, block_size: int, tp_axis: Optional[str] = None,
                  gather_logits: bool = True):
    """v2 ragged forward: the paged kernel applies the sliding window directly
    (reference mistral serving uses windowed blocked flash)."""
    return llama.forward_paged(config, params, tokens, n_tokens, start_pos, block_tables,
                               kv_cache, block_size=block_size,
                               window=config.sliding_window, tp_axis=tp_axis,
                               gather_logits=gather_logits)


def config_from_hf(hf_config) -> MistralConfig:
    base = llama.config_from_hf(hf_config)
    return MistralConfig(**dataclasses.asdict(base),
                         sliding_window=getattr(hf_config, "sliding_window", None))
