"""Shared transformer building blocks (pure-function, pytree-params style).

These replace the reference's fused CUDA transformer kernels
(csrc/transformer/ds_transformer_cuda.cpp — qkv gemm/softmax/layernorm/gelu
fusions): under XLA those fusions are automatic, so the blocks are written for
MXU-friendly shapes (large batched matmuls, bf16 inputs) and the layer stack is
a ``lax.scan`` over stacked layer params — which (a) compiles once for all
layers, and (b) under ZeRO-3 naturally gathers ONE layer's params per scan step,
the analog of the reference's per-submodule allgather/release coordinator
(runtime/zero/partitioned_param_coordinator.py:257).

Attention routes through ``attention_fn`` so Ulysses sequence parallelism
(deepspeed_tpu/sequence) or a Pallas flash kernel can be injected — mirroring
DistributedAttention wrapping "any local attention" (deepspeed/sequence/layer.py:60).
"""

import dataclasses
import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------------- norms
def rms_norm(x, weight, eps=1e-6):
    """RMSNorm (reference csrc/transformer/inference/csrc/rms_norm.cu analog)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(x, weight, bias, eps=1e-5):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


# ----------------------------------------------------------------- rotary
def rotary_tables(head_dim: int, max_seq: int, theta: float = 10000.0):
    """Returns NUMPY tables (config-static constants): layer closures that
    capture them stay trace-free, which custom_vjp wrappers
    (activation_checkpointing.offload_checkpoint) require — a jnp constant
    created during tracing is a tracer, and custom_vjp can't close over
    tracers.  apply_rotary converts at use."""
    inv_freq = 1.0 / (theta**(np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
    t = np.arange(max_seq, dtype=np.float32)
    freqs = np.outer(t, inv_freq)  # [S, D/2]
    return np.cos(freqs), np.sin(freqs)


def apply_rotary(x, cos, sin, positions=None):
    """x: [B, S, H, D]. cos/sin: [maxS, D/2] (numpy or jnp)."""
    seq = x.shape[1]
    if positions is None:
        c = jnp.asarray(cos[:seq])[None, :, None, :]
        s = jnp.asarray(sin[:seq])[None, :, None, :]
    else:
        c = jnp.asarray(cos)[positions][:, :, None, :]
        s = jnp.asarray(sin)[positions][:, :, None, :]
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = c.astype(x.dtype)
    s = s.astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def kv_projection_shardable(shape) -> bool:
    """Whether a kv projection weight ([..., in, out]) may be column-sharded
    over the tensor axis.

    GQA/MQA kv projections (output narrower than the model dim) stay
    REPLICATED — the Megatron/AutoTP convention when kv heads don't divide
    over tp.  Beyond being the right layout (a kv projection is small, and a
    sub-head shard forces an allgather at every attention), sub-head-aligned
    kv sharding silently MISCOMPILES in older XLA SPMD partitioners:
    ``lax.scan`` + the rotate-half rotary on a sub-head-sharded operand
    returns wrong numerics (no error — ~90% of logits off).  tp_rules can't
    see head_dim, so "narrower than the input dim" is the conservative
    stand-in that exactly captures GQA/MQA while leaving MHA layouts (out ==
    in, head-aligned whenever q-sharding is) untouched."""
    return len(shape) >= 2 and shape[-1] >= shape[-2]


# ----------------------------------------------------------------- attention
def sdpa(q, k, v, causal=True, mask=None, softmax_scale=None, bias=None):
    """Scaled dot-product attention. q,k,v: [B, S, H, D] (k/v may have fewer
    heads — GQA — broadcast via repeat). fp32 softmax for stability.
    ``bias``: additive logit bias broadcastable to [B, H, Sq, Sk] (ALiBi)."""
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    sk = k.shape[1]
    if causal:
        # support sq != sk (decode): query i attends keys <= i + (sk - sq)
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        causal_mask = kpos <= qpos
        logits = jnp.where(causal_mask[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def default_attention():
    """Resolve the attention impl for this backend: the Pallas flash kernel on
    TPU (ops/attention/flash.py — the reference's fused-attention analog,
    csrc/transformer/ds_attention.cu), plain XLA sdpa elsewhere.  Callers that
    pass an explicit ``attention_fn`` (Ulysses, blocksparse, tests) override it."""
    from ..ops import _pallas
    if _pallas.use_pallas():
        from ..ops.attention.flash import flash_attention
        return flash_attention
    return sdpa


# Config-installed attention override — the functional analog of the
# reference's module injection swapping attention for SparseSelfAttention when
# the JSON's ``sparse_attention`` section is set (sparse_self_attention.py:99,
# wired by initialize()).  Models that route through attention_block pick it up
# at trace time unless they pass an explicit attention_fn; "engaged" records
# that a trace actually consumed it (tested, not just installed).
_CONFIGURED_ATTENTION = {"fn": None, "engaged": False}


def set_default_attention(fn):
    """Install (or clear, fn=None) the process-wide default attention_fn."""
    _CONFIGURED_ATTENTION["fn"] = fn
    _CONFIGURED_ATTENTION["engaged"] = False


def scoped_default_attention(loss_fn, attention_fn):
    """Wrap ``loss_fn`` so ``attention_fn`` (possibly None) is the configured
    default exactly while loss_fn's body runs — i.e. while jit TRACES it.
    This pins each engine's attention choice to its own loss function: two
    engines with different sparse_attention configs coexist in one process,
    and an engine that configured none can never inherit another's kernel."""

    def scoped(*args, **kwargs):
        prev = _CONFIGURED_ATTENTION["fn"]
        _CONFIGURED_ATTENTION["fn"] = attention_fn
        try:
            return loss_fn(*args, **kwargs)
        finally:
            _CONFIGURED_ATTENTION["fn"] = prev

    return scoped


def configured_attention_engaged() -> bool:
    return _CONFIGURED_ATTENTION["engaged"]


# ------------------------------------------------------- random-LTD scoping
# Engine-side random-LTD activation for the in-repo zoo (reference
# convert_to_random_ltd rewrites nn.Modules from config alone,
# runtime/data_pipeline/data_routing/helper.py:11).  The functional analog:
# initialize() scopes an LTD state around the loss_fn exactly like the sparse-
# attention default above; model forwards that support token dropping read it
# at TRACE time via configured_ltd().  ``state["keep"]`` is a python int —
# baked into the trace — so the engine re-jits when the scheduler's budget
# steps (the reference pays the same recompile via its seqlen buckets).
_CONFIGURED_LTD = {"state": None, "engaged": False}

# Force-empty pin: scoped_random_ltd(fn, None) installs this sentinel rather
# than None so INNER scopes can tell "an outer scope pinned LTD off" (eval)
# apart from "no scope active".  Without it the engine's eval wrapper was dead
# code: initialize() already wraps the loss_fn with the train LTD state, and
# that inner wrapper re-installed the state right over eval's empty pin, so
# eval traced WITH token dropping (ADVICE r5 medium).
_LTD_FORCE_EMPTY = object()


def scoped_random_ltd(loss_fn, ltd_state):
    """Pin ``ltd_state`` as the configured random-LTD while loss_fn traces
    (``None`` pins the scope EMPTY — how the engine's eval step keeps LTD
    train-only; the empty pin is AUTHORITATIVE over inner train wrappers).
    Engagement is recorded on the state dict itself (``ltd_state["engaged"]``),
    so each engine sees its own truth rather than a process-global flag."""
    pin = _LTD_FORCE_EMPTY if ltd_state is None else ltd_state

    def scoped(*args, **kwargs):
        prev = _CONFIGURED_LTD["state"]
        if prev is _LTD_FORCE_EMPTY and ltd_state is not None:
            # an outer scope pinned LTD off — the train wrapper must not
            # re-engage it (eval measures the full model)
            return loss_fn(*args, **kwargs)
        _CONFIGURED_LTD["state"] = pin
        if ltd_state is not None:
            _CONFIGURED_LTD["engaged"] = False  # fresh trace, fresh verdict
        try:
            return loss_fn(*args, **kwargs)
        finally:
            _CONFIGURED_LTD["state"] = prev

    return scoped


def configured_ltd():
    st = _CONFIGURED_LTD["state"]
    return None if st is _LTD_FORCE_EMPTY else st


def configured_ltd_engaged() -> bool:
    return _CONFIGURED_LTD["engaged"]


def random_ltd_scan(layer, x, stacked_params, rng, keep: int):
    """Scan a layer stack with random layerwise token dropping: first and last
    layers see every token (reference random_ltd keeps the outer layers
    intact); each middle layer processes an independent random subset of
    ``keep`` tokens — dropped tokens ride the residual stream unchanged —
    with rotary/causal math on ORIGINAL positions via the layer's
    ``positions`` argument.  Cuts middle-layer attention cost by (keep/S)^2
    (reference csrc/random_ltd token_sort/gather kernels; here the sort/
    gather is jnp.take/at[].set and XLA fuses it)."""
    from ..runtime.data_pipeline.random_ltd import (gather_tokens,
                                                    sample_token_indices,
                                                    scatter_tokens)
    leaves = jax.tree_util.tree_leaves(stacked_params)
    L = int(leaves[0].shape[0])
    S = x.shape[1]
    take = lambda i: jax.tree_util.tree_map(lambda l: l[i], stacked_params)
    if L < 3 or keep >= S:
        x, _ = jax.lax.scan(layer, x, stacked_params)
        return x
    _CONFIGURED_LTD["engaged"] = True
    st = _CONFIGURED_LTD["state"]
    if isinstance(st, dict):  # never the force-empty sentinel
        st["engaged"] = True  # per-engine truth (the global resets each trace)
    x, _ = layer(x, take(0))
    mids = jax.tree_util.tree_map(lambda l: l[1:-1], stacked_params)

    def mid_body(carry, lp):
        h, key = carry
        key, sub = jax.random.split(key)
        idx = sample_token_indices(sub, S, keep)
        kept = gather_tokens(h, idx)
        # positions passed POSITIONALLY: custom_vjp-wrapped layers
        # (offload_checkpoint) accept no kwargs
        y, _ = layer(kept, lp, idx[None, :])  # [1, K]: original rotary positions
        return (scatter_tokens(h, y, idx), key), None

    (x, _), _ = jax.lax.scan(mid_body, (x, rng), mids)
    x, _ = layer(x, take(L - 1))
    return x


def _resolve_attention(attention_fn):
    if attention_fn is not None:
        return attention_fn
    if _CONFIGURED_ATTENTION["fn"] is not None:
        _CONFIGURED_ATTENTION["engaged"] = True
        return _CONFIGURED_ATTENTION["fn"]
    return default_attention()


def attention_block(params, x, *, n_heads, n_kv_heads, cos, sin, causal=True,
                    attention_fn=None, positions=None, kv_cache=None):
    """Multi-head attention with rotary + GQA.

    params: {wq, wk, wv, wo} each [model, heads*dim] / [heads*dim, model].
    kv_cache: optional (k_cache, v_cache, cache_len) for decode; returns
    (out, new_kv_cache).
    """
    b, s, dm = x.shape
    head_dim = params["wq"].shape[1] // n_heads
    q = (x @ params["wq"].astype(x.dtype)).reshape(b, s, n_heads, head_dim)
    k = (x @ params["wk"].astype(x.dtype)).reshape(b, s, n_kv_heads, head_dim)
    v = (x @ params["wv"].astype(x.dtype)).reshape(b, s, n_kv_heads, head_dim)
    q = apply_rotary(q, cos, sin, positions)
    k = apply_rotary(k, cos, sin, positions)

    new_cache = None
    if kv_cache is not None:
        k_cache, v_cache, cache_len = kv_cache
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, cache_len, axis=1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, cache_len, axis=1)
        k_full, v_full = k_cache, v_cache
        # mask out cache positions beyond cache_len + s
        kpos = jnp.arange(k_cache.shape[1])[None, None, None, :]
        valid = kpos < (cache_len + s)
        attn_fn = _resolve_attention(attention_fn)
        qpos = (jnp.arange(s) + cache_len)
        # causal over absolute positions
        causal_mask = kpos[:, :, :, :] <= qpos[None, None, :, None]
        out = attn_fn(q, k_full, v_full, causal=False, mask=jnp.logical_and(valid, causal_mask))
        new_cache = (k_cache, v_cache, cache_len + s)
    else:
        attn_fn = _resolve_attention(attention_fn)
        out = attn_fn(q, k, v, causal=causal)
    out = out.reshape(b, s, n_heads * head_dim)
    out = out @ params["wo"].astype(x.dtype)
    return out, new_cache


# ----------------------------------------------------------------- mlp
def swiglu_mlp(params, x):
    """Llama-style gated MLP: down(silu(gate(x)) * up(x))."""
    gate = jax.nn.silu(x @ params["w_gate"].astype(x.dtype))
    up = x @ params["w_up"].astype(x.dtype)
    return (gate * up) @ params["w_down"].astype(x.dtype)


def gelu_mlp(params, x):
    """GPT2/BERT-style MLP: fc2(gelu(fc1(x)))."""
    h = jax.nn.gelu((x @ params["w_fc1"].astype(x.dtype)) + params["b_fc1"].astype(x.dtype), approximate=True)
    return (h @ params["w_fc2"].astype(x.dtype)) + params["b_fc2"].astype(x.dtype)


# ------------------------------------------------------- model-family shared
def causal_lm_batch(ids):
    """Shift token ids into (input_ids, labels) next-token pairs."""
    ids = np.asarray(ids)
    return {"input_ids": ids[:, :-1], "labels": ids[:, 1:]}


def count_params(init_fn) -> int:
    """Parameter count without materializing (jax.eval_shape over init)."""
    shapes = jax.eval_shape(init_fn)
    return sum(int(np.prod(np.shape(l))) for l in jax.tree_util.tree_leaves(shapes))


def init_paged_kv_pool(num_layers: int, num_kv_heads: int, head_dim: int,
                       num_blocks: int, block_size: int, dtype=jnp.bfloat16):
    """Paged KV pool [L, NB, KV, bs, Dh] — heads-major so the Pallas paged
    kernel's trailing (bs, Dh) tile satisfies TPU tiling; the last block is
    the trash target for padded-token writes."""
    shape = (num_layers, num_blocks, num_kv_heads, block_size, head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}




# ------------------------------------------------------ HF state-dict helpers
def hf_tensor(state_dict, name):
    """torch tensor / array -> fp32 numpy (shared by every from_hf_state_dict)."""
    w = state_dict[name]
    return w.float().numpy() if hasattr(w, "float") else np.asarray(w, np.float32)


def hf_stack(state_dict, fmt, num_layers, dtype, transpose=True):
    """Stack one per-layer HF tensor into an [L, ...] leaf, transposing torch
    Linear [out, in] into our [in, out] unless ``transpose=False``."""
    ws = [hf_tensor(state_dict, fmt.format(i)) for i in range(num_layers)]
    return jnp.asarray(np.stack([w.T if transpose else w for w in ws]), dtype)


# -------------------------------------------------------- paged-serving shared
def paged_chunk_indices(tokens, n_tokens, start_pos, block_tables, num_blocks: int,
                        block_size: int):
    """Shared index scaffolding for every family's ``forward_paged``: maps the
    ragged chunk's absolute positions onto paged-KV pool coordinates.

    Returns (safe_pos [N,T], valid [N,T], lengths [N], blk [N,T], off [N,T]):
    ``blk``/``off`` address pool[blk, :, off] for each token's KV write, with
    padded tokens routed to the trash block (``num_blocks - 1``).
    """
    b, tchunk = tokens.shape
    trash = num_blocks - 1
    positions = start_pos[:, None] + jnp.arange(tchunk)[None, :]
    valid = jnp.arange(tchunk)[None, :] < n_tokens[:, None]
    safe_pos = jnp.where(valid, positions, 0)
    lengths = start_pos + n_tokens
    blk = jnp.take_along_axis(block_tables, safe_pos // block_size, axis=1)
    blk = jnp.where(valid, blk, trash)
    off = jnp.where(valid, safe_pos % block_size, 0)
    return safe_pos, valid, lengths, blk, off


# ----------------------------------------------------------------- losses
def cross_entropy_loss(logits, labels, ignore_index=-100, z_loss=0.0):
    """Token cross entropy with masking; logits [B,S,V], labels [B,S] int."""
    logits = logits.astype(jnp.float32)
    mask = labels != ignore_index
    safe_labels = jnp.where(mask, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * mask
    loss = nll.sum() / jnp.maximum(mask.sum(), 1)
    if z_loss > 0.0:
        loss = loss + z_loss * jnp.mean((logz * mask)**2)
    return loss


def init_linear(key, in_dim, out_dim, scale=None, dtype=jnp.float32):
    scale = scale if scale is not None else 1.0 / np.sqrt(in_dim)
    return jax.random.normal(key, (in_dim, out_dim), dtype) * scale
