"""BERT-style bidirectional encoder with MLM loss.

Parity: the reference's config ladder step 1 (bert-base + ZeRO-1, BASELINE.md)
and the fused-transformer training kernels' target workload
(csrc/transformer/ — BERT-style layers, tests/unit/ops/transformer/).
"""

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

from .transformer import cross_entropy_loss, default_attention, gelu_mlp, init_linear, layer_norm, sdpa


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 512
    type_vocab_size: int = 2
    ln_eps: float = 1e-12
    remat: bool = True

    @staticmethod
    def bert_base():
        return BertConfig()

    @staticmethod
    def tiny(vocab=256, hidden=64, layers=2, heads=4, seq=64):
        return BertConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers, num_heads=heads, max_seq_len=seq)


def init_params(config: BertConfig, key, dtype=jnp.float32):
    L, D, V = config.num_layers, config.hidden_size, config.vocab_size
    keys = jax.random.split(key, 8)

    def stack(key, in_dim, out_dim):
        ks = jax.random.split(key, L)
        return jnp.stack([init_linear(k, in_dim, out_dim, dtype=dtype) for k in ks])

    return {
        "tok_emb": jax.random.normal(keys[0], (V, D), dtype) * 0.02,
        "pos_emb": jax.random.normal(keys[1], (config.max_seq_len, D), dtype) * 0.02,
        "type_emb": jax.random.normal(keys[6], (config.type_vocab_size, D), dtype) * 0.02,
        "emb_ln_w": jnp.ones((D, ), dtype),
        "emb_ln_b": jnp.zeros((D, ), dtype),
        "layers": {
            "ln1_w": jnp.ones((L, D), dtype), "ln1_b": jnp.zeros((L, D), dtype),
            "ln2_w": jnp.ones((L, D), dtype), "ln2_b": jnp.zeros((L, D), dtype),
            "attn": {
                "w_qkv": stack(keys[2], D, 3 * D),
                "b_qkv": jnp.zeros((L, 3 * D), dtype),
                "w_proj": stack(keys[3], D, D),
                "b_proj": jnp.zeros((L, D), dtype),
            },
            "mlp": {
                "w_fc1": stack(keys[4], D, 4 * D),
                "b_fc1": jnp.zeros((L, 4 * D), dtype),
                "w_fc2": stack(keys[5], 4 * D, D),
                "b_fc2": jnp.zeros((L, D), dtype),
            },
        },
        "mlm_head": init_linear(keys[7], D, V, dtype=dtype),
    }


def forward(config: BertConfig, params, input_ids, token_type_ids=None, attention_mask=None, attention_fn=None):
    b, s = input_ids.shape
    x = params["tok_emb"][input_ids] + params["pos_emb"][:s][None]
    if token_type_ids is not None:
        x = x + params["type_emb"][token_type_ids]
    x = layer_norm(x, params["emb_ln_w"], params["emb_ln_b"], config.ln_eps)
    H = config.num_heads
    attn_fn = attention_fn or default_attention()
    mask = None
    if attention_mask is not None:
        mask = attention_mask[:, None, None, :].astype(bool)  # [B,1,1,S] broadcast over heads/query

    def layer(x, lp):
        # post-LN BERT: attn -> add&norm -> mlp -> add&norm
        qkv = x @ lp["attn"]["w_qkv"].astype(x.dtype) + lp["attn"]["b_qkv"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        d = q.shape[-1] // H
        att = attn_fn(q.reshape(b, s, H, d), k.reshape(b, s, H, d), v.reshape(b, s, H, d),
                      causal=False, mask=mask).reshape(b, s, H * d)
        x = layer_norm(x + att @ lp["attn"]["w_proj"].astype(x.dtype) + lp["attn"]["b_proj"].astype(x.dtype),
                       lp["ln1_w"], lp["ln1_b"], config.ln_eps)
        x = layer_norm(x + gelu_mlp(lp["mlp"], x), lp["ln2_w"], lp["ln2_b"], config.ln_eps)
        return x, None

    if config.remat:
        layer = jax.checkpoint(layer)
    x, _ = jax.lax.scan(layer, x, params["layers"])
    return x @ params["mlm_head"].astype(x.dtype)


def make_loss_fn(config: BertConfig, attention_fn=None) -> Callable:
    """MLM loss; batch: {input_ids, labels[, token_type_ids, attention_mask]}."""

    def loss_fn(params, batch, rng):
        logits = forward(config, params, batch["input_ids"],
                         token_type_ids=batch.get("token_type_ids"),
                         attention_mask=batch.get("attention_mask"),
                         attention_fn=attention_fn)
        return cross_entropy_loss(logits, batch["labels"])

    return loss_fn
