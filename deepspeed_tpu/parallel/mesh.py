"""Device mesh & parallelism topology.

TPU-native replacement for the reference's process-group factories
(deepspeed/utils/groups.py: ``_create_model_parallel:64``,
``_create_expert_and_data_parallel:113``, sequence accessors ``:452-491``) and
``ProcessTopology`` (deepspeed/runtime/pipe/topology.py:12).

Instead of creating torch.distributed process groups per parallelism flavor, we
construct ONE ``jax.sharding.Mesh`` with named axes; each reference "group" becomes
a mesh axis (or tuple of axes) that collectives reduce over:

  reference group                     mesh axis
  ------------------------------      -------------------
  data_parallel_group                 ("data",) (+ "fsdp" when ZeRO shards there)
  model_parallel_group (TP)           ("tensor",)
  pipe_parallel_group                 ("pipe",)
  expert_parallel_group               ("expert",)
  sequence_parallel_group             ("sequence",)
  sequence_data_parallel_group        ("data", "sequence")
  expert_data_parallel_group          ("data",) complement of expert
  zero hpZ secondary partition        inner slice of "fsdp" (ici-adjacent)

Axis order places "tensor"/"sequence" innermost so their collectives ride
ICI-adjacent links, and "pipe" outermost (DCN-friendly) — the same intent as the
reference's D+E vs E+D group layouts (blogs/comm-opt/README.md:37).
"""

import collections
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..runtime.config import MeshConfig
from ..utils.logging import logger

# Canonical axis names (every subsystem refers to these).
DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
TENSOR_AXIS = "tensor"
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"

ALL_AXES = (PIPE_AXIS, DATA_AXIS, FSDP_AXIS, EXPERT_AXIS, SEQUENCE_AXIS, TENSOR_AXIS)


class MeshTopology:
    """Named-axis cartesian device grid — analog of ``ProcessTopology``
    (runtime/pipe/topology.py:12) + ``PipelineParallelGrid`` (:251), realized as a
    ``jax.sharding.Mesh`` plus accessors mirroring groups.py."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    # ---- construction -------------------------------------------------------
    @classmethod
    def build(cls, config: Optional[MeshConfig] = None, devices: Optional[Sequence] = None) -> "MeshTopology":
        config = config or MeshConfig()
        devices = list(devices) if devices is not None else list(jax.devices())
        n = len(devices)
        sizes = dict(config.axis_sizes())
        fixed = 1
        wild_axis = None
        for a, s in sizes.items():
            if s == -1:
                wild_axis = a
            else:
                fixed *= s
        if wild_axis is None:
            if fixed != n:
                raise ValueError(f"mesh axes {sizes} multiply to {fixed} but {n} devices are present")
        else:
            if n % fixed != 0:
                raise ValueError(f"{n} devices not divisible by fixed axes product {fixed}")
            sizes[wild_axis] = n // fixed
        order = list(config.axis_order)
        for a in ALL_AXES:
            if a not in order:
                order.append(a)
        shape = [sizes[a] for a in order]
        grid = np.asarray(devices).reshape(shape)
        mesh = Mesh(grid, axis_names=tuple(order))
        logger.info(f"MeshTopology: {dict(zip(order, shape))} over {n} devices")
        return cls(mesh)

    @classmethod
    def from_axis_dict(cls, axes: Dict[str, int], devices: Optional[Sequence] = None) -> "MeshTopology":
        cfg = {a: axes.get(a, 1) for a in ALL_AXES}
        return cls.build(MeshConfig(**cfg), devices=devices)

    # ---- accessors (groups.py parity) ---------------------------------------
    def axis_size(self, axis: str) -> int:
        return self.mesh.shape[axis]

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def get_data_parallel_world_size(self) -> int:
        """ZeRO's dp world: data × fsdp (the reference shards ZeRO state across the
        whole dp group; we split it into replicated 'data' and sharded 'fsdp')."""
        return self.axis_size(DATA_AXIS) * self.axis_size(FSDP_AXIS)

    def get_model_parallel_world_size(self) -> int:
        return self.axis_size(TENSOR_AXIS)

    def get_pipe_parallel_world_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    def get_expert_parallel_world_size(self) -> int:
        return self.axis_size(EXPERT_AXIS)

    def get_sequence_parallel_world_size(self) -> int:
        return self.axis_size(SEQUENCE_AXIS)

    def get_sequence_data_parallel_world_size(self) -> int:
        """Reference ``_get_sequence_data_parallel_world_size`` (groups.py:497):
        the group ZeRO shards across when Ulysses is active."""
        return self.get_data_parallel_world_size() * self.get_sequence_parallel_world_size()

    # Axis tuples for collectives (feed to lax.p* axis_name=...)
    def data_parallel_axes(self) -> Tuple[str, ...]:
        axes = tuple(a for a in (DATA_AXIS, FSDP_AXIS) if self.axis_size(a) > 1)
        return axes or (DATA_AXIS, )

    def sharding(self, spec: PartitionSpec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __enter__(self):
        self._ctx = self.mesh
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        return self._ctx.__exit__(*exc)

    def __repr__(self):
        return f"MeshTopology({dict(self.mesh.shape)})"


_GLOBAL_TOPOLOGY: Optional[MeshTopology] = None


def set_topology(topo: MeshTopology):
    global _GLOBAL_TOPOLOGY
    _GLOBAL_TOPOLOGY = topo


def get_topology() -> MeshTopology:
    global _GLOBAL_TOPOLOGY
    if _GLOBAL_TOPOLOGY is None:
        _GLOBAL_TOPOLOGY = MeshTopology.build()
    return _GLOBAL_TOPOLOGY


def reset_topology():
    global _GLOBAL_TOPOLOGY
    _GLOBAL_TOPOLOGY = None
