from .mesh import (ALL_AXES, DATA_AXIS, EXPERT_AXIS, FSDP_AXIS, PIPE_AXIS, SEQUENCE_AXIS, TENSOR_AXIS, MeshTopology,
                   get_topology, reset_topology, set_topology)
