"""Profiling tools (reference deepspeed/profiling/)."""
from .flops_profiler import FlopsProfiler, ProfileResult, get_model_profile, profile_fn
