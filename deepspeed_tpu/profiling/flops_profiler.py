"""FLOPs profiler.

Analog of the reference FlopsProfiler (profiling/flops_profiler/profiler.py:28):
the reference monkey-patches torch functionals to count MACs at runtime; under
XLA the compiler already knows — we trace the jitted function once and read
the compiler's own cost analysis (flops/bytes accessed), plus a breakdown of
parameter counts.  ``get_model_profile`` mirrors the standalone entry
(profiler.py:1146).
"""

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from ..utils.logging import log_dist


@dataclasses.dataclass
class ProfileResult:
    flops: float  # per invocation
    bytes_accessed: float
    params: int
    flops_per_param: float

    def human(self) -> str:
        return (f"flops/step={_num(self.flops)}  hbm bytes/step={_num(self.bytes_accessed)}  "
                f"params={_num(self.params)}")


def _num(x: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(x) < 1000:
            return f"{x:.2f}{unit}"
        x /= 1000
    return f"{x:.2f}E"


def profile_fn(fn: Callable, *example_args, static_argnums=()) -> ProfileResult:
    """Compile ``fn`` and read XLA's cost analysis."""
    lowered = jax.jit(fn, static_argnums=static_argnums).lower(*example_args)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    params = 0
    for a in jax.tree_util.tree_leaves(example_args):
        if hasattr(a, "size"):
            params += int(np.size(a))
    return ProfileResult(flops=flops, bytes_accessed=bytes_accessed, params=params,
                         flops_per_param=flops / max(params, 1))


def get_model_profile(loss_fn: Callable, params: Any, batch: Any,
                      rng=None, print_profile: bool = True) -> ProfileResult:
    """Profile one loss-fn invocation (reference get_model_profile:1146)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    res = profile_fn(loss_fn, params, batch, rng)
    n_params = sum(int(np.size(p)) for p in jax.tree_util.tree_leaves(params))
    res = ProfileResult(flops=res.flops, bytes_accessed=res.bytes_accessed,
                        params=n_params, flops_per_param=res.flops / max(n_params, 1))
    if print_profile:
        log_dist(f"flops profile: {res.human()}", ranks=[0])
    return res


def per_module_profile(params: Any, tokens: int, top_k: int = 0):
    """Per-module parameter/FLOP attribution (reference
    ``print_model_profile:282`` — per-module MACs table).

    The reference counts MACs analytically per nn.Module via forward hooks;
    functional pytrees have no modules, so the unit of attribution is the
    param subtree.  Classification is shape + NAME based (the name stands in
    for the reference's module type): leaves matching norm/bias/scale/ln are
    elementwise regardless of stacking (a scan-stacked norm is [L, D], not a
    projection); ``embed``-named tables are lookups (gather, ~copy cost);
    every other >=2D leaf is a projection applied once per token
    (2 * tokens * nelem MACs->FLOPs).  Scan-stacked projections [L, in, out]
    count all L applications.  Returns rows [{'module', 'params', 'flops',
    'flops_pct'}] sorted by flops desc (all rows, or ``top_k``).
    """
    import re as _re
    flat, _ = jax.tree_util.tree_flatten_with_path(params)

    def key_of(path):
        return ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)

    elementwise_pat = _re.compile(r"(?:^|[._])(?:\w*norm\w*|bias|b|scale|ln\w*|g)(?:$|[._])")
    pos_pat = _re.compile(r"(?:^|[._])(?:pos\w*|wpe)(?:$|[._])")
    lookup_pat = _re.compile(r"(?:^|[._])(?:embed\w*|wte|wpe|pos\w*|tok\w*)(?:$|[._])")
    head_pat = _re.compile(r"(?:^|[._])(?:lm_head|unembed|output\w*)(?:$|[._])")

    all_keys = [key_of(p) for p, _ in flat]
    # no explicit unembedding leaf => embeddings are tied: the TOKEN embed
    # table is also the logits projection, the model's biggest matmul
    # (positional tables are lookups only — they never unembed)
    tied_unembed = not any(head_pat.search(k) for k in all_keys)

    rows = []
    for (path, leaf), key in zip(flat, all_keys):
        n = int(np.size(leaf))
        if elementwise_pat.search(key) or np.ndim(leaf) < 2:
            # norms/biases: one multiply-add per element per token; stacked
            # [L, D] leaves apply all L per token, so the whole nelem counts
            flops = float(tokens * max(n, 1))
        elif lookup_pat.search(key):
            flops = float(tokens * int(np.shape(leaf)[-1]))  # gather copy
            if tied_unembed and not pos_pat.search(key):
                flops += 2.0 * tokens * n  # + the tied logits matmul
        else:
            flops = 2.0 * tokens * n       # one matmul pass per token
        rows.append({"module": key, "params": n, "flops": flops})
    total = sum(r["flops"] for r in rows) or 1.0
    for r in rows:
        r["flops_pct"] = 100.0 * r["flops"] / total
    rows.sort(key=lambda r: r["flops"], reverse=True)
    return rows[:top_k] if top_k else rows


def format_module_table(rows, top_k: int = 10) -> str:
    lines = [f"{'module':<48} {'params':>10} {'flops':>10} {'%':>6}"]
    for r in rows[:top_k]:
        lines.append(f"{r['module']:<48} {_num(r['params']):>10} "
                     f"{_num(r['flops']):>10} {r['flops_pct']:>5.1f}%")
    shown = sum(r['flops_pct'] for r in rows[:top_k])
    if len(rows) > top_k:
        lines.append(f"... {len(rows) - top_k} more modules ({100 - shown:.1f}% of flops)")
    return "\n".join(lines)


class FlopsProfiler:
    """Engine-attached profiler (reference FlopsProfiler lifecycle:
    start_profile/stop_profile/print_model_profile) reading XLA cost analysis
    of the engine's compiled train step."""

    def __init__(self, engine=None):
        self.engine = engine
        self._result: Optional[ProfileResult] = None

    def profile_train_step(self, batch, pre_sharded: bool = False) -> ProfileResult:
        """``pre_sharded``: batch is already gas-laid-out AND device-placed (the
        engine's in-step call) — re-running the layout would mis-reshape when
        gas == train_batch_size."""
        eng = self.engine
        if not pre_sharded:
            batch = eng._ensure_gas_layout(batch)
            batch = eng._shard_batch(batch)
        lowered = jax.jit(lambda s, b: eng.train_step_fn(s, b)).lower(eng.state, batch)
        try:
            # cost analysis straight off the lowered HLO — no second backend
            # compile of the train step (which can take minutes on TPU)
            cost = lowered.cost_analysis()
        except Exception:
            cost = None
        if not cost:
            cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0] if cost else {}
        n_params = sum(int(np.size(p)) for p in jax.tree_util.tree_leaves(eng.state.params))
        self._result = ProfileResult(flops=float(cost.get("flops", 0.0)),
                                     bytes_accessed=float(cost.get("bytes accessed", 0.0)),
                                     params=n_params,
                                     flops_per_param=float(cost.get("flops", 0.0)) / max(n_params, 1))
        return self._result

    def print_model_profile(self, tokens: Optional[int] = None, top_k: int = 10):
        """Whole-program totals + per-module table (reference :282)."""
        if self._result is not None:
            log_dist(f"train-step profile: {self._result.human()}", ranks=[0])
        if self.engine is not None and tokens is not None:
            rows = per_module_profile(self.engine.state.params, tokens)
            log_dist("\n" + format_module_table(rows, top_k=top_k), ranks=[0])
