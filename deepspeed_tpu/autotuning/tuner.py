"""Tuner strategies over a discrete experiment space.

Reference: deepspeed/autotuning/tuner/ — BaseTuner.tune (base_tuner.py:34,
early stopping on non-improving trials), GridSearchTuner/RandomTuner
(index_based_tuner.py), ModelBasedTuner (model_based_tuner.py:34, XGBoost
cost model over config features).  The model-based tuner here fits a ridge
regression on one-hot config features — no xgboost dependency, same role:
spend the measurement budget near the predicted optimum.
"""

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

Experiment = Dict[str, Any]
RunFn = Callable[[Experiment], Optional[float]]  # None => failed/OOM


class BaseTuner:
    """Iterates candidate experiments, tracking the best measured metric.

    ``run_fn(exp) -> metric`` (higher is better; callers pre-negate latency).
    Early-stops after ``early_stopping`` consecutive non-improving trials.
    """

    def __init__(self, exps: List[Experiment], run_fn: RunFn, early_stopping: int = 5,
                 seed: int = 1234):
        self.all_exps = list(exps)
        self.run_fn = run_fn
        self.early_stopping = early_stopping
        self.best_exp: Optional[Experiment] = None
        self.best_metric: float = -float("inf")
        self.records: List[Tuple[Experiment, Optional[float]]] = []
        # private seeded stream: exploration order is reproducible across
        # reruns/ranks instead of riding the global `random` module state
        self._rng = random.Random(seed)

    def next_batch(self, remaining: List[Experiment]) -> List[Experiment]:
        raise NotImplementedError

    def tune(self, num_trials: Optional[int] = None) -> Tuple[Optional[Experiment], float]:
        remaining = list(self.all_exps)
        budget = num_trials if num_trials is not None else len(remaining)
        stale = 0
        while remaining and len(self.records) < budget:
            for exp in self.next_batch(remaining):
                remaining.remove(exp)
                metric = self.run_fn(exp)
                self.records.append((exp, metric))
                if metric is not None and metric > self.best_metric:
                    self.best_metric = metric
                    self.best_exp = exp
                    stale = 0
                else:
                    stale += 1
                if stale >= self.early_stopping or len(self.records) >= budget:
                    return self.best_exp, self.best_metric
        return self.best_exp, self.best_metric


class GridSearchTuner(BaseTuner):
    """Exhaustive in declaration order (reference index_based_tuner.py:28)."""

    def next_batch(self, remaining):
        return [remaining[0]]


class RandomTuner(BaseTuner):
    """Uniform random order (reference index_based_tuner.py:14)."""

    def next_batch(self, remaining):
        return [self._rng.choice(remaining)]


def _featurize(exps: List[Experiment]):
    """Encode configs for the regression: numeric knobs (micro-batch, bucket
    sizes) become normalized linear + quadratic terms so the model can place a
    peak *between* tried values; categorical knobs are one-hot; plus a bias."""
    flat = [_flatten(e) for e in exps]
    numeric_keys, categorical = set(), set()
    for f in flat:
        for k, v in f.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                categorical.add((k, repr(v)))
            else:
                numeric_keys.add(k)
    scales = {k: max(abs(float(f.get(k, 0.0))) for f in flat) or 1.0
              for k in numeric_keys}
    num_idx = {k: i for i, k in enumerate(sorted(numeric_keys))}
    cat_idx = {f: i for i, f in enumerate(sorted(categorical))}
    nnum, ncat = len(num_idx), len(cat_idx)

    def vec(exp):
        x = np.zeros(2 * nnum + ncat + 1, dtype=np.float64)  # dslint: disable=float64-in-compute  # host-only ridge-regression features; never shipped to a device
        for k, v in _flatten(exp).items():
            if k in num_idx:
                z = float(v) / scales[k]
                x[2 * num_idx[k]] = z
                x[2 * num_idx[k] + 1] = z * z
            else:
                i = cat_idx.get((k, repr(v)))
                if i is not None:
                    x[2 * nnum + i] = 1.0
        x[-1] = 1.0  # bias
        return x

    return vec


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


class ModelBasedTuner(BaseTuner):
    """Explore-then-exploit with a ridge-regression cost model.

    First ``num_random`` trials are random (exploration), then each batch
    refits the model on measured points and proposes the untried candidate
    with the highest predicted metric (reference model_based_tuner.py:34
    uses XGBoost the same way).
    """

    def __init__(self, exps, run_fn, early_stopping: int = 5, num_random: int = 3,
                 ridge: float = 1e-3, seed: int = 1234):
        super().__init__(exps, run_fn, early_stopping, seed=seed)
        self.num_random = num_random
        self.ridge = ridge
        self._vec = _featurize(self.all_exps)

    def next_batch(self, remaining):
        measured = [(e, m) for e, m in self.records if m is not None]
        if len(measured) < self.num_random:
            return [self._rng.choice(remaining)]
        X = np.stack([self._vec(e) for e, _ in measured])
        y = np.array([m for _, m in measured])
        n = X.shape[1]
        w = np.linalg.solve(X.T @ X + self.ridge * np.eye(n), X.T @ y)
        preds = [(float(self._vec(e) @ w), e) for e in remaining]
        preds.sort(key=lambda p: p[0], reverse=True)
        return [preds[0][1]]
