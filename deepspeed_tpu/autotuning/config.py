"""Autotuning config section (reference deepspeed/autotuning/config.py:
DeepSpeedAutotuningConfig + constants.py defaults)."""

from typing import List, Optional

from ..runtime.config_utils import ConfigModel, Field


class AutotuningConfig(ConfigModel):
    """``autotuning`` section of the training config."""
    allow_extra = True

    enabled: bool = False
    fast: bool = True  # micro-batch sweep only; False adds remat/ZeRO++ knobs
    metric: str = Field("throughput", choices=("latency", "throughput", "flops"))
    start_profile_step: int = Field(3, ge=0)   # warmup steps (compile + cache)
    end_profile_step: int = Field(5, ge=1)     # measured window = end - start
    tuner_type: str = Field("model_based", choices=("gridsearch", "random", "model_based"))
    tuner_early_stopping: int = Field(5, ge=1)  # stop after N non-improving trials
    tuner_num_trials: int = Field(50, ge=1)
    max_train_batch_size: Optional[int] = None  # global cap: mbs * gas * dp
    min_train_batch_size: int = Field(1, ge=1)  # global floor on the sweep
    micro_batch_sizes: Optional[List[int]] = None  # user override of the mbs sweep
    zero_stages: Optional[List[int]] = None        # None -> try all feasible
    exps_dir: str = "autotuning_exps"      # experiment records (jsonl)
    results_dir: str = "autotuning_results"  # winning config
    overwrite: bool = False                # clear previous records first
    # device memory override in bytes; None -> accelerator total_memory()
    # (memory_stats() can be empty on some transports, e.g. the axon tunnel)
    device_memory: Optional[int] = None

    def model_validate(self):
        if self.end_profile_step <= self.start_profile_step:
            raise ValueError("autotuning: end_profile_step must exceed start_profile_step")
