"""The autotuner: memory-feasibility pruning + per-stage tuning spaces +
measured short runs.

Reference: deepspeed/autotuning/autotuner.py — ``tune:404`` walks ZeRO stages
0→3, prunes stages whose model-state memory cannot fit
(``get_instantiation_memory_required_per_gpu:882``), sweeps micro-batch sizes
within each stage's space (``tune_space:525``), and records/emits the best
config.  Differences by design:

* experiments run **in-process** — each candidate re-jits the train step
  (XLA recompile replaces the reference's per-experiment launcher sub-job,
  scheduler.py:33);
* the per-stage spaces tune TPU knobs (remat policy) instead of CUDA ones
  (allgather_bucket_size etc.), which XLA owns;
* memory math assumes bf16 params/grads + fp32 master/m/v (the engine's
  layout, runtime/engine.py), not fp16+fp32 apex conventions.
"""

import itertools
import json
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..utils.logging import logger
from .config import AutotuningConfig
from .tuner import BaseTuner, GridSearchTuner, ModelBasedTuner, RandomTuner

BYTES_PER_PARAM_BF16 = 2
BYTES_PER_PARAM_GRAD = 2          # grads kept in compute dtype
BYTES_PER_PARAM_OPT = 12          # fp32 master + m + v


@dataclass
class ModelInfo:
    """What the tuner needs to know about the model (reference
    ``model_info_profile_run:663`` measures this with a profile job)."""
    num_params: int
    activation_mem_per_mbs: int  # bytes of activations at micro-batch 1


def model_state_memory(num_params: int, stage: int, dp_size: int) -> int:
    """Per-chip model-state bytes under a given ZeRO stage (reference
    autotuner.py:882 ``get_instantiation_memory_required_per_gpu``)."""
    p, g, o = (num_params * BYTES_PER_PARAM_BF16, num_params * BYTES_PER_PARAM_GRAD,
               num_params * BYTES_PER_PARAM_OPT)
    d = max(1, dp_size)
    if stage == 0:
        return p + g + o
    if stage == 1:
        return p + g + o // d
    if stage == 2:
        return p + (g + o) // d
    return (p + g + o) // d


# Per-stage extra knobs (the reference's DEFAULT_TUNING_SPACE_ZERO_*,
# constants.py:116-185, retargeted to TPU knobs).
REMAT_POLICIES = ["dots_with_no_batch_dims_saveable", "nothing_saveable"]


def stage_tuning_space(stage: int, fast: bool = True) -> Dict[str, List[Any]]:
    """Fast mode (reference ``fast_enabled:386``) sweeps micro-batch only;
    full mode adds the remat policy and stage-3 ZeRO++ levers."""
    if fast:
        return {}
    space: Dict[str, List[Any]] = {"activation_checkpointing.policy": REMAT_POLICIES}
    if stage == 3:
        # ZeRO++ analogs are stage-3 levers (runtime/zero/quantized.py)
        space["zero_optimization.zero_quantized_weights"] = [False, True]
    return space


def _set_path(cfg: Dict[str, Any], dotted: str, value: Any) -> None:
    node = cfg
    keys = dotted.split(".")
    for k in keys[:-1]:
        node = node.setdefault(k, {})
    node[keys[-1]] = value


class Autotuner:
    """Searches (stage, micro-batch, knobs) and emits the best config.

    ``runner(exp_config) -> metrics`` executes one short experiment and
    returns ``{"throughput": samples/s, "latency": s, "flops": flops/s}`` or
    None on failure/OOM.  Tests stub it; production uses
    ``make_engine_runner`` below.
    """

    def __init__(self, model_info: ModelInfo, runner: Callable[[Dict[str, Any]], Optional[Dict[str, float]]],
                 user_config: Optional[Dict[str, Any]] = None, dp_size: int = 1,
                 device_memory: Optional[int] = None,
                 config: Optional[AutotuningConfig] = None):
        self.model_info = model_info
        self.runner = runner
        self.user_config = dict(user_config or {})
        self.dp_size = dp_size
        self.config = config or AutotuningConfig(
            **(self.user_config.get("autotuning") or {}))
        self.device_memory = (device_memory if device_memory is not None
                              else self.config.device_memory)
        if self.device_memory is None:
            from ..accelerator import get_accelerator
            self.device_memory = get_accelerator().total_memory() or 16 * (1 << 30)
        self.records: List[Dict[str, Any]] = []
        self.best_exp: Optional[Dict[str, Any]] = None
        self.best_metric: float = -float("inf")

    # ----------------------------------------------------------- search space
    def feasible_stages(self) -> List[int]:
        stages = self.config.zero_stages or [0, 1, 2, 3]
        act = self.model_info.activation_mem_per_mbs
        out = []
        for s in stages:
            need = model_state_memory(self.model_info.num_params, s, self.dp_size) + act
            if need <= self.device_memory:
                out.append(s)
            else:
                logger.info(f"autotuning: ZeRO-{s} infeasible "
                            f"(needs {need >> 20} MiB > {self.device_memory >> 20} MiB)")
        return out

    def max_micro_batch(self, stage: int) -> int:
        free = self.device_memory - model_state_memory(
            self.model_info.num_params, stage, self.dp_size)
        return max(0, free // max(1, self.model_info.activation_mem_per_mbs))

    def _user_gas(self) -> int:
        return int(self.user_config.get("gradient_accumulation_steps") or 1)

    def micro_batch_candidates(self, stage: int) -> List[int]:
        """Memory cap ∩ the user's global batch window: train_batch = mbs * gas
        * dp must land in [min_train_batch_size, max_train_batch_size]."""
        cap = self.max_micro_batch(stage)
        scale = self._user_gas() * max(1, self.dp_size)
        if self.config.max_train_batch_size:
            cap = min(cap, self.config.max_train_batch_size // scale)
        floor = -(-self.config.min_train_batch_size // scale)  # ceil div
        if self.config.micro_batch_sizes:
            return [m for m in self.config.micro_batch_sizes if floor <= m <= cap]
        out, m = [], 1
        while m <= cap:
            if m >= floor:
                out.append(m)
            m *= 2
        return out

    def experiments_for_stage(self, stage: int) -> List[Dict[str, Any]]:
        mbs_list = self.micro_batch_candidates(stage)
        if not mbs_list:
            return []
        space = stage_tuning_space(stage, fast=self.config.fast)
        keys = sorted(space)
        exps = []
        for mbs in mbs_list:
            for combo in itertools.product(*(space[k] for k in keys)):
                exp = json.loads(json.dumps(self.user_config))  # deep copy
                exp.pop("autotuning", None)
                _set_path(exp, "zero_optimization.stage", stage)
                exp["train_micro_batch_size_per_gpu"] = mbs
                # retune the batch triple: keep user gas, drop fixed total
                exp.pop("train_batch_size", None)
                for k, v in zip(keys, combo):
                    _set_path(exp, k, v)
                exps.append(exp)
        return exps

    # ------------------------------------------------------------------ tuning
    def _metric_of(self, metrics: Optional[Dict[str, float]]) -> Optional[float]:
        if metrics is None:
            return None
        name = self.config.metric
        val = metrics.get(name)
        if val is None:
            return None
        return -val if name == "latency" else val

    def _make_tuner(self, exps, run_fn) -> BaseTuner:
        cls = {"gridsearch": GridSearchTuner, "random": RandomTuner,
               "model_based": ModelBasedTuner}[self.config.tuner_type]
        return cls(exps, run_fn, early_stopping=self.config.tuner_early_stopping)

    def tune(self) -> Optional[Dict[str, Any]]:
        """Returns the best experiment config (or None if nothing ran)."""
        t0 = time.time()
        for stage in self.feasible_stages():
            exps = self.experiments_for_stage(stage)
            if not exps:
                continue
            logger.info(f"autotuning: ZeRO-{stage} space has {len(exps)} experiments")

            def run_fn(exp):
                metrics = self.runner(exp)
                rec = {"config": exp, "metrics": metrics, "stage": stage}
                self.records.append(rec)
                return self._metric_of(metrics)

            tuner = self._make_tuner(exps, run_fn)
            best_exp, best_metric = tuner.tune(num_trials=self.config.tuner_num_trials)
            if best_exp is not None and best_metric > self.best_metric:
                self.best_metric = best_metric
                self.best_exp = best_exp
        best_display = None
        if self.best_exp is not None:
            # latency is negated internally for max-comparison; report raw
            best_display = -self.best_metric if self.config.metric == "latency" else self.best_metric
        logger.info(f"autotuning: {len(self.records)} experiments in "
                    f"{time.time() - t0:.1f}s; best {self.config.metric} = {best_display}")
        return self.best_exp

    # ----------------------------------------------------------------- output
    def write_results(self) -> Optional[str]:
        """Write experiment records to exps_dir and the winning config to
        results_dir (reference autotuner.py:1055 ds_config_optimal.json);
        ``overwrite`` clears previous runs' records first."""
        import shutil
        for d in (self.config.exps_dir, self.config.results_dir):
            if self.config.overwrite and os.path.isdir(d):
                shutil.rmtree(d, ignore_errors=True)
            os.makedirs(d, exist_ok=True)
        with open(os.path.join(self.config.exps_dir, "experiments.jsonl"), "w") as fh:
            for rec in self.records:
                fh.write(json.dumps(rec) + "\n")
        if self.best_exp is None:
            return None
        path = os.path.join(self.config.results_dir, "dstpu_config_optimal.json")
        with open(path, "w") as fh:
            json.dump(self.best_exp, fh, indent=2)
        return path


def make_engine_runner(loss_fn, params, topology=None, example_batch_fn=None,
                       warmup_steps: int = 2, measure_steps: int = 3,
                       autotuning_config: Optional[AutotuningConfig] = None):
    """Build the default measured runner: construct an Engine per candidate
    config, run a few steps, report steady-state throughput/latency/flops.

    ``example_batch_fn(train_batch_size) -> batch`` supplies data.  When an
    ``autotuning_config`` is given, its start/end_profile_step define the
    warmup and measured windows (reference autotuner profile-step knobs).
    A value fetch (float(loss)) closes each measurement — on relay transports
    block_until_ready can return early, so only fetches truly sync.
    """
    if autotuning_config is not None:
        warmup_steps = autotuning_config.start_profile_step
        measure_steps = autotuning_config.end_profile_step - autotuning_config.start_profile_step

    def runner(exp_config):
        from ..profiling.flops_profiler import FlopsProfiler
        from ..runtime.config import load_config
        from ..runtime.engine import Engine
        try:
            cfg = load_config(exp_config)
            engine = Engine(loss_fn=loss_fn, params=params, config=cfg, topology=topology)
            batch = example_batch_fn(engine.train_batch_size)
            for _ in range(max(1, warmup_steps)):
                metrics = engine.train_batch(batch)
            float(metrics.loss)  # sync before timing
            t0 = time.time()
            for _ in range(max(1, measure_steps)):
                metrics = engine.train_batch(batch)
            float(metrics.loss)  # only a value fetch truly syncs on relays
            dt = (time.time() - t0) / max(1, measure_steps)
            step_flops = FlopsProfiler(engine).profile_train_step(batch).flops
            samples = engine.train_batch_size
            return {"throughput": samples / dt, "latency": dt,
                    "flops": step_flops / dt}
        except Exception as e:  # OOM / invalid combo -> prune this point
            logger.warning(f"autotuning experiment failed: {e}")
            return None

    return runner
