"""Autotuning — search over (ZeRO stage, micro-batch, remat policy, ZeRO++
knobs) with short measured runs, emitting the best engine config.

TPU-native analog of the reference autotuner (deepspeed/autotuning/
autotuner.py:42 ``Autotuner``, scheduler.py:33 ``ResourceManager``,
tuner/{base_tuner,index_based_tuner,model_based_tuner}.py): where the
reference schedules whole launcher sub-jobs per experiment, here one process
re-jits the train step per candidate config (XLA recompile ~= the reference's
process relaunch, but cheaper and in-process) and measures steady-state step
time on the live mesh.
"""

from .autotuner import Autotuner, ModelInfo
from .config import AutotuningConfig
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner
