"""Environment/compatibility report CLI — the ds_report analog
(reference deepspeed/env_report.py, bin/ds_report): shows framework, JAX/TPU
runtime, device inventory, and native-op build status.
"""

import importlib
import platform
import sys


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _try_version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception:
        return "not installed"


def op_report() -> list:
    """Native-op compatibility matrix (reference op compatibility table)."""
    from .ops.op_builder import AsyncIOBuilder, CPUAdamBuilder
    rows = []
    for builder in (AsyncIOBuilder(), CPUAdamBuilder()):
        compatible = builder.is_compatible()
        built = False
        if compatible:
            try:
                builder.load()
                built = True
            except Exception:
                built = False
        rows.append((builder.name, compatible, built))
    return rows


def pallas_report() -> list:
    """Pallas kernel availability (flash attention, fused optimizers, quantizer)."""
    rows = []
    for name, mod in (("flash_attention", "deepspeed_tpu.ops.attention.flash"),
                      ("fused_adam", "deepspeed_tpu.ops.adam.fused_adam"),
                      ("quantizer", "deepspeed_tpu.ops.quantizer.quantize")):
        try:
            importlib.import_module(mod)
            rows.append((name, True))
        except Exception:
            rows.append((name, False))
    return rows


def main(argv=None):
    import deepspeed_tpu
    print("-" * 70)
    print("DeepSpeed-TPU C++/Pallas op report")
    print("-" * 70)
    for name, compatible, built in op_report():
        mark = GREEN_OK if built else RED_NO
        print(f"{name:<24} compatible={str(compatible):<6} built ... {mark}")
    for name, ok in pallas_report():
        print(f"{name:<24} pallas kernel ............ {GREEN_OK if ok else RED_NO}")
    print("-" * 70)
    print("General environment:")
    print(f"  python ................ {platform.python_version()}")
    print(f"  platform .............. {platform.platform()}")
    print(f"  deepspeed_tpu ......... {deepspeed_tpu.__version__}")
    for mod in ("jax", "jaxlib", "flax", "optax", "numpy", "transformers"):
        print(f"  {mod:<20} {_try_version(mod)}")
    try:
        import jax
        devs = jax.devices()
        print(f"  jax backend ........... {jax.default_backend()}")
        print(f"  devices ............... {len(devs)} x {devs[0].device_kind if devs else 'none'}")
    except Exception as exc:
        print(f"  jax devices ........... unavailable ({exc})")
    print("-" * 70)
    return 0


if __name__ == "__main__":
    sys.exit(main())
