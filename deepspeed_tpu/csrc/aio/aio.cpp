// Threaded async file I/O for NVMe/disk tensor offload.
//
// TPU-native analog of the reference's csrc/aio library
// (deepspeed_aio_thread.cpp / py_ds_aio.cpp): the reference drives libaio
// O_DIRECT queues feeding GPU-pinned buffers; here a worker-thread pool issues
// pread/pwrite against host buffers that JAX device_put/device_get DMA to the
// TPU. Requests return immediately with an id; wait() joins one, wait_all()
// drains the queue. C ABI for ctypes binding (no pybind11 in this image).

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  int id;
  bool is_write;
  std::string path;
  void* buf;
  size_t nbytes;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::unordered_map<int, long long> results;  // id -> bytes or -errno
  std::atomic<int> next_id{1};
  int in_flight = 0;
  bool shutdown = false;

  explicit Handle(int num_threads) {
    for (int i = 0; i < num_threads; ++i) {
      workers.emplace_back([this] { this->worker(); });
    }
  }

  void worker() {
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) return;
        req = queue.front();
        queue.pop_front();
      }
      long long result = run(req);
      {
        std::lock_guard<std::mutex> lk(mu);
        results[req.id] = result;
        --in_flight;
      }
      done_cv.notify_all();
    }
  }

  static long long run(const Request& req) {
    int flags = req.is_write ? (O_WRONLY | O_CREAT | O_TRUNC) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    size_t off = 0;
    while (off < req.nbytes) {
      ssize_t n = req.is_write
                      ? ::pwrite(fd, static_cast<char*>(req.buf) + off, req.nbytes - off, off)
                      : ::pread(fd, static_cast<char*>(req.buf) + off, req.nbytes - off, off);
      if (n < 0) {
        int err = errno;
        ::close(fd);
        return -err;
      }
      if (n == 0) break;  // EOF on read
      off += static_cast<size_t>(n);
    }
    ::close(fd);
    return static_cast<long long>(off);
  }

  int submit(bool is_write, const char* path, void* buf, size_t nbytes) {
    int id = next_id.fetch_add(1);
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(Request{id, is_write, path, buf, nbytes});
      ++in_flight;
    }
    cv.notify_one();
    return id;
  }

  long long wait(int id) {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this, id] { return results.count(id) > 0; });
    long long r = results[id];
    results.erase(id);
    return r;
  }

  int wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this] { return in_flight == 0; });
    int failures = 0;
    for (auto& kv : results)
      if (kv.second < 0) ++failures;
    results.clear();
    return failures;
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }
};

}  // namespace

extern "C" {

void* dstpu_aio_open(int num_threads) {
  if (num_threads < 1) num_threads = 1;
  return new Handle(num_threads);
}

void dstpu_aio_close(void* h) { delete static_cast<Handle*>(h); }

int dstpu_aio_pwrite(void* h, const char* path, void* buf, size_t nbytes) {
  return static_cast<Handle*>(h)->submit(true, path, buf, nbytes);
}

int dstpu_aio_pread(void* h, const char* path, void* buf, size_t nbytes) {
  return static_cast<Handle*>(h)->submit(false, path, buf, nbytes);
}

long long dstpu_aio_wait(void* h, int id) { return static_cast<Handle*>(h)->wait(id); }

int dstpu_aio_wait_all(void* h) { return static_cast<Handle*>(h)->wait_all(); }

}  // extern "C"
