// Threaded async file I/O for NVMe/disk tensor offload.
//
// TPU-native analog of the reference's csrc/aio library
// (deepspeed_aio_thread.cpp / py_ds_aio.cpp): the reference drives libaio
// O_DIRECT queues feeding GPU-pinned buffers; here a worker-thread pool issues
// pread/pwrite against host buffers that JAX device_put/device_get DMA to the
// TPU. Requests return immediately with an id; wait() joins one, wait_all()
// drains the queue. C ABI for ctypes binding (no pybind11 in this image).
//
// O_DIRECT mode (dstpu_aio_open_ex(threads, use_odirect=1)): the bulk of each
// transfer goes through an O_DIRECT fd via a 4096-aligned staging buffer
// (bypassing the page cache, as the reference's deepspeed_aio_common.cpp
// does), with the unaligned tail handled on a buffered fd.  Filesystems that
// reject O_DIRECT (tmpfs) fall back to fully buffered I/O per file.

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <string>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
  int id;
  bool is_write;
  std::string path;
  void* buf;
  size_t nbytes;
};

constexpr size_t kAlign = 4096;           // O_DIRECT block/buffer alignment
constexpr size_t kStageBytes = 16 << 20;  // staging chunk per worker

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::unordered_map<int, long long> results;  // id -> bytes or -errno
  std::atomic<int> next_id{1};
  int in_flight = 0;
  bool shutdown = false;
  bool use_odirect = false;

  explicit Handle(int num_threads, bool odirect = false) : use_odirect(odirect) {
    for (int i = 0; i < num_threads; ++i) {
      workers.emplace_back([this] { this->worker(); });
    }
  }

  void worker() {
    void* stage = nullptr;  // per-worker aligned staging buffer, lazy
    for (;;) {
      Request req;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return shutdown || !queue.empty(); });
        if (shutdown && queue.empty()) break;
        req = queue.front();
        queue.pop_front();
      }
      long long result = -1;
      // zero-byte requests take the buffered path so writes still create the
      // file (O_CREAT|O_TRUNC) and reads of missing files still report ENOENT
      if (use_odirect && req.nbytes > 0) {
        if (stage == nullptr && posix_memalign(&stage, kAlign, kStageBytes) != 0) stage = nullptr;
        result = stage ? run_direct(req, stage) : -ENOMEM;
        if (result == -EINVAL) result = run_buffered(req);  // fs rejects O_DIRECT
      } else {
        result = run_buffered(req);
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        results[req.id] = result;
        --in_flight;
      }
      done_cv.notify_all();
    }
    ::free(stage);
  }

  static long long io_loop(int fd, bool is_write, char* buf, size_t nbytes, size_t file_off) {
    size_t off = 0;
    while (off < nbytes) {
      ssize_t n = is_write ? ::pwrite(fd, buf + off, nbytes - off, file_off + off)
                           : ::pread(fd, buf + off, nbytes - off, file_off + off);
      if (n < 0) return -errno;
      if (n == 0) break;  // EOF on read
      off += static_cast<size_t>(n);
    }
    return static_cast<long long>(off);
  }

  static long long run_buffered(const Request& req) {
    int flags = req.is_write ? (O_WRONLY | O_CREAT | O_TRUNC) : O_RDONLY;
    int fd = ::open(req.path.c_str(), flags, 0644);
    if (fd < 0) return -errno;
    long long n = io_loop(fd, req.is_write, static_cast<char*>(req.buf), req.nbytes, 0);
    ::close(fd);
    return n;
  }

  // Bulk via O_DIRECT + aligned staging copies; sub-block tail via a second
  // buffered fd.  Returns -EINVAL if the filesystem refuses O_DIRECT so the
  // caller can fall back wholesale.
  static long long run_direct(const Request& req, void* stage) {
    const size_t aligned = req.nbytes & ~(kAlign - 1);
    char* user = static_cast<char*>(req.buf);
    long long total = 0;
    if (aligned > 0) {
      int flags = req.is_write ? (O_WRONLY | O_CREAT | O_TRUNC | O_DIRECT)
                               : (O_RDONLY | O_DIRECT);
      int fd = ::open(req.path.c_str(), flags, 0644);
      if (fd < 0) return -errno;
      for (size_t off = 0; off < aligned; off += kStageBytes) {
        size_t chunk = aligned - off < kStageBytes ? aligned - off : kStageBytes;
        if (req.is_write) std::memcpy(stage, user + off, chunk);
        long long n = io_loop(fd, req.is_write, static_cast<char*>(stage), chunk, off);
        if (n < 0) {
          ::close(fd);
          return n;
        }
        // copy only the bytes actually read — a short read must not leak the
        // staging buffer's previous contents past EOF
        if (!req.is_write && n > 0) std::memcpy(user + off, stage, static_cast<size_t>(n));
        total += n;
        if (static_cast<size_t>(n) < chunk) break;  // EOF
      }
      ::close(fd);
    }
    const size_t tail = req.nbytes - aligned;
    if (tail > 0) {
      int flags = req.is_write ? (O_WRONLY | (aligned ? 0 : O_CREAT | O_TRUNC)) : O_RDONLY;
      int fd = ::open(req.path.c_str(), flags, 0644);
      if (fd < 0) return -errno;
      long long n = io_loop(fd, req.is_write, user + aligned, tail, aligned);
      ::close(fd);
      if (n < 0) return n;
      total += n;
    }
    return total;
  }

  int submit(bool is_write, const char* path, void* buf, size_t nbytes) {
    int id = next_id.fetch_add(1);
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(Request{id, is_write, path, buf, nbytes});
      ++in_flight;
    }
    cv.notify_one();
    return id;
  }

  long long wait(int id) {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this, id] { return results.count(id) > 0; });
    long long r = results[id];
    results.erase(id);
    return r;
  }

  int wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this] { return in_flight == 0; });
    int failures = 0;
    for (auto& kv : results)
      if (kv.second < 0) ++failures;
    results.clear();
    return failures;
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }
};

}  // namespace

extern "C" {

void* dstpu_aio_open(int num_threads) {
  if (num_threads < 1) num_threads = 1;
  return new Handle(num_threads);
}

void* dstpu_aio_open_ex(int num_threads, int use_odirect) {
  if (num_threads < 1) num_threads = 1;
  return new Handle(num_threads, use_odirect != 0);
}

void dstpu_aio_close(void* h) { delete static_cast<Handle*>(h); }

int dstpu_aio_pwrite(void* h, const char* path, void* buf, size_t nbytes) {
  return static_cast<Handle*>(h)->submit(true, path, buf, nbytes);
}

int dstpu_aio_pread(void* h, const char* path, void* buf, size_t nbytes) {
  return static_cast<Handle*>(h)->submit(false, path, buf, nbytes);
}

long long dstpu_aio_wait(void* h, int id) { return static_cast<Handle*>(h)->wait(id); }

int dstpu_aio_wait_all(void* h) { return static_cast<Handle*>(h)->wait_all(); }

}  // extern "C"
