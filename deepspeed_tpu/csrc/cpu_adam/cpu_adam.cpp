// Host AdamW for offloaded optimizer states.
//
// Analog of the reference's DeepSpeedCPUAdam (csrc/adam/cpu_adam_impl.cpp with
// AVX simd.h intrinsics): steps fp32 master params + moments living in host
// RAM while the TPU holds only the bf16 compute copy. OpenMP threads across
// chunks; the inner loop is written branch-free so the compiler vectorizes it
// (-O3 -march=native reaches the same AVX2/AVX512 codegen as the reference's
// hand-written intrinsics).

#include <cmath>
#include <cstddef>

extern "C" {

// p/m/v updated in place; g may alias bf16-widened gradients already converted
// to fp32 by the caller. bias_correction: 1-based step, 0 disables.
void dstpu_adamw_step(float* p, float* m, float* v, const float* g, size_t n,
                      float lr, float beta1, float beta2, float eps,
                      float weight_decay, int step) {
  float bc1 = 1.0f, bc2 = 1.0f;
  if (step > 0) {
    bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
    bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  }
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2 = 1.0f / bc2;
#pragma omp parallel for schedule(static)
  for (long long i = 0; i < static_cast<long long>(n); ++i) {
    const float gi = g[i];
    const float mi = beta1 * m[i] + (1.0f - beta1) * gi;
    const float vi = beta2 * v[i] + (1.0f - beta2) * gi * gi;
    m[i] = mi;
    v[i] = vi;
    const float m_hat = mi * inv_bc1;
    const float v_hat = vi * inv_bc2;
    p[i] -= lr * (m_hat / (std::sqrt(v_hat) + eps) + weight_decay * p[i]);
  }
}

}  // extern "C"
