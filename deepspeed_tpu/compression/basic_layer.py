"""Compression primitives: structured pruning masks, activation quantization,
layer reduction.

Analog of the reference's basic_layer.py (deepspeed/compression/
basic_layer.py — ``LinearLayer_Compress`` with sparse/row/head pruning +
weight quantization, ``QuantAct``, ``Embedding_Compress``) and the
layer-reduction path of compress.py.  The reference subclasses nn.Linear and
mutates modules; here every method is a pure array transform over param
leaves, composing with the pytree walk in compress.init_compression.
"""

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


# ------------------------------------------------------------ structured masks
def head_prune_mask(w: jnp.ndarray, num_heads: int, density: float,
                    head_axis: str = "in") -> jnp.ndarray:
    """Attention-head pruning mask (reference head pruning on the attention
    output projection, basic_layer.py head_pruning_*).

    ``w`` is a 2D projection; heads tile the ``in`` (dim 0, the wo case: rows
    are head_dim-sized groups of the attention output) or ``out`` axis (dim 1,
    the wq/wk/wv case).  Heads are ranked by L1 norm; the weakest are zeroed
    whole, keeping ``density`` fraction.  3D scan-stacked leaves [L, d, d]
    (this repo's model layout) prune each layer independently via vmap.
    """
    if w.ndim == 3:
        return jax.vmap(lambda lw: head_prune_mask(lw, num_heads, density, head_axis))(w)
    if w.ndim != 2:
        raise ValueError("head pruning applies to 2D projections (or [L, d, d] stacks)")
    axis = 0 if head_axis == "in" else 1
    if w.shape[axis] % num_heads != 0:
        raise ValueError(f"axis {axis} size {w.shape[axis]} not divisible by {num_heads} heads")
    head_dim = w.shape[axis] // num_heads
    if axis == 0:
        per_head = jnp.sum(jnp.abs(w).reshape(num_heads, head_dim, w.shape[1]), axis=(1, 2))
    else:
        per_head = jnp.sum(jnp.abs(w).reshape(w.shape[0], num_heads, head_dim), axis=(0, 2))
    k = max(1, int(round(num_heads * density)))
    thresh = jnp.sort(per_head)[-k]
    keep = (per_head >= thresh).astype(w.dtype)  # [H]
    if axis == 0:
        mask = jnp.repeat(keep, head_dim)[:, None]
    else:
        mask = jnp.repeat(keep, head_dim)[None, :]
    return jnp.broadcast_to(mask, w.shape)


def channel_prune_mask(w: jnp.ndarray, density: float) -> jnp.ndarray:
    """Structured channel (dim-0 / input-feature) pruning by L1 norm —
    the reference's conv channel pruning retargeted to the leading axis."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(1, w.ndim)))
    k = max(1, int(round(norms.size * density)))
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep.reshape((-1,) + (1,) * (w.ndim - 1)), w.shape)


# ------------------------------------------------------ activation quantization
class QuantAct:
    """Activation fake-quantizer (reference QuantAct, basic_layer.py:41).

    ``dynamic=True`` computes the range per call from the traced activation —
    safe anywhere, including inside jit.  Static mode tracks a running max
    (momentum EMA) on the HOST: calibrate by calling it on concrete arrays
    (eager forward passes), then ``freeze()``; the frozen scale is a Python
    constant, so the frozen quantizer IS jit-safe.  Calibrating inside a
    jitted function cannot work (host state can't update under trace) and
    raises instead of silently mis-calibrating.
    """

    def __init__(self, bits: int = 8, dynamic: bool = True, momentum: float = 0.95):
        self.bits = bits
        self.dynamic = dynamic
        self.momentum = momentum
        self.running_max: Optional[float] = None
        self.frozen = False

    def freeze(self):
        self.frozen = True

    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        qmax = 2.0 ** (self.bits - 1) - 1
        if self.dynamic:
            scale = jnp.maximum(jnp.abs(x).max(), 1e-8) / qmax
        else:
            if not self.frozen:
                if isinstance(x, jax.core.Tracer):
                    raise RuntimeError(
                        "QuantAct static calibration saw a traced array — run "
                        "calibration passes EAGERLY (outside jit), then freeze(); "
                        "or use dynamic=True for in-jit ranges")
                cur = float(jnp.abs(x).max())
                self.running_max = (cur if self.running_max is None else
                                    self.momentum * self.running_max +
                                    (1 - self.momentum) * cur)
            scale = max(self.running_max or 1e-8, 1e-8) / qmax
        q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
        return (q * scale).astype(x.dtype)


# ------------------------------------------------------------- layer reduction
def layer_reduction(stacked_params: Any, keep_layers: Sequence[int]) -> Any:
    """Depth reduction on scan-stacked layer params (reference compress.py
    layer_reduction: student keeps ``keep_layers`` of the teacher's layers,
    e.g. [0, 2, 4, ...] — the teacher-layer remap of TinyBERT-style KD)."""
    idx = np.asarray(keep_layers, np.int32)
    leaves = jax.tree_util.tree_leaves(stacked_params)
    lead = {np.shape(l)[0] if np.ndim(l) >= 1 else 0 for l in leaves}
    if len(lead) != 1:
        # heterogeneous leading dims mean this is NOT a pure layer stack —
        # silently slicing would corrupt e.g. an embedding table; callers must
        # point at the stacked subtree (redundancy_clean's module_name_prefix)
        raise ValueError(
            f"layer_reduction needs a homogeneous [L, ...] stack; got leading "
            f"dims {sorted(lead)} — select the stacked subtree explicitly")
    (num_layers,) = lead
    if num_layers <= int(idx.max()):
        raise ValueError(f"keep_layers index {int(idx.max())} out of range for "
                         f"{num_layers} layers")
    return jax.tree_util.tree_map(lambda leaf: jnp.take(leaf, idx, axis=0), stacked_params)


# --------------------------------------------------------- physical shrinking
def shrink_rows(w: jnp.ndarray, mask_row_keep: np.ndarray) -> jnp.ndarray:
    """Materialize row pruning by slicing the kept rows out (reference
    redundancy_clean:148 — after mask training, weights physically shrink)."""
    keep = np.nonzero(np.asarray(mask_row_keep))[0]
    return jnp.take(w, keep, axis=w.ndim - 1)
