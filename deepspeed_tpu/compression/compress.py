"""Compression library — weight/activation quantization + pruning.

Analog of the reference compression module (deepspeed/compression/compress.py
init_compression:100 / redundancy_clean:148, basic_layer.py LinearLayer_Compress
variants, scheduler.py): the reference swaps nn.Linear modules for compressed
variants; here compression is a pytree transform — masks/fake-quant applied to
matching param leaves — plus a scheduler that ramps compression over steps.

Methods (per reference config groups): weight quantization (symmetric int4/8
fake quant), sparse pruning (magnitude topk), row pruning (structured L1 rows),
head pruning (attention-head granularity).
"""

import re
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist


def _match(path: str, patterns) -> bool:
    return any(re.search(p, path) for p in patterns)


def fake_quantize(w: jnp.ndarray, bits: int = 8, group_size: int = 0) -> jnp.ndarray:
    """Symmetric fake quantization (QuantAct/weight quantization analog)."""
    qmax = 2.0**(bits - 1) - 1
    if group_size and w.size % group_size == 0:
        flat = w.reshape(-1, group_size)
        scale = jnp.maximum(jnp.abs(flat).max(axis=1, keepdims=True), 1e-8) / qmax
        q = jnp.clip(jnp.round(flat / scale), -qmax, qmax)
        return (q * scale).reshape(w.shape).astype(w.dtype)
    scale = jnp.maximum(jnp.abs(w).max(), 1e-8) / qmax
    return (jnp.clip(jnp.round(w / scale), -qmax, qmax) * scale).astype(w.dtype)


def sparse_prune_mask(w: jnp.ndarray, density: float) -> jnp.ndarray:
    """Unstructured magnitude pruning mask keeping ``density`` fraction."""
    k = max(1, int(round(w.size * density)))
    thresh = jnp.sort(jnp.abs(w).reshape(-1))[-k]
    return (jnp.abs(w) >= thresh).astype(w.dtype)


def row_prune_mask(w: jnp.ndarray, density: float) -> jnp.ndarray:
    """Structured row pruning by L1 norm (rows = output features, last dim)."""
    norms = jnp.sum(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    k = max(1, int(round(norms.size * density)))
    thresh = jnp.sort(norms)[-k]
    keep = (norms >= thresh).astype(w.dtype)
    return jnp.broadcast_to(keep, w.shape)


class CompressionScheduler:
    """Ramp compression over steps (reference compression scheduler.py):
    no-op until offset, then apply every ``frequency`` steps."""

    def __init__(self, schedule_offset: int = 0, frequency: int = 1):
        self.schedule_offset = schedule_offset
        self.frequency = max(1, frequency)

    def is_active(self, global_step: int) -> bool:
        return global_step >= self.schedule_offset and \
            (global_step - self.schedule_offset) % self.frequency == 0


def init_compression(params: Any, config: Dict, paths: Optional[Any] = None) -> Any:
    """Apply configured compression transforms to matching leaves
    (reference init_compression:100).

    config example (reference-shaped):
      {"weight_quantization": {"shared_parameters": {...}, "different_groups": {
           "wq1": {"params": {"target_bits": 8}, "modules": ["attn\\."]}}},
       "sparse_pruning": {"different_groups": {"sp1": {"params": {"dense_ratio": 0.5},
           "modules": [".*mlp.*"]}}}}
    """
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)

    def key_of(path):
        return ".".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)

    from .basic_layer import channel_prune_mask, head_prune_mask

    wq = config.get("weight_quantization", {}).get("different_groups", {})
    sp = config.get("sparse_pruning", {}).get("different_groups", {})
    rp = config.get("row_pruning", {}).get("different_groups", {})
    hp = config.get("head_pruning", {}).get("different_groups", {})
    cp = config.get("channel_pruning", {}).get("different_groups", {})
    hp_shared = config.get("head_pruning", {}).get("shared_parameters", {})

    out = []
    n_q = n_s = n_r = n_h = n_c = 0
    for path, leaf in flat:
        key = key_of(path)
        new = leaf
        if np.ndim(leaf) >= 2:
            for group in wq.values():
                if _match(key, group.get("modules", [".*"])):
                    bits = int(group.get("params", {}).get("target_bits", 8))
                    new = fake_quantize(new, bits=bits)
                    n_q += 1
                    break
            for group in sp.values():
                if _match(key, group.get("modules", [".*"])):
                    density = float(group.get("params", {}).get("dense_ratio", 0.5))
                    new = new * sparse_prune_mask(new, density)
                    n_s += 1
                    break
            for group in rp.values():
                if _match(key, group.get("modules", [".*"])):
                    density = float(group.get("params", {}).get("dense_ratio", 0.5))
                    new = new * row_prune_mask(new, density)
                    n_r += 1
                    break
            for group in hp.values():
                if _match(key, group.get("modules", [".*"])):
                    gp = group.get("params", {})
                    heads = int(gp.get("num_heads", hp_shared.get("num_heads", 0)))
                    if heads <= 0:
                        raise ValueError("head_pruning needs num_heads (group params "
                                         "or shared_parameters)")
                    density = float(gp.get("dense_ratio", 0.5))
                    new = new * head_prune_mask(new, heads, density,
                                                head_axis=gp.get("head_axis", "in"))
                    n_h += 1
                    break
            for group in cp.values():
                if _match(key, group.get("modules", [".*"])):
                    density = float(group.get("params", {}).get("dense_ratio", 0.5))
                    new = new * channel_prune_mask(new, density)
                    n_c += 1
                    break
        out.append(new)
    log_dist(f"compression: quantized={n_q} sparse={n_s} row={n_r} head={n_h} "
             f"channel={n_c} leaves", ranks=[0])
    return jax.tree_util.tree_unflatten(treedef, out)


def redundancy_clean(params: Any, config: Dict) -> Any:
    """Materialize compression permanently (reference redundancy_clean:148):
    re-apply masks so zeros are baked in, then perform layer reduction if the
    config requests it (``compression_training.layer_reduction`` — student
    keeps a subset of teacher layers, physically dropping the rest)."""
    params = init_compression(params, config)
    lr_cfg = config.get("layer_reduction", {})
    if lr_cfg.get("enabled"):
        from .basic_layer import layer_reduction
        keep = lr_cfg.get("keep_layers")
        if keep is None:
            num = int(lr_cfg["keep_number_layer"])
            total = int(lr_cfg["teacher_layer"])
            keep = np.linspace(0, total - 1, num).round().astype(int).tolist()
        target = lr_cfg.get("module_name_prefix")
        if target:
            sub = params
            for part in target.split("."):
                sub = sub[part]
            reduced = layer_reduction(sub, keep)
            params = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy tree
            node = params
            parts = target.split(".")
            for part in parts[:-1]:
                node = node[part]
            node[parts[-1]] = reduced
        else:
            params = layer_reduction(params, keep)
    return params
