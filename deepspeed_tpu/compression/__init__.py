"""Compression library (reference deepspeed/compression/)."""
from .basic_layer import (QuantAct, channel_prune_mask, head_prune_mask,
                          layer_reduction, shrink_rows)
from .compress import (CompressionScheduler, fake_quantize, init_compression, redundancy_clean,
                       row_prune_mask, sparse_prune_mask)
