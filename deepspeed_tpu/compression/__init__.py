"""Compression library (reference deepspeed/compression/)."""
from .compress import (CompressionScheduler, fake_quantize, init_compression, redundancy_clean,
                       row_prune_mask, sparse_prune_mask)
