"""``dstpu-lint`` command line.

    dstpu-lint [paths...]                # default: deepspeed_tpu/ + tests/
    dstpu-lint --changed [BASE]          # only files changed vs a git base
    dstpu-lint --format json             # machine-readable
    dstpu-lint --format sarif            # CI inline-annotation format
    dstpu-lint --update-baseline         # grandfather current findings
    dstpu-lint --update-api-surface      # re-pin the external jax surface
    dstpu-lint --update-mesh-manifest    # re-pin the declared mesh axes
    dstpu-lint --jobs 4                  # fork 4 workers over the file pass
    dstpu-lint --list-rules
    dstpu-lint --list-suppressions       # audit inline suppressions

Exit codes: 0 clean, 1 non-baselined findings, 2 usage/internal error.
"""

import argparse
import os
import sys

from .api_surface import (DEFAULT_MANIFEST_NAME, collect_api_surface,
                          load_api_surface, save_api_surface)
from .baseline import (DEFAULT_BASELINE_NAME, load_baseline, load_baseline_entries,
                       save_baseline)
from .mesh_model import (DEFAULT_MESH_MANIFEST_NAME, collect_mesh_axes,
                         load_mesh_manifest, save_mesh_manifest)
from .reporters import report_json, report_sarif, report_text
from .rules import META_RULES, RULES, build_rules
from .runner import (LintResult, changed_python_files, iter_python_files,
                     load_modules, run_lint)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dstpu-lint",
        description="JAX/TPU-aware static analysis for deepspeed_tpu (dslint)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: deepspeed_tpu/ "
                        "plus tests/, which only test-scoped rules scan)")
    p.add_argument("--root", default=None,
                   help="repo root for relative paths + default baseline location "
                        "(default: cwd)")
    p.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="lint only .py files changed vs the git BASE (default "
                        "HEAD: uncommitted work; use origin/main for a "
                        "pre-push pass), scoped to the standard scan roots — "
                        "subset lints still build whole-package context, so "
                        "findings match the full run; mutually exclusive "
                        "with explicit paths")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON path (default: <root>/{DEFAULT_BASELINE_NAME})")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file (report everything)")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current findings and exit 0")
    p.add_argument("--api-surface", default=None,
                   help="api-surface manifest path "
                        f"(default: <root>/{DEFAULT_MANIFEST_NAME})")
    p.add_argument("--update-api-surface", action="store_true",
                   help="re-pin the package's external jax surface into the "
                        "manifest and exit 0 (review the diff before committing)")
    p.add_argument("--mesh-manifest", default=None,
                   help="mesh-axis manifest path "
                        f"(default: <root>/{DEFAULT_MESH_MANIFEST_NAME})")
    p.add_argument("--update-mesh-manifest", action="store_true",
                   help="re-pin the package's declared mesh axis names into "
                        "the manifest and exit 0 (review the diff before "
                        "committing)")
    p.add_argument("--disable", default="",
                   help="comma-separated rule names to skip")
    p.add_argument("--select", default="",
                   help="comma-separated rule names to run exclusively")
    p.add_argument("--no-unused-suppressions", action="store_true",
                   help="don't report stale suppression comments")
    p.add_argument("--jobs", type=int, default=1, metavar="N",
                   help="fork N parallel workers over the per-file rule pass "
                        "(0 = cpu count); the project-context build stays "
                        "single-pass, and results are identical to -j1")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-suppressions", action="store_true",
                   help="audit every inline suppression: per-rule counts with "
                        "file:line and reasons, stale/reasonless entries "
                        "highlighted; exits 1 if any need attention")
    return p


def _list_suppressions(paths, root, rules, api_surface, mesh_manifest,
                       jobs: int) -> int:
    """The ``--list-suppressions`` audit.  Static inventory first (every
    suppression comment, including inert reasonless ones), then a full lint
    to mark entries whose finding no longer exists as stale."""
    from .suppressions import parse_suppressions
    modules, _ = load_modules(iter_python_files(paths), root)
    entries = []            # (relpath, Suppression)
    reasonless = []         # bad-suppression Findings
    for mod in modules:
        sups, problems = parse_suppressions(mod.source, mod.relpath)
        entries.extend((mod.relpath, s) for s in sups)
        reasonless.extend(p for p in problems if p.rule == "bad-suppression")
    result = run_lint(paths, root=root, rules=rules, baseline={},
                      report_unused_suppressions=True,
                      api_surface=api_surface, mesh_manifest=mesh_manifest,
                      jobs=jobs)
    stale = {(f.path, f.line) for f in result.findings
             if f.rule == "unused-suppression"}
    n_stale = sum(1 for rp, s in entries if (rp, s.line) in stale)
    print(f"dstpu-lint: {len(entries)} suppression(s) across "
          f"{len({rp for rp, _ in entries})} file(s); {n_stale} stale, "
          f"{len(reasonless)} without a reason")
    by_rule: dict = {}
    for rp, s in entries:
        for r in s.rules:
            by_rule.setdefault(r, []).append((rp, s))
    for rule in sorted(by_rule):
        rows = sorted(by_rule[rule], key=lambda t: (t[0], t[1].line))
        print(f"\n{rule} ({len(rows)})")
        for rp, s in rows:
            mark = " [STALE]" if (rp, s.line) in stale else ""
            print(f"  {rp}:{s.line}{mark}  {s.reason}")
    if reasonless:
        print(f"\nwithout a reason ({len(reasonless)}) — inert; fix or remove")
        for p in sorted(reasonless, key=lambda f: (f.path, f.line)):
            print(f"  {p.path}:{p.line} [NO REASON]  {p.snippet}")
    return 1 if (n_stale or reasonless) else 0


def main(argv=None) -> int:
    args = _parser().parse_args(argv)
    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:26s} {RULES[name].description}")
        for name, desc in sorted(META_RULES.items()):
            print(f"{name:26s} (meta) {desc}")
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    if args.jobs < 0:
        print("dstpu-lint: --jobs must be >= 0", file=sys.stderr)
        return 2
    jobs = args.jobs or (os.cpu_count() or 1)
    if args.list_suppressions and (args.update_baseline or
                                   args.update_api_surface or
                                   args.update_mesh_manifest):
        print("dstpu-lint: --list-suppressions is a read-only audit; it "
              "cannot be combined with --update-*", file=sys.stderr)
        return 2
    if args.changed is not None and args.paths:
        print("dstpu-lint: --changed computes its own file set; explicit "
              "paths cannot be combined with it", file=sys.stderr)
        return 2
    if args.changed is not None and (args.update_baseline or
                                     args.update_api_surface or
                                     args.update_mesh_manifest):
        # an empty change set exits 0 before the update blocks run — the
        # requested regeneration would silently no-op while reporting success
        print("dstpu-lint: --changed cannot be combined with --update-* "
              "(baseline/manifest regeneration always covers the full "
              "package)", file=sys.stderr)
        return 2
    if args.changed is not None:
        try:
            paths = changed_python_files(root, args.changed)
        except (ValueError, OSError) as exc:
            print(f"dstpu-lint: --changed: {exc}", file=sys.stderr)
            return 2
        if not paths:
            if args.format == "text":
                print(f"dstpu-lint: no python files changed vs {args.changed}")
            else:
                # a CI consumer piping --format json/sarif must get a valid
                # (empty) document on no-change runs, not a prose line
                empty = LintResult(findings=[], baselined=[], suppressed_count=0,
                                   files_checked=0, rules_run=[], seconds=0.0)
                print({"json": report_json,
                       "sarif": report_sarif}[args.format](empty))
            return 0
    elif args.paths:
        paths = args.paths
    else:
        # tests/ rides along by default, scanned only by test-scoped rules
        # (direct-shimmed-import), so a drifted test import is a lint error
        # instead of a silent collection failure
        paths = [os.path.join(root, "deepspeed_tpu")]
        tests_dir = os.path.join(root, "tests")
        if os.path.isdir(tests_dir):
            paths.append(tests_dir)
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"dstpu-lint: no such path(s): {', '.join(missing)}", file=sys.stderr)
        return 2

    try:
        selected = [r.strip() for r in args.select.split(",") if r.strip()] or None
        disabled = [r.strip() for r in args.disable.split(",") if r.strip()]
        rules = build_rules(selected, disabled)
    except KeyError as exc:
        print(f"dstpu-lint: {exc.args[0]}", file=sys.stderr)
        return 2
    if args.update_baseline and (selected or disabled):
        # a restricted-rule run sees only a slice of the findings; rewriting
        # the baseline from it would silently delete every other rule's entries
        print("dstpu-lint: --update-baseline cannot be combined with "
              "--select/--disable (it would drop the unselected rules' entries)",
              file=sys.stderr)
        return 2
    api_path = args.api_surface or os.path.join(root, DEFAULT_MANIFEST_NAME)
    if args.update_api_surface:
        # same hardening as --update-baseline: the manifest is ALWAYS the whole
        # package's surface — a rule-restricted or path-restricted run must not
        # quietly re-pin from a partial view
        if selected or disabled:
            print("dstpu-lint: --update-api-surface cannot be combined with "
                  "--select/--disable (the manifest is rule-independent and "
                  "always covers the full package)", file=sys.stderr)
            return 2
        pkg = os.path.join(root, "deepspeed_tpu")
        if not os.path.isdir(pkg):
            print(f"dstpu-lint: no package at {pkg} to pin", file=sys.stderr)
            return 2
        modules, errors = load_modules(iter_python_files([pkg]), root)
        if errors:
            print(f"dstpu-lint: refusing to update the api-surface manifest with "
                  f"{len(errors)} unparseable file(s) — the pinned surface would "
                  f"be incomplete: "
                  + "; ".join(f"{e.path}:{e.line}" for e in errors[:5]),
                  file=sys.stderr)
            return 2
        surface = collect_api_surface(modules)
        save_api_surface(api_path, surface)
        print(f"dstpu-lint: api-surface manifest updated ({len(surface)} "
              f"symbol(s) over {len(modules)} package files) -> {api_path}")
        return 0

    mesh_path = args.mesh_manifest or os.path.join(root, DEFAULT_MESH_MANIFEST_NAME)
    if args.update_mesh_manifest:
        # same hardening as the other two manifests: the pinned axis set is
        # ALWAYS the whole package's declarations — a rule-restricted or
        # path-restricted run must not quietly re-pin from a partial view
        if selected or disabled:
            print("dstpu-lint: --update-mesh-manifest cannot be combined with "
                  "--select/--disable (the manifest is rule-independent and "
                  "always covers the full package)", file=sys.stderr)
            return 2
        pkg = os.path.join(root, "deepspeed_tpu")
        if not os.path.isdir(pkg):
            print(f"dstpu-lint: no package at {pkg} to pin", file=sys.stderr)
            return 2
        modules, errors = load_modules(iter_python_files([pkg]), root)
        if errors:
            print(f"dstpu-lint: refusing to update the mesh manifest with "
                  f"{len(errors)} unparseable file(s) — the pinned axis set "
                  f"would be incomplete: "
                  + "; ".join(f"{e.path}:{e.line}" for e in errors[:5]),
                  file=sys.stderr)
            return 2
        axes = collect_mesh_axes(modules)
        save_mesh_manifest(mesh_path, axes)
        print(f"dstpu-lint: mesh manifest updated ({len(axes)} axis name(s) "
              f"over {len(modules)} package files) -> {mesh_path}")
        return 0

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE_NAME)
    try:
        baseline = {} if (args.no_baseline or args.update_baseline) \
            else load_baseline(baseline_path)
    except (ValueError, OSError) as exc:
        print(f"dstpu-lint: bad baseline {baseline_path}: {exc}", file=sys.stderr)
        return 2
    try:
        api_surface = load_api_surface(api_path)
    except (ValueError, OSError) as exc:
        print(f"dstpu-lint: bad api-surface manifest {api_path}: {exc}",
              file=sys.stderr)
        return 2
    try:
        mesh_manifest = load_mesh_manifest(mesh_path)
    except (ValueError, OSError) as exc:
        print(f"dstpu-lint: bad mesh manifest {mesh_path}: {exc}",
              file=sys.stderr)
        return 2

    if args.list_suppressions:
        return _list_suppressions(paths, root, rules, api_surface,
                                  mesh_manifest, jobs)

    result = run_lint(paths, root=root, rules=rules, baseline=baseline,
                      report_unused_suppressions=not args.no_unused_suppressions,
                      api_surface=api_surface, mesh_manifest=mesh_manifest,
                      jobs=jobs)

    if args.update_baseline:
        # meta findings (stale suppressions, bad comments, parse errors) are
        # actionable hygiene, never grandfathered; entries for files outside
        # this run's scope are carried forward (a subset update must not
        # delete other files' entries)
        keep = [f for f in result.findings if f.rule not in META_RULES]
        checked = set(result.checked_paths)
        preserved = [e for e in load_baseline_entries(baseline_path)
                     if e.get("path") not in checked]
        save_baseline(baseline_path, keep, preserve_entries=preserved)
        print(f"dstpu-lint: baseline updated ({len(keep)} finding(s) grandfathered, "
              f"{len(preserved)} out-of-scope entr(ies) preserved) -> {baseline_path}")
        return 0

    reporter = {"json": report_json, "sarif": report_sarif,
                "text": report_text}[args.format]
    print(reporter(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
