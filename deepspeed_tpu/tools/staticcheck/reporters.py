"""Text and JSON reporters for lint results."""

import json

from .runner import LintResult


def report_text(result: LintResult) -> str:
    lines = [f.format_text() for f in result.findings]
    s = result.summary()
    tail = (f"dslint: {s['files_checked']} files, {len(result.rules_run)} rules, "
            f"{s['findings']} finding(s) "
            f"({s['baselined']} baselined, {s['suppressed']} suppressed) "
            f"in {s['seconds']:.2f}s")
    if result.findings:
        by_rule = ", ".join(f"{k}={v}" for k, v in s["by_rule"].items())
        tail += f"\n  by rule: {by_rule}"
    lines.append(tail)
    return "\n".join(lines)


def report_json(result: LintResult) -> str:
    return json.dumps({
        "version": 1,
        "findings": [f.to_dict() for f in result.findings],
        "baselined": [f.to_dict() for f in result.baselined],
        "summary": result.summary(),
    }, indent=1)
