"""Text, JSON and SARIF reporters for lint results."""

import json

from .runner import LintResult
from .rules import META_RULES, RULES

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def report_text(result: LintResult) -> str:
    lines = [f.format_text() for f in result.findings]
    s = result.summary()
    tail = (f"dslint: {s['files_checked']} files, {len(result.rules_run)} rules, "
            f"{s['findings']} finding(s) "
            f"({s['baselined']} baselined, {s['suppressed']} suppressed) "
            f"in {s['seconds']:.2f}s")
    if result.findings:
        by_rule = ", ".join(f"{k}={v}" for k, v in s["by_rule"].items())
        tail += f"\n  by rule: {by_rule}"
    lines.append(tail)
    return "\n".join(lines)


def report_json(result: LintResult) -> str:
    return json.dumps({
        "version": 1,
        "findings": [f.to_dict() for f in result.findings],
        "baselined": [f.to_dict() for f in result.baselined],
        "summary": result.summary(),
    }, indent=1)


def report_sarif(result: LintResult) -> str:
    """SARIF 2.1.0 — what CI annotation surfaces (GitHub code scanning et
    al.) ingest to render findings inline on the diff.  Only ACTIVE findings
    are emitted (baselined debt would re-annotate every PR); the dslint
    fingerprint rides in partialFingerprints so upload dedup matches the
    baseline's identity, and rule metadata comes from the live registry so
    the catalog can't drift from the code."""
    rule_ids = sorted({f.rule for f in result.findings} |
                      set(result.rules_run))
    rules_meta = []
    for rid in rule_ids:
        if rid in RULES:
            desc = RULES[rid].description
        else:
            desc = META_RULES.get(rid, "")
        rules_meta.append({"id": rid,
                           "shortDescription": {"text": desc or rid}})
    index_of = {m["id"]: i for i, m in enumerate(rules_meta)}
    results = []
    for f in result.findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": index_of.get(f.rule, -1),
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f.message},
            "partialFingerprints": {"dslintFingerprint/v1": f.fingerprint},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1,
                               "endLine": max(f.end_line, f.line),
                               "snippet": {"text": f.snippet}},
                },
            }],
        })
    return json.dumps({
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                # informationUri must be an ABSOLUTE URI per SARIF 2.1.0 —
                # omitted (optional) rather than risking strict-consumer
                # rejection on a relative path
                "name": "dslint",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }, indent=1)
