"""Lint driver: walk files, build the project context, run rules, apply
suppressions and the baseline."""

import ast
import dataclasses
import os
import subprocess
import time
from typing import Dict, Iterable, List, Optional, Sequence, Set

from .api_surface import DEFAULT_MANIFEST_NAME, load_api_surface
from .baseline import apply_baseline
from .context import ModuleInfo, ProjectContext
from .findings import Finding
from .mesh_model import DEFAULT_MESH_MANIFEST_NAME, load_mesh_manifest
from .rules import RULES, Rule, build_rules
from .suppressions import SuppressionIndex, parse_suppressions

EXCLUDE_DIR_NAMES = {"__pycache__", ".git", ".ipynb_checkpoints"}
# files under tests/ are scoped to the rules that opt into scanning them
# (Rule.scan_tests) — library contracts like hot-path syncs don't apply there
TEST_PATH_PREFIX = "tests/"

_UNSET = object()


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]            # active (non-baselined, non-suppressed)
    baselined: List[Finding]
    suppressed_count: int
    files_checked: int
    rules_run: List[str]
    seconds: float
    checked_paths: List[str] = dataclasses.field(default_factory=list)  # relpaths

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary(self) -> Dict[str, object]:
        by_rule: Dict[str, int] = {}
        for f in self.findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        return {"files_checked": self.files_checked,
                "rules_run": self.rules_run,
                "findings": len(self.findings),
                "baselined": len(self.baselined),
                "suppressed": self.suppressed_count,
                "by_rule": dict(sorted(by_rule.items())),
                "seconds": round(self.seconds, 2),
                "ok": self.ok}


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs if d not in EXCLUDE_DIR_NAMES)
            out.extend(os.path.join(root, f) for f in sorted(files) if f.endswith(".py"))
    return out


# the default scan roots, shared with the CLI: --changed keeps its findings
# identical to what the full `dstpu-lint` run reports, so it must not pull in
# repo files (bench/scripts/conftest) the full run never lints
DEFAULT_SCAN_DIRS = ("deepspeed_tpu", "tests")


def changed_python_files(root: str, base: str) -> List[str]:
    """Absolute paths of ``.py`` files changed vs the git ``base`` — committed
    diff, working-tree edits, and untracked files; deletions drop out; scoped
    to the default scan roots under ``root``.  Powers ``dstpu-lint --changed
    [BASE]``: subset lints still build whole-package context (run_lint below),
    so a changed-files run reports exactly what the full run would report for
    those files."""
    # `git diff --name-only` prints paths relative to the repo TOPLEVEL, which
    # is not necessarily `root` (package in a monorepo subdir, or invoked from
    # inside the tree) — resolve against the toplevel or every committed
    # change silently drops out of the file set
    top = subprocess.run(["git", "rev-parse", "--show-toplevel"],
                         cwd=root, capture_output=True, text=True)
    if top.returncode != 0:
        raise ValueError(f"not a git repository: "
                         f"{top.stderr.strip() or top.stdout.strip()}")
    toplevel = top.stdout.strip()
    # diff vs the MERGE-BASE, not BASE itself: with BASE=origin/main on a
    # branch that is behind upstream, a two-dot diff would pull in every file
    # changed only upstream — files the developer never touched
    mb = subprocess.run(["git", "merge-base", base, "HEAD"],
                        cwd=root, capture_output=True, text=True)
    diff_base = mb.stdout.strip() if mb.returncode == 0 else base
    # quotepath=off: with the default core.quotepath, a non-ASCII filename
    # comes back octal-escaped in quotes and fails the .py filter — the
    # developer's change silently drops out (a false-green lane)
    diff = subprocess.run(
        ["git", "-c", "core.quotepath=off", "diff", "--name-only",
         "--diff-filter=d", diff_base],
        cwd=root, capture_output=True, text=True)
    if diff.returncode != 0:
        raise ValueError(f"git diff vs '{base}' failed: "
                         f"{diff.stderr.strip() or diff.stdout.strip()}")
    untracked = subprocess.run(
        ["git", "-c", "core.quotepath=off", "ls-files", "--others",
         "--exclude-standard"],
        cwd=root, capture_output=True, text=True)
    if untracked.returncode != 0:
        # an empty untracked set from a failed query would silently drop new
        # files from the lint set — the same false-green class as above
        raise ValueError(f"git ls-files failed: "
                         f"{untracked.stderr.strip() or untracked.stdout.strip()}")
    abs_root = os.path.abspath(root)
    scan_roots = tuple(os.path.join(abs_root, d) + os.sep
                       for d in DEFAULT_SCAN_DIRS
                       if os.path.isdir(os.path.join(abs_root, d)))
    if not scan_roots:  # no package layout under root: everything under root
        scan_roots = (abs_root + os.sep, )
    names = [os.path.join(toplevel, n) for n in diff.stdout.splitlines()]
    # ls-files paths are cwd-relative (= root, the subprocess cwd)
    names += [os.path.join(root, n) for n in untracked.stdout.splitlines()]
    out: Set[str] = set()
    for path in names:
        if not path.endswith(".py"):
            continue
        path = os.path.abspath(path)
        if path.startswith(scan_roots) and os.path.isfile(path):
            out.add(path)
    return sorted(out)


def _relpath(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        rel = path
    return rel.replace(os.sep, "/")


def load_modules(files: Sequence[str], root: str):
    modules: List[ModuleInfo] = []
    errors: List[Finding] = []
    for path in files:
        rel = _relpath(path, root)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            line = getattr(exc, "lineno", 1) or 1
            errors.append(Finding(rule="parse-error", path=rel, line=line, col=0,
                                  message=f"cannot check file: {exc}"))
            continue
        modules.append(ModuleInfo(path=path, relpath=rel, source=source, tree=tree,
                                  lines=source.splitlines()))
    return modules, errors


def _lint_one_module(mod: ModuleInfo, rules: List[Rule], ctx: ProjectContext,
                     report_unused_suppressions: bool):
    """Per-module pass: run rules, apply suppressions.  Returns
    (kept findings, suppressed count) — the unit of work ``--jobs`` fans out."""
    mod_rules = rules if not mod.relpath.startswith(TEST_PATH_PREFIX) \
        else [r for r in rules if r.scan_tests]
    raw: List[Finding] = []
    for rule in mod_rules:
        raw.extend(rule.check(mod, ctx))
    suppressions, problems = parse_suppressions(mod.source, mod.relpath)
    index = SuppressionIndex(suppressions)
    kept = [f for f in raw if not index.suppresses(f)]
    suppressed = len(raw) - len(kept)
    kept.extend(problems)
    if report_unused_suppressions:
        for s in index.unused({r.name for r in mod_rules}):
            kept.append(Finding(
                rule="unused-suppression", path=mod.relpath, line=s.line, col=s.col,
                message=f"suppression of {', '.join(s.rules)} matched no finding — "
                        f"stale; remove it (reason was: {s.reason})",
                snippet=mod.snippet(s.line), severity="warning"))
    return kept, suppressed


# parent-side state inherited by forked --jobs workers (copy-on-write): the
# context is built ONCE in the parent; children only receive module indices
_FORK_STATE = None


def _fork_worker(indices: List[int]):
    modules, rules, ctx, report_unused = _FORK_STATE
    findings: List[Finding] = []
    suppressed = 0
    for i in indices:
        kept, sup = _lint_one_module(modules[i], rules, ctx, report_unused)
        findings.extend(kept)
        suppressed += sup
    return findings, suppressed


def _lint_parallel(modules: List[ModuleInfo], rules: List[Rule],
                   ctx: ProjectContext, report_unused: bool, jobs: int):
    """Fork-based fan-out over modules.  Returns (findings, suppressed), or
    None when fork is unavailable (caller falls back to sequential).  Fork is
    required — spawn would re-pickle every parse tree and rebuild nothing."""
    import multiprocessing as mp
    global _FORK_STATE
    try:
        mpctx = mp.get_context("fork")
    except ValueError:
        return None
    jobs = max(1, min(jobs, len(modules)))
    if jobs == 1:
        return None
    # round-robin keeps big files (which cluster at similar paths) spread out
    chunks = [list(range(i, len(modules), jobs)) for i in range(jobs)]
    _FORK_STATE = (modules, rules, ctx, report_unused)
    try:
        with mpctx.Pool(jobs) as pool:
            results = pool.map(_fork_worker, chunks)
    finally:
        _FORK_STATE = None
    findings = [f for part, _ in results for f in part]
    suppressed = sum(sup for _, sup in results)
    return findings, suppressed


def lint_modules(modules: List[ModuleInfo], rules: Optional[List[Rule]] = None,
                 extra_declared_keys: Iterable[str] = (),
                 report_unused_suppressions: bool = True,
                 context_modules: Optional[List[ModuleInfo]] = None,
                 api_surface=None, mesh_manifest=None, jobs: int = 1,
                 _stats: Optional[Dict[str, int]] = None) -> List[Finding]:
    """Findings come only from ``modules``; ``context_modules`` (a superset,
    default = modules) feeds ProjectContext so a subset lint still sees the
    whole package's schemas/registries.  ``jobs > 1`` forks that many workers
    over the per-module pass (the context build stays single-pass in the
    parent); results are identical to sequential by construction — each
    module is linted exactly once against the same shared context."""
    rules = rules if rules is not None else build_rules()
    ctx = ProjectContext(context_modules or modules,
                         extra_declared_keys=extra_declared_keys,
                         api_surface=api_surface, mesh_manifest=mesh_manifest)
    findings: List[Finding] = []
    suppressed = 0
    parallel = None
    if jobs != 1 and len(modules) > 1:
        parallel = _lint_parallel(modules, rules, ctx,
                                  report_unused_suppressions, jobs)
    if parallel is not None:
        findings, suppressed = parallel
    else:
        for mod in modules:
            kept, sup = _lint_one_module(mod, rules, ctx,
                                         report_unused_suppressions)
            findings.extend(kept)
            suppressed += sup
    if _stats is not None:
        _stats["suppressed"] = suppressed
    return sorted(findings, key=Finding.sort_key)


def run_lint(paths: Sequence[str], root: Optional[str] = None,
             rules: Optional[List[Rule]] = None,
             baseline: Optional[Dict[str, int]] = None,
             report_unused_suppressions: bool = True,
             api_surface=_UNSET, mesh_manifest=_UNSET,
             jobs: int = 1) -> LintResult:
    t0 = time.perf_counter()
    root = root or os.getcwd()
    files = iter_python_files(paths)
    modules, errors = load_modules(files, root)
    rules = rules if rules is not None else build_rules()
    if api_surface is _UNSET:
        # default: the committed manifest at the repo root (None = never
        # generated, which jax-api-surface reports as its own finding)
        api_surface = load_api_surface(os.path.join(root, DEFAULT_MANIFEST_NAME))
    if mesh_manifest is _UNSET:
        # same contract for the mesh manifest (unknown-mesh-axis owns it)
        mesh_manifest = load_mesh_manifest(
            os.path.join(root, DEFAULT_MESH_MANIFEST_NAME))
    # linting a SUBSET still needs whole-package context (ConfigModel schemas,
    # the DECLARED_EXTRA_KEYS registry) or declared-key checks mass-misfire
    context_modules = modules
    pkg_root = os.path.join(root, "deepspeed_tpu")
    if os.path.isdir(pkg_root):
        # compare normalized: a linted file given as a RELATIVE path must not
        # re-enter as a context duplicate — the duplicate's parse tree would
        # shadow the linted module's per-relpath facts (mesh model, jit roots),
        # and any id()-keyed node lookup on them silently stops matching
        have = {os.path.abspath(m.path) for m in modules}
        extra_files = [f for f in iter_python_files([pkg_root])
                       if os.path.abspath(f) not in have]
        if extra_files:
            extra_modules, _ = load_modules(extra_files, root)
            context_modules = modules + extra_modules
    stats: Dict[str, int] = {}
    all_findings = errors + lint_modules(
        modules, rules, report_unused_suppressions=report_unused_suppressions,
        context_modules=context_modules, api_surface=api_surface,
        mesh_manifest=mesh_manifest, jobs=jobs, _stats=stats)
    active, baselined = apply_baseline(all_findings, baseline or {})
    checked = sorted({m.relpath for m in modules} | {e.path for e in errors})
    return LintResult(findings=active, baselined=baselined,
                      suppressed_count=stats.get("suppressed", 0),
                      files_checked=len(files),
                      rules_run=[r.name for r in rules],
                      seconds=time.perf_counter() - t0,
                      checked_paths=checked)


def lint_source(source: str, filename: str = "snippet.py",
                rule_names: Optional[Sequence[str]] = None,
                extra_declared_keys: Iterable[str] = (),
                report_unused_suppressions: bool = False,
                context_sources: Optional[Dict[str, str]] = None,
                api_surface=None, mesh_manifest=None) -> List[Finding]:
    """Test/fixture helper: lint one source string in isolation.

    ``context_sources`` ({filename: source}) joins the ProjectContext without
    being linted — e.g. a fake ``deepspeed_tpu/compat/__init__.py`` carrying a
    SHIMMED_SYMBOLS registry for direct-shimmed-import fixtures, or a fake
    ``deepspeed_tpu/parallel/mesh.py`` declaring axis constants for the mesh
    rules.  ``api_surface`` / ``mesh_manifest`` are the pinned sets for the
    two manifest rules (None = manifest never generated)."""
    try:
        tree = ast.parse(source, filename=filename)
    except SyntaxError as exc:
        return [Finding(rule="parse-error", path=filename, line=exc.lineno or 1,
                        col=0, message=str(exc))]
    mod = ModuleInfo(path=filename, relpath=filename, source=source, tree=tree,
                     lines=source.splitlines())
    context = [mod]
    for ctx_name, ctx_src in (context_sources or {}).items():
        context.append(ModuleInfo(path=ctx_name, relpath=ctx_name, source=ctx_src,
                                  tree=ast.parse(ctx_src, filename=ctx_name),
                                  lines=ctx_src.splitlines()))
    rules = build_rules(rule_names) if rule_names is not None else build_rules()
    return lint_modules([mod], rules, extra_declared_keys=extra_declared_keys,
                        report_unused_suppressions=report_unused_suppressions,
                        context_modules=context, api_surface=api_surface,
                        mesh_manifest=mesh_manifest)
