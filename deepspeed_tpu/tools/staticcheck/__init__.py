"""dslint: JAX/TPU-aware static analysis purpose-built for this codebase.

Entry points:

- CLI: ``bin/dstpu-lint`` / ``python -m deepspeed_tpu.tools.staticcheck.cli``
- ``make lint`` and the ``lint`` lane in ``run_tests.py`` (CI gate: non-zero
  exit on any non-baselined finding)
- library: ``run_lint(paths)`` / ``lint_source(src)`` for tests and tooling

See rules.py for the rule catalog, suppressions.py for the inline
``# dslint: disable=<rule>  # reason`` grammar, and baseline.py for the
grandfathering policy.
"""

from .baseline import DEFAULT_BASELINE_NAME, load_baseline, save_baseline
from .findings import Finding
from .mesh_model import (DEFAULT_MESH_MANIFEST_NAME, MeshModel,
                         collect_mesh_axes, load_mesh_manifest,
                         save_mesh_manifest)
from .rules import META_RULES, RULES, build_rules
from .runner import LintResult, lint_source, run_lint
from .thread_model import ThreadModel

__all__ = ["DEFAULT_BASELINE_NAME", "DEFAULT_MESH_MANIFEST_NAME", "Finding",
           "LintResult", "META_RULES", "MeshModel", "RULES", "ThreadModel",
           "build_rules", "collect_mesh_axes", "lint_source", "load_baseline",
           "load_mesh_manifest", "run_lint", "save_baseline",
           "save_mesh_manifest"]
