"""Finding record + fingerprinting.

A finding's identity for baseline purposes is (rule, path, source-line TEXT) —
not the line NUMBER — so unrelated edits above a grandfathered finding don't
invalidate the baseline, while editing the flagged line itself (presumably to
fix it) retires the entry.
"""

import dataclasses
import hashlib
from typing import Any, Dict

SEVERITIES = ("error", "warning")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str = ""  # the stripped source line the finding anchors to
    severity: str = "error"
    # last line of the enclosing statement (0 = same as `line`): a same-line
    # suppression comment anywhere in a multi-line statement covers the finding
    end_line: int = 0

    @property
    def fingerprint(self) -> str:
        digest = hashlib.sha1(
            f"{self.rule}::{self.path}::{self.snippet}".encode("utf-8", "replace")).hexdigest()
        return digest[:16]

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint
        return d

    def format_text(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        out = f"{loc}: {self.severity}[{self.rule}] {self.message}"
        if self.snippet:
            out += f"\n    {self.snippet}"
        return out
