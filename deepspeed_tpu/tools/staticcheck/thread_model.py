"""Cross-module thread model for the concurrency rules (threadcheck).

The serving stack has a real thread plane — the ``ThreadingHTTPServer`` ops
endpoints, the async checkpoint writer, the daemon collective-timeout worker
in ``comm/comm.py`` — whose safety rests on hand-enforced conventions (the
``OpsCache`` "GIL-atomic whole-string assignment" contract, the "handlers
never touch the engine" scrape rule).  This module gives the rules a static
model of that plane:

- **thread roots** — every function another thread can enter:
  ``threading.Thread(target=...)`` / ``Timer`` targets, ``Executor.submit``
  callables, methods of HTTP handler classes (``BaseHTTPRequestHandler``
  subclasses — the stdlib server spawns a thread per request), callbacks
  handed to ``register_collector``, and ``signal.signal`` handlers (their own
  plane: signals are main-thread *reentrancy*, not parallelism, so they feed
  only the handler-holds-engine rule, never the data-race rules);
- **reachability** — which functions each root can reach through a
  conservative name-based call graph (``self.m()`` through the class/base
  table, bare names through lexical scoping, ``obj.m()`` through light type
  inference from constructor assignments / annotations / parameter
  annotations);
- **attribute access events** — every read / whole-attribute rebind /
  augmented assignment / in-place container mutation of ``self.x`` (or a
  typed object's ``x``), keyed ``(ClassName, attr)``, each stamped with the
  set of locks held at that point (``with`` statements over
  ``threading.Lock``-typed attributes / module constants);
- **lock-order edges** — nested acquisitions, aggregated project-wide.

Everything is pure AST (the analyzer keeps working when the library is broken
at import time) and conservative: what cannot be resolved statically is
dropped, never guessed — the rules only fire on facts the model proved.
"""

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .context import (ModuleInfo, annotate_parents, enclosing, param_names,
                      parent, terminal_name as _terminal_name)

FuncKey = Tuple[str, str]  # (relpath, qualname)

# thread-creation callables whose target is a thread entrypoint
THREAD_CTOR_NAMES = {"Thread", "Timer"}
# Executor.submit(fn, ...) — the pool runs fn on a worker thread
SUBMIT_METHOD = "submit"
# stdlib socketserver/http.server handler bases: the threading server mixes
# in one thread per request, so EVERY method of a subclass is thread-entered
HANDLER_BASE_NAMES = {"BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
                      "CGIHTTPRequestHandler", "BaseRequestHandler",
                      "StreamRequestHandler", "DatagramRequestHandler"}
# MetricsRegistry.register_collector(fn): "collectors run on the OWNING
# thread" is the documented contract — registration makes fn thread-visible
COLLECTOR_REGISTER_NAME = "register_collector"

# lock-object constructors (threading module) — an attribute/constant
# assigned from one of these is a lock for span tracking and lock identity
LOCK_CTOR_NAMES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
# attribute types whose cross-thread use is sanctioned (internally
# synchronized, or a synchronization primitive itself) — exempt from the
# data-race rules
THREADSAFE_TYPE_NAMES = LOCK_CTOR_NAMES | {
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "deque",
    "Event", "Barrier", "local", "ThreadPoolExecutor"}

# in-place mutation methods on containers — a publish must be a whole-
# attribute rebind, never one of these on a shared object
MUTATING_METHODS = {"append", "appendleft", "extend", "insert", "remove",
                    "pop", "popleft", "clear", "update", "setdefault",
                    "add", "discard", "sort", "reverse", "popitem",
                    "__setitem__"}

# provably-mutable constructor spellings for rebind values
MUTABLE_CTOR_NAMES = {"dict", "list", "set", "bytearray", "defaultdict",
                      "OrderedDict", "Counter"}

# "engine/manager" identification for handler-holds-engine: a class is
# engine-like when it defines a train/serve hot-path method, or pairs an
# Engine-ish name with step(), or a Manager/Supervisor/Router name with a
# serving verb — mirrors the host-sync rule's hot-path vocabulary
ENGINE_HOT_METHODS = {"train_batch", "eval_batch", "decode_burst",
                      "train_step"}
ENGINE_NAME_FRAGMENT = "Engine"
MANAGER_NAME_SUFFIXES = ("Manager", "Supervisor", "Router")
MANAGER_VERBS = {"serve", "step", "put"}

ROOT_KINDS = ("thread", "handler", "collector", "signal")


def _annotation_type(node: Optional[ast.AST]) -> Optional[str]:
    """Terminal class name of an annotation (``OpsCache``, ``x.OpsCache``,
    ``"OpsCache"``); parameterized/complex annotations resolve to None."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.split(".")[-1].strip() or None
    return _terminal_name(node)


def _ctor_type(value: ast.AST) -> Optional[str]:
    """Class name when ``value`` is a plain ``T(...)`` construction."""
    if isinstance(value, ast.Call):
        return _terminal_name(value.func)
    return None


@dataclasses.dataclass
class ClassFacts:
    name: str
    relpath: str
    node: ast.ClassDef
    bases: Tuple[str, ...]
    methods: Dict[str, FuncKey]
    attr_types: Dict[str, str]  # attr -> terminal class name, when inferred


@dataclasses.dataclass
class FunctionFacts:
    key: FuncKey
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    relpath: str
    cls: Optional[str]  # lexically-enclosing class name, if any
    name: str
    callees: List[Tuple] = dataclasses.field(default_factory=list)
    resolved_callees: Set[FuncKey] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class AttrEvent:
    owner: str  # class name owning the attribute
    attr: str
    kind: str  # read | rebind | augassign | mutcall | substore | delete
    func: FuncKey
    relpath: str
    node: ast.AST
    locks: FrozenSet[str]
    in_init: bool  # inside the owner's own __init__ (pre-publication)
    value: Optional[ast.AST] = None  # assigned expression, for rebinds

WRITE_KINDS = {"rebind", "augassign", "substore", "delete"}
INPLACE_KINDS = {"mutcall", "substore", "delete"}


@dataclasses.dataclass
class ThreadRoot:
    key: Optional[FuncKey]  # None when the target could not be resolved
    kind: str  # one of ROOT_KINDS
    relpath: str
    node: ast.AST  # the site (Thread call / handler classdef / register call)
    label: str


@dataclasses.dataclass
class BlockingCall:
    func: FuncKey
    relpath: str
    node: ast.AST
    what: str  # e.g. "time.sleep", "Thread.join", "subprocess.run"
    locks: FrozenSet[str]


@dataclasses.dataclass
class LockEdge:
    outer: str
    inner: str
    func: FuncKey
    relpath: str
    node: ast.AST  # the INNER acquisition site


class ThreadModel:
    """Project-wide thread-plane facts shared by the concurrency rules."""

    def __init__(self, modules: List[ModuleInfo]):
        self.classes: Dict[str, ClassFacts] = {}
        self.functions: Dict[FuncKey, FunctionFacts] = {}
        self.roots: List[ThreadRoot] = []
        self.attr_events: Dict[Tuple[str, str], List[AttrEvent]] = {}
        self.blocking_calls: List[BlockingCall] = []
        self.lock_edges: List[LockEdge] = []
        self.engine_refs: Dict[FuncKey, List[Tuple[ast.AST, str]]] = {}
        # lock identity -> True for every lock the model recognized
        self.lock_ids: Set[str] = set()
        self._fn_by_id: Dict[int, FunctionFacts] = {}
        self._module_lock_consts: Dict[str, Set[str]] = {}
        # relpath -> {local name: "defining_relpath:NAME"} from-imports,
        # giving imported module-level locks their defining identity
        self._import_aliases: Dict[str, Dict[str, str]] = {}

        for mod in modules:
            # idempotent; ProjectContext annotates too, but the model must
            # also stand alone (tests, tooling)
            annotate_parents(mod.tree)
            self._collect_structure(mod)
        self._finish_attr_types()
        self.engine_classes = {c.name for c in self.classes.values()
                               if self._engine_like(c)}
        for mod in modules:
            self._collect_bodies(mod)
        self._resolve_callees()
        self._collect_roots(modules)
        self.thread_reachable: Set[FuncKey] = self._reach(
            {"thread", "handler", "collector"})
        self.signal_reachable: Set[FuncKey] = self._reach({"signal"})
        self._collect_engine_refs()

    # ------------------------------------------------------------- structure
    def _collect_structure(self, mod: ModuleInfo) -> None:
        lock_consts = self._module_lock_consts.setdefault(mod.relpath, set())
        aliases = self._import_aliases.setdefault(mod.relpath, {})
        for node in mod.tree.body:
            # module-level LOCK = threading.Lock()
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    _ctor_type(node.value) in LOCK_CTOR_NAMES:
                lock_consts.add(node.targets[0].id)
                self.lock_ids.add(f"{mod.relpath}:{node.targets[0].id}")
            # from pkg.mod import LOCK [as L] — same lock identity as the
            # defining module's (cross-module lock-order depends on this)
            elif isinstance(node, ast.ImportFrom) and node.module and \
                    node.level == 0:
                src = node.module.replace(".", "/") + ".py"
                for a in node.names:
                    aliases[a.asname or a.name] = f"{src}:{a.name}"

        def visit(node: ast.AST, qual: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    cname = child.name
                    cqual = f"{qual}{cname}" if not qual else f"{qual}.{cname}"
                    bases = tuple(b for b in
                                  (_terminal_name(x) for x in child.bases)
                                  if b is not None)
                    facts = ClassFacts(name=cname, relpath=mod.relpath,
                                       node=child, bases=bases, methods={},
                                       attr_types={})
                    # first definition wins on a (rare) cross-module name
                    # collision — conservative, and class names here are
                    # project-unique by convention
                    self.classes.setdefault(cname, facts)
                    for stmt in child.body:
                        if isinstance(stmt, ast.AnnAssign) and \
                                isinstance(stmt.target, ast.Name):
                            t = _annotation_type(stmt.annotation)
                            if t is not None:
                                facts.attr_types.setdefault(stmt.target.id, t)
                    visit(child, cqual, cname)
                elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fqual = f"{qual}.{child.name}" if qual else child.name
                    key = (mod.relpath, fqual)
                    facts = FunctionFacts(key=key, node=child,
                                          relpath=mod.relpath, cls=cls,
                                          name=child.name)
                    self.functions[key] = facts
                    self._fn_by_id[id(child)] = facts
                    if cls is not None and cls in self.classes and \
                            self.classes[cls].node is enclosing(
                                child, ast.ClassDef):
                        self.classes[cls].methods.setdefault(child.name, key)
                    visit(child, fqual, cls)
                else:
                    visit(child, qual, cls)

        visit(mod.tree, "", None)

    def _finish_attr_types(self) -> None:
        """``self.a = T(...)`` / ``self.a: T`` inside any method of C types
        C's attribute ``a`` — the seam that lets a handler's annotated local
        (``cache: OpsCache = ...``) join the owning class's attribute table."""
        for fn in self.functions.values():
            if fn.cls is None or fn.cls not in self.classes:
                continue
            facts = self.classes[fn.cls]
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    tgt, val = node.target, node.value
                else:
                    continue
                if isinstance(tgt, ast.Attribute) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "self":
                    t = _ctor_type(val)
                    if t is not None and (t in self.classes or
                                          t in THREADSAFE_TYPE_NAMES):
                        facts.attr_types.setdefault(tgt.attr, t)
                    if t in LOCK_CTOR_NAMES:
                        self.lock_ids.add(f"{fn.cls}.{tgt.attr}")

    def _engine_like(self, c: ClassFacts) -> bool:
        methods = set(c.methods) | {
            m for b in self._base_chain(c.name)
            for m in self.classes[b].methods if b in self.classes}
        if methods & ENGINE_HOT_METHODS:
            return True
        if ENGINE_NAME_FRAGMENT in c.name and "step" in methods:
            return True
        return c.name.endswith(MANAGER_NAME_SUFFIXES) and \
            bool(methods & MANAGER_VERBS)

    def _base_chain(self, cname: str) -> List[str]:
        out, seen, todo = [], set(), [cname]
        while todo:
            cur = todo.pop()
            if cur in seen or cur not in self.classes:
                continue
            seen.add(cur)
            out.append(cur)
            todo.extend(self.classes[cur].bases)
        return out

    def resolve_method(self, cname: str, method: str) -> Optional[FuncKey]:
        for c in self._base_chain(cname):
            key = self.classes[c].methods.get(method)
            if key is not None:
                return key
        return None

    # ---------------------------------------------------------------- bodies
    def _collect_bodies(self, mod: ModuleInfo) -> None:
        for fn in self.functions.values():
            if fn.relpath != mod.relpath:
                continue
            _BodyScanner(self, mod, fn).run()

    def _resolve_callees(self) -> None:
        for fn in self.functions.values():
            for callee in fn.callees:
                key = self._resolve_callee(fn, callee)
                if key is not None:
                    fn.resolved_callees.add(key)

    def _resolve_callee(self, fn: FunctionFacts, callee: Tuple) -> Optional[FuncKey]:
        kind = callee[0]
        if kind == "self" and fn.cls is not None:
            return self.resolve_method(fn.cls, callee[1])
        if kind == "typed":
            return self.resolve_method(callee[1], callee[2])
        if kind == "bare":
            return self._resolve_bare(fn.relpath, fn.node, callee[1])
        return None

    def _resolve_bare(self, relpath: str, from_node: ast.AST,
                      name: str) -> Optional[FuncKey]:
        """Nested def in an enclosing function, else a module-level def in
        the same module.  Imported/aliased callables resolve to None."""
        scope = from_node
        while scope is not None:
            for child in ast.iter_child_nodes(scope):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and child.name == name and id(child) in self._fn_by_id:
                    return self._fn_by_id[id(child)].key
            scope = enclosing(scope, ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Module)
        return None

    # ----------------------------------------------------------------- roots
    def _collect_roots(self, modules: List[ModuleInfo]) -> None:
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self._handler_class_roots(mod, node)
                if not isinstance(node, ast.Call):
                    continue
                t = _terminal_name(node.func)
                target: Optional[ast.AST] = None
                kind = None
                if t in THREAD_CTOR_NAMES:
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target, kind = kw.value, "thread"
                    if target is None and t == "Timer" and len(node.args) >= 2:
                        target, kind = node.args[1], "thread"
                elif t == SUBMIT_METHOD and isinstance(node.func, ast.Attribute) \
                        and node.args:
                    target, kind = node.args[0], "thread"
                elif t == COLLECTOR_REGISTER_NAME and node.args:
                    target, kind = node.args[0], "collector"
                elif t == "signal" and isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "signal" and len(node.args) >= 2:
                    target, kind = node.args[1], "signal"
                if target is None or kind is None:
                    continue
                key = self._resolve_target(mod, node, target)
                self.roots.append(ThreadRoot(
                    key=key, kind=kind, relpath=mod.relpath, node=node,
                    label=f"{ast.unparse(node.func)}(...) at "
                          f"{mod.relpath}:{node.lineno}"))

    def _handler_class_roots(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        chain = self._base_chain(node.name)
        bases = set()
        for c in chain:
            bases |= set(self.classes[c].bases)
        bases |= {b for b in (_terminal_name(x) for x in node.bases) if b}
        if not (bases & HANDLER_BASE_NAMES):
            return
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                facts = self._fn_by_id.get(id(stmt))
                if facts is not None:
                    self.roots.append(ThreadRoot(
                        key=facts.key, kind="handler", relpath=mod.relpath,
                        node=stmt,
                        label=f"HTTP handler {node.name}.{stmt.name} at "
                              f"{mod.relpath}:{stmt.lineno}"))

    def _resolve_target(self, mod: ModuleInfo, site: ast.AST,
                        target: ast.AST) -> Optional[FuncKey]:
        # self._worker  /  self.obj.method
        if isinstance(target, ast.Attribute):
            base = target.value
            if isinstance(base, ast.Name) and base.id == "self":
                fn = self._enclosing_function(site)
                if fn is not None and fn.cls is not None:
                    return self.resolve_method(fn.cls, target.attr)
            owner = self._typed_owner(mod, site, base)
            if owner is not None:
                return self.resolve_method(owner, target.attr)
            return None
        if isinstance(target, ast.Name):
            fn = self._enclosing_function(site)
            from_node = fn.node if fn is not None else mod.tree
            return self._resolve_bare(mod.relpath, from_node, target.id)
        return None  # lambda / call result: unresolved, skipped

    def _enclosing_function(self, node: ast.AST) -> Optional[FunctionFacts]:
        cur = enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef)
        while cur is not None:
            facts = self._fn_by_id.get(id(cur))
            if facts is not None:
                return facts
            cur = enclosing(cur, ast.FunctionDef, ast.AsyncFunctionDef)
        return None

    def _typed_owner(self, mod: ModuleInfo, site: ast.AST,
                     base: ast.AST) -> Optional[str]:
        """Class name of ``base`` when the enclosing scope types it (used by
        root-target resolution; body-level typing lives in _BodyScanner)."""
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and base.value.id == "self":
            fn = self._enclosing_function(site)
            if fn is not None and fn.cls in self.classes:
                return self.classes[fn.cls].attr_types.get(base.attr)
        return None

    # ---------------------------------------------------------- reachability
    def _reach(self, kinds: Set[str]) -> Set[FuncKey]:
        seen: Set[FuncKey] = set()
        todo = [r.key for r in self.roots if r.kind in kinds and r.key]
        while todo:
            key = todo.pop()
            if key in seen or key not in self.functions:
                continue
            seen.add(key)
            todo.extend(self.functions[key].resolved_callees)
        return seen

    def root_for(self, key: FuncKey, kinds: Iterable[str]) -> Optional[ThreadRoot]:
        """A root (of the given kinds) that reaches ``key`` — for messages."""
        for r in self.roots:
            if r.kind not in kinds or r.key is None:
                continue
            seen, todo = set(), [r.key]
            while todo:
                cur = todo.pop()
                if cur == key:
                    return r
                if cur in seen or cur not in self.functions:
                    continue
                seen.add(cur)
                todo.extend(self.functions[cur].resolved_callees)
        return None

    # --------------------------------------------------------- engine lookup
    def _collect_engine_refs(self) -> None:
        for fn in self.functions.values():
            refs: List[Tuple[ast.AST, str]] = []
            own_engine = fn.cls in self.engine_classes
            types = _local_types(self, fn)
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load):
                    if node.id == "self" and own_engine:
                        refs.append((node, fn.cls))
                    elif types.get(node.id) in self.engine_classes:
                        refs.append((node, types[node.id]))
                elif isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self" and fn.cls in self.classes:
                    t = self.classes[fn.cls].attr_types.get(node.attr)
                    if t in self.engine_classes:
                        refs.append((node, t))
            if refs:
                refs.sort(key=lambda r: (r[0].lineno, r[0].col_offset))
                self.engine_refs[fn.key] = refs

    # -------------------------------------------------------------- plumbing
    def add_event(self, ev: AttrEvent) -> None:
        self.attr_events.setdefault((ev.owner, ev.attr), []).append(ev)

    def attr_type(self, owner: str, attr: str) -> Optional[str]:
        c = self.classes.get(owner)
        if c is None:
            return None
        for name in self._base_chain(owner):
            t = self.classes[name].attr_types.get(attr)
            if t is not None:
                return t
        return None

    def is_threadsafe_attr(self, owner: str, attr: str) -> bool:
        t = self.attr_type(owner, attr)
        return t in THREADSAFE_TYPE_NAMES

    def plane_of(self, key: FuncKey) -> str:
        """'thread' | 'signal' | 'main' — signal-only functions are their own
        plane (reentrancy, not parallelism) and never join the race rules."""
        if key in self.thread_reachable:
            return "thread"
        if key in self.signal_reachable:
            return "signal"
        return "main"


def _local_types(model: ThreadModel, fn: FunctionFacts) -> Dict[str, str]:
    """name -> class name for typed locals/params of ``fn`` (constructor
    assignments, annotated assignments, parameter annotations)."""
    types: Dict[str, str] = {}
    args = fn.node.args
    for a in list(getattr(args, "posonlyargs", [])) + list(args.args) + \
            list(args.kwonlyargs):
        t = _annotation_type(a.annotation)
        if t is not None:
            types[a.arg] = t
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            t = _ctor_type(node.value)
            if t is not None and (t in model.classes or
                                  t in THREADSAFE_TYPE_NAMES):
                types.setdefault(node.targets[0].id, t)
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            t = _annotation_type(node.annotation)
            if t is not None:
                types.setdefault(node.target.id, t)
    return types


# blocking-call classification for blocking-under-lock
_SLEEP_MODULES = {"time", "gevent"}
_SUBPROCESS_FNS = {"run", "check_call", "check_output", "call", "Popen"}
_COLLECTIVE_FNS = {"all_reduce", "all_gather", "allreduce", "allgather",
                   "barrier", "broadcast", "reduce_scatter", "psum", "pmean",
                   "bounded_collective"}
_JOINABLE_TYPES = {"Thread", "Timer", "Queue", "JoinableQueue",
                   "ThreadPoolExecutor", "Process"}
_JOINABLE_NAME_HINTS = ("thread", "worker", "proc")


class _BodyScanner:
    """One function body: attribute events, lock spans, blocking calls,
    nested-acquisition edges, and the (unresolved) callee list."""

    def __init__(self, model: ThreadModel, mod: ModuleInfo, fn: FunctionFacts):
        self.model = model
        self.mod = mod
        self.fn = fn
        self.types = _local_types(model, fn)
        self.lock_aliases: Dict[str, str] = {}  # local name -> lock id
        self.held: List[str] = []
        self.in_init = fn.name == "__init__"
        self.nested = {id(n) for n in ast.walk(fn.node)
                       if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                         ast.Lambda)) and n is not fn.node}

    def run(self) -> None:
        for stmt in self.fn.node.body:
            self._stmt(stmt)

    # --------------------------------------------------------------- helpers
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        """Stable identity of a lock expression, else None."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                and self.fn.cls is not None:
            lid = f"{self.fn.cls}.{expr.attr}"
            return lid if lid in self.model.lock_ids else None
        if isinstance(expr, ast.Name):
            alias = self.lock_aliases.get(expr.id)
            if alias is not None:
                return alias
            lid = f"{self.fn.relpath}:{expr.id}"
            if expr.id in self.model._module_lock_consts.get(
                    self.fn.relpath, ()):
                return lid
            imported = self.model._import_aliases.get(
                self.fn.relpath, {}).get(expr.id)
            if imported is not None and imported in self.model.lock_ids:
                return imported
            if self.types.get(expr.id) in LOCK_CTOR_NAMES:
                # function-local lock: identity scoped to this function
                return f"{self.fn.key[0]}:{self.fn.key[1]}:{expr.id}"
        return None

    def _owner_of(self, base: ast.AST) -> Optional[str]:
        """Class owning an attribute access rooted at ``base``."""
        if isinstance(base, ast.Name):
            if base.id == "self":
                return self.fn.cls
            return self.types.get(base.id)
        return None

    # ------------------------------------------------------------ statements
    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested scopes are their own FunctionFacts
        if isinstance(stmt, ast.With) or isinstance(stmt, ast.AsyncWith):
            acquired: List[str] = []
            for item in stmt.items:
                lid = self._lock_id(item.context_expr)
                self._expr(item.context_expr)
                if lid is not None:
                    for outer in self.held + acquired:
                        if outer != lid:
                            self.model.lock_edges.append(LockEdge(
                                outer=outer, inner=lid, func=self.fn.key,
                                relpath=self.fn.relpath,
                                node=item.context_expr))
                    acquired.append(lid)
            self.held.extend(acquired)
            for inner in stmt.body:
                self._stmt(inner)
            for _ in acquired:
                self.held.pop()
            return
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value)
            # single-level lock aliasing: lk = self._lock / lk = _LOCK
            if len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
                lid = self._lock_id(stmt.value)
                if lid is not None:
                    self.lock_aliases[stmt.targets[0].id] = lid
            for tgt in stmt.targets:
                self._target(tgt, value=stmt.value)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value)
            owner = None
            if isinstance(stmt.target, ast.Attribute):
                owner = self._owner_of(stmt.target.value)
                if owner is not None:
                    self._event(owner, stmt.target.attr, "augassign",
                                stmt.target)
            if owner is None and isinstance(stmt.target, ast.Subscript):
                self._target(stmt.target, value=stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value)
            self._target(stmt.target, value=stmt.value)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Attribute):
                    owner = self._owner_of(tgt.value)
                    if owner is not None:
                        self._event(owner, tgt.attr, "delete", tgt)
                elif isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Attribute):
                    owner = self._owner_of(tgt.value.value)
                    if owner is not None:
                        self._event(owner, tgt.value.attr, "delete", tgt)
            return
        # generic statement (if/for/while/try/expr/return/...): child
        # statements recurse (keeping the held-lock stack correct through
        # compound bodies), child expressions are scanned for events
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._expr(child)
            elif isinstance(child, ast.ExceptHandler):
                for inner in child.body:
                    self._stmt(inner)

    def _target(self, tgt: ast.AST, value: Optional[ast.AST]) -> None:
        if isinstance(tgt, ast.Attribute):
            owner = self._owner_of(tgt.value)
            if owner is not None:
                self._event(owner, tgt.attr, "rebind", tgt, value=value)
        elif isinstance(tgt, ast.Subscript):
            if isinstance(tgt.value, ast.Attribute):
                owner = self._owner_of(tgt.value.value)
                if owner is not None:
                    self._event(owner, tgt.value.attr, "substore", tgt)
            self._expr(tgt.slice)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target(el, value=None)

    # ----------------------------------------------------------- expressions
    def _expr(self, expr: ast.AST) -> None:
        for node in self._walk_own(expr):
            if isinstance(node, ast.Call):
                self._call(node)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                owner = self._owner_of(node.value)
                if owner is None:
                    continue
                up = parent(node)
                if isinstance(up, ast.Attribute) or (
                        isinstance(up, ast.Call) and up.func is node):
                    continue  # handled at the call / outer attribute
                self._event(owner, node.attr, "read", node)

    def _walk_own(self, root: ast.AST):
        stack = [root]
        while stack:
            node = stack.pop()
            if id(node) in self.nested and node is not root:
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _call(self, call: ast.Call) -> None:
        f = call.func
        t = _terminal_name(f)
        # ---- callee recording
        if isinstance(f, ast.Name):
            self.fn.callees.append(("bare", f.id))
        elif isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self":
                self.fn.callees.append(("self", f.attr))
            else:
                owner = self._owner_of(base)
                if owner is not None:
                    self.fn.callees.append(("typed", owner, f.attr))
                if isinstance(base, ast.Attribute):
                    # self.obj.method(...): typed through the attr table
                    aowner = self._owner_of(base.value)
                    if aowner is not None:
                        atype = self.model.attr_type(aowner, base.attr)
                        if atype is not None:
                            self.fn.callees.append(("typed", atype, f.attr))
        # ---- attribute events through calls: self.attr.mutate(...)
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Attribute):
            owner = self._owner_of(f.value.value)
            if owner is not None:
                kind = "mutcall" if t in MUTATING_METHODS else "read"
                self._event(owner, f.value.attr, kind, f.value)
        # ---- blocking calls while a lock is held
        if self.held:
            what = self._blocking_kind(call, t)
            if what is not None:
                self.model.blocking_calls.append(BlockingCall(
                    func=self.fn.key, relpath=self.fn.relpath, node=call,
                    what=what, locks=frozenset(self.held)))

    def _blocking_kind(self, call: ast.Call, t: Optional[str]) -> Optional[str]:
        f = call.func
        if t == "sleep":
            if isinstance(f, ast.Name):
                return "sleep"
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in _SLEEP_MODULES:
                return f"{f.value.id}.sleep"
            return None
        if t in _SUBPROCESS_FNS:
            if t == "Popen" and isinstance(f, ast.Name):
                return "Popen"
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "subprocess":
                return f"subprocess.{t}"
            return None
        if t == "fsync":
            return "os.fsync"
        if t in _COLLECTIVE_FNS:
            return f"collective entry {t}()"
        if t in ("block_until_ready", "device_get"):
            return f"device sync {t}()"
        if t == "join" and isinstance(f, ast.Attribute):
            recv = f.value
            rtype = None
            if isinstance(recv, ast.Name):
                rtype = self.types.get(recv.id)
            elif isinstance(recv, ast.Attribute):
                owner = self._owner_of(recv.value)
                if owner is not None:
                    rtype = self.model.attr_type(owner, recv.attr)
            if rtype in _JOINABLE_TYPES:
                return f"{rtype}.join"
            text = ast.unparse(recv).lower()
            if rtype is None and any(h in text for h in _JOINABLE_NAME_HINTS):
                return "join"
        return None

    def _event(self, owner: str, attr: str, kind: str, node: ast.AST,
               value: Optional[ast.AST] = None) -> None:
        self.model.add_event(AttrEvent(
            owner=owner, attr=attr, kind=kind, func=self.fn.key,
            relpath=self.fn.relpath, node=node,
            locks=frozenset(self.held),
            in_init=self.in_init and self.fn.cls == owner, value=value))


def is_mutable_value(expr: Optional[ast.AST]) -> bool:
    """Provably-mutable rebind values: container literals/comprehensions and
    bare mutable-constructor calls.  Names/attributes/call results are NOT
    provably mutable — the model never guesses."""
    if expr is None:
        return False
    if isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.SetComp, ast.DictComp)):
        return True
    if isinstance(expr, ast.Call):
        return _terminal_name(expr.func) in MUTABLE_CTOR_NAMES
    return False
