"""Cross-module analysis context.

One pass over every module under lint builds the project-wide facts the rules
need:

- which function defs are **jit roots** (passed to ``jax.jit`` by name,
  decorated with it, or wrapped in ``functools.partial`` inside the jit call),
  plus their static argument names (``static_argnums``/``static_argnames``);
- where jitted callables **donate buffers** (``donate_argnums``) and how the
  resulting callable is bound (local name, attribute, container, returned);
- the set of **declared config keys**: every field name of every
  ``ConfigModel`` subclass anywhere in the tree, every ``deprecated_names``
  alias, every module-level ``<NAME> = "literal"`` key constant in
  ``runtime/config.py``, and the ``DECLARED_EXTRA_KEYS`` registry (reference
  spellings read out of deliberately-unmodeled ``Dict[str, Any]`` sections).
"""

import ast
import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

PARENT_FIELD = "_dslint_parent"


def annotate_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            setattr(child, PARENT_FIELD, node)


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, PARENT_FIELD, None)


def enclosing(node: ast.AST, *types) -> Optional[ast.AST]:
    cur = parent(node)
    while cur is not None and not isinstance(cur, types):
        cur = parent(cur)
    return cur


def enclosing_statement(node: ast.AST) -> ast.stmt:
    cur = node
    while not isinstance(cur, ast.stmt):
        nxt = parent(cur)
        if nxt is None:
            break
        cur = nxt
    return cur


@dataclasses.dataclass
class ModuleInfo:
    path: str  # absolute
    relpath: str  # repo-relative, forward slashes
    source: str
    tree: ast.Module
    lines: List[str]

    def snippet(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def terminal_name(node: ast.AST) -> Optional[str]:
    """The rightmost name of a ``Name``/``Attribute`` expression (``jnp.zeros``
    -> "zeros") — the shared call-identification helper of rules/mesh model."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _is_jax_jit(func: ast.AST) -> bool:
    """Matches ``jax.jit`` / bare ``jit`` (imported from jax)."""
    if isinstance(func, ast.Attribute) and func.attr == "jit" and \
            isinstance(func.value, ast.Name) and func.value.id == "jax":
        return True
    return isinstance(func, ast.Name) and func.id == "jit"


def _is_partial(func: ast.AST) -> bool:
    if isinstance(func, ast.Attribute) and func.attr == "partial":
        return True
    return isinstance(func, ast.Name) and func.id == "partial"


def _int_tuple(node: ast.AST) -> Tuple[int, ...]:
    """Literal ints from ``donate_argnums=(0, 1)`` / ``static_argnums=2``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value, )
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    return ()


def _str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value, )
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return tuple(el.value for el in node.elts
                     if isinstance(el, ast.Constant) and isinstance(el.value, str))
    return ()


def param_names(fn: ast.AST) -> List[str]:
    args = fn.args
    names = [a.arg for a in getattr(args, "posonlyargs", []) + args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return names


@dataclasses.dataclass
class JitRoot:
    fn: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    static_names: Set[str]
    jit_call: Optional[ast.Call]  # None for decorator form


@dataclasses.dataclass
class DonationSite:
    jit_call: ast.Call
    donated: Tuple[int, ...]
    # how the donating callable is bound at the jit site
    binding: str  # "local" | "attribute" | "container" | "returned" | "immediate" | "other"
    name: Optional[str]  # local/attribute name when binding is local/attribute
    fn_node: Optional[ast.AST]  # resolved function def, when available


@dataclasses.dataclass
class StaticJitSite:
    """A ``jax.jit(..., static_argnums/static_argnames=...)`` call and how
    the resulting callable is bound — the recompile-risk rule audits every
    call site's static-position arguments (each distinct value is a fresh
    compiled program)."""
    jit_call: ast.Call
    static_positions: Tuple[int, ...]  # from static_argnums
    static_names: Tuple[str, ...]  # from static_argnames
    # DonationSite.binding vocabulary plus "decorated" (@jax.jit(...) /
    # @partial(jax.jit, ...) on a def — `name` is the decorated function)
    binding: str
    name: Optional[str]
    fn_node: Optional[ast.AST]


def _binding_of(jit_call: ast.Call) -> Tuple[str, Optional[str]]:
    """How the callable produced by ``jit_call`` is bound at the site."""
    up = parent(jit_call)
    if isinstance(up, ast.Call) and up.func is jit_call:
        return "immediate", None
    if isinstance(up, ast.Return):
        return "returned", None
    if isinstance(up, ast.Assign) and len(up.targets) == 1:
        tgt = up.targets[0]
        if isinstance(tgt, ast.Name):
            return "local", tgt.id
        if isinstance(tgt, ast.Attribute):
            return "attribute", tgt.attr
        if isinstance(tgt, ast.Subscript):
            return "container", None
    return "other", None


class _FunctionCollector(ast.NodeVisitor):
    """Map every function name to its def node, per lexical scope chain."""

    def __init__(self):
        self.defs: List[Tuple[ast.AST, ast.AST]] = []  # (scope, fndef)

    def visit_FunctionDef(self, node):
        self.defs.append((enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.ClassDef, ast.Module) or node, node))
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _resolve_function(name_node: ast.Name, tree: ast.Module,
                      defs: List[Tuple[ast.AST, ast.AST]]) -> Optional[ast.AST]:
    """Find the def for ``name_node`` by walking outward through lexical
    scopes.  Good enough for the ``fn = def ...; jax.jit(fn)`` idiom; aliased
    or imported callables resolve to None (and are skipped)."""
    want = name_node.id
    scope = enclosing(name_node, ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.ClassDef, ast.Module) or tree
    while scope is not None:
        for owner, fndef in defs:
            if fndef.name == want and owner is scope:
                return fndef
        scope = enclosing(scope, ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Module)
    return None


def _jit_target(call: ast.Call, tree: ast.Module,
                defs: List[Tuple[ast.AST, ast.AST]]) -> Optional[ast.AST]:
    """The function def a ``jax.jit(...)`` call traces, unwrapping one level
    of ``functools.partial``."""
    if not call.args:
        return None
    target = call.args[0]
    if isinstance(target, ast.Call) and _is_partial(target.func) and target.args:
        target = target.args[0]
    if isinstance(target, ast.Lambda):
        return target
    if isinstance(target, ast.Name):
        return _resolve_function(target, tree, defs)
    return None


def collect_jit_roots(module: ModuleInfo) -> Dict[int, JitRoot]:
    """id(fn_node) -> JitRoot for every function this module jits."""
    tree = module.tree
    collector = _FunctionCollector()
    collector.visit(tree)
    roots: Dict[int, JitRoot] = {}

    def add(fn, static_names, jit_call):
        if fn is None:
            return
        prev = roots.get(id(fn))
        if prev is not None:
            prev.static_names |= static_names
            return
        roots[id(fn)] = JitRoot(fn=fn, static_names=set(static_names), jit_call=jit_call)

    def static_names_of(call: ast.Call, fn) -> Set[str]:
        static: Set[str] = set()
        names = param_names(fn)
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                static |= {names[i] for i in _int_tuple(kw.value) if i < len(names)}
            elif kw.arg == "static_argnames":
                static |= set(_str_tuple(kw.value))
        return static

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jax_jit(node.func):
            fn = _jit_target(node, tree, collector.defs)
            add(fn, static_names_of(node, fn) if fn is not None else set(), node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jax_jit(dec):
                    add(node, set(), None)
                elif isinstance(dec, ast.Call) and (_is_jax_jit(dec.func) or (
                        _is_partial(dec.func) and dec.args and _is_jax_jit(dec.args[0]))):
                    # @jax.jit(...) / @partial(jax.jit, static_argnums=...) —
                    # the static args live on the decorator call itself
                    add(node, static_names_of(dec, node), None)
    return roots


def collect_donation_sites(module: ModuleInfo) -> List[DonationSite]:
    tree = module.tree
    collector = _FunctionCollector()
    collector.visit(tree)
    sites: List[DonationSite] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_jax_jit(node.func)):
            continue
        nums: Tuple[int, ...] = ()
        names: Tuple[str, ...] = ()
        for kw in node.keywords:
            if kw.arg == "donate_argnums":
                nums = _int_tuple(kw.value)
            elif kw.arg == "donate_argnames":
                names = _str_tuple(kw.value)
        if not nums and not names:
            continue
        fn_node = _jit_target(node, tree, collector.defs)
        donated = set(nums)
        if names and fn_node is not None:
            # argnames resolve to positions through the traced fn's signature;
            # an unresolvable target fn leaves only the argnums sites checkable
            params = param_names(fn_node)
            donated |= {params.index(n) for n in names if n in params}
        donated = tuple(sorted(donated))
        if not donated:
            continue
        binding, name = _binding_of(node)
        sites.append(DonationSite(jit_call=node, donated=donated, binding=binding,
                                  name=name, fn_node=fn_node))
    return sites


def _static_kwargs(call: ast.Call) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _int_tuple(kw.value)
        elif kw.arg == "static_argnames":
            names = _str_tuple(kw.value)
    return nums, names


def collect_static_jit_sites(module: ModuleInfo) -> List[StaticJitSite]:
    tree = module.tree
    collector = _FunctionCollector()
    collector.visit(tree)
    # a @jax.jit(...) decorator is also a Call matching the plain branch —
    # without this it would be recorded twice (once as "decorated", once with
    # an opaque binding)
    deco_calls = {id(d)
                  for n in ast.walk(tree)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                  for d in n.decorator_list}
    sites: List[StaticJitSite] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jax_jit(node.func) and \
                id(node) not in deco_calls:
            nums, names = _static_kwargs(node)
            if not nums and not names:
                continue
            binding, name = _binding_of(node)
            sites.append(StaticJitSite(
                jit_call=node, static_positions=tuple(sorted(nums)),
                static_names=names, binding=binding, name=name,
                fn_node=_jit_target(node, tree, collector.defs)))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # @jax.jit(static_argnums=...) / @partial(jax.jit, static_...=...)
            # — the same decorator forms collect_jit_roots models; the
            # decorated NAME is the callable every call site binds
            for dec in node.decorator_list:
                if not (isinstance(dec, ast.Call) and (_is_jax_jit(dec.func) or (
                        _is_partial(dec.func) and dec.args and
                        _is_jax_jit(dec.args[0])))):
                    continue
                nums, names = _static_kwargs(dec)
                if not nums and not names:
                    continue
                sites.append(StaticJitSite(
                    jit_call=dec, static_positions=tuple(sorted(nums)),
                    static_names=names, binding="decorated", name=node.name,
                    fn_node=node))
    return sites


# ---------------------------------------------------------- shimmed symbols
COMPAT_PATH_FRAGMENT = "deepspeed_tpu/compat/"
SHIMMED_REGISTRY = "SHIMMED_SYMBOLS"


def _shimmed_symbols_from_module(tree: ast.Module) -> Dict[str, Tuple[str, ...]]:
    """Parse the ``SHIMMED_SYMBOLS`` registry literal out of a compat module:
    exported name -> ordered "module:attr" candidate spellings.  Read by AST
    (never by import) so the lint rule works even when jax is broken — and can
    never go stale relative to what the shim actually covers."""
    out: Dict[str, Tuple[str, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name) \
                and node.value is not None:
            target, value = node.target.id, node.value
        else:
            continue
        if target != SHIMMED_REGISTRY or not isinstance(value, ast.Dict):
            continue
        for key, val in zip(value.keys, value.values):
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                out[key.value] = _str_tuple(val)
    return out


# --------------------------------------------------------------- config keys
CONFIG_BASE_NAMES = {"ConfigModel"}
EXTRA_KEYS_REGISTRY = "DECLARED_EXTRA_KEYS"


def _config_keys_from_module(tree: ast.Module) -> Set[str]:
    keys: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            base_names = {b.id for b in node.bases if isinstance(b, ast.Name)} | \
                         {b.attr for b in node.bases if isinstance(b, ast.Attribute)}
            if not (base_names & CONFIG_BASE_NAMES):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name):
                    keys.add(stmt.target.id)
                    if isinstance(stmt.value, ast.Call):
                        for kw in stmt.value.keywords:
                            if kw.arg == "deprecated_names":
                                keys |= set(_str_tuple(kw.value))
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            if tname == EXTRA_KEYS_REGISTRY:
                val = node.value
                if isinstance(val, ast.Call) and val.args:  # frozenset({...})
                    val = val.args[0]
                keys |= set(_str_tuple(val))
            elif tname.isupper() and isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                # module-level key constants (TRAIN_BATCH_SIZE = "train_batch_size")
                keys.add(node.value.value)
    return keys


class ProjectContext:
    """Facts shared by every rule over one lint invocation."""

    def __init__(self, modules: List[ModuleInfo], extra_declared_keys=(),
                 api_surface: Optional[Set[str]] = None,
                 mesh_manifest: Optional[Set[str]] = None):
        self.modules = modules
        self.declared_config_keys: Set[str] = set(extra_declared_keys)
        # exported name -> candidate "module:attr" spellings, read from the
        # compat package's SHIMMED_SYMBOLS registry (None of it hardcoded here)
        self.shimmed_symbols: Dict[str, Tuple[str, ...]] = {}
        # pinned external-API symbols from .dslint-api-surface.json; None when
        # the manifest has never been generated
        self.api_surface = api_surface
        # pinned mesh axis names from .dslint-mesh-manifest.json; None when
        # never generated (unknown-mesh-axis reports that as its own finding)
        self.mesh_manifest = mesh_manifest
        self._jit_roots: Dict[str, Dict[int, JitRoot]] = {}
        self._donations: Dict[str, List[DonationSite]] = {}
        self._static_sites: Dict[str, List[StaticJitSite]] = {}
        for mod in modules:
            annotate_parents(mod.tree)
            self.declared_config_keys |= _config_keys_from_module(mod.tree)
            if COMPAT_PATH_FRAGMENT in mod.relpath:
                self.shimmed_symbols.update(_shimmed_symbols_from_module(mod.tree))
            self._jit_roots[mod.relpath] = collect_jit_roots(mod)
            self._donations[mod.relpath] = collect_donation_sites(mod)
            self._static_sites[mod.relpath] = collect_static_jit_sites(mod)
        # deferred import: mesh_model imports ModuleInfo from this module
        from .mesh_model import MeshModel
        self.mesh_model = MeshModel(modules)
        # same pattern for the concurrency layer: the cross-module thread
        # model (thread roots, reachability, attribute/lock facts)
        from .thread_model import ThreadModel
        self.thread_model = ThreadModel(modules)

    def jit_roots(self, module: ModuleInfo) -> Dict[int, JitRoot]:
        return self._jit_roots.get(module.relpath, {})

    def donation_sites(self, module: ModuleInfo) -> List[DonationSite]:
        return self._donations.get(module.relpath, [])

    def static_jit_sites(self, module: ModuleInfo) -> List[StaticJitSite]:
        return self._static_sites.get(module.relpath, [])
