"""dslint rule registry.

Every rule is grounded in a bug class this codebase actually hit (see the
suppression reasons left in-tree for the survivors):

- host-sync-in-hot-path: implicit device→host syncs inside train/eval/serving
  step code (``float()``/``.item()``/``np.asarray()``/``jax.device_get``/
  ``block_until_ready`` on device values) — each one stalls the XLA dispatch
  pipeline for a full round-trip.
- traced-control-flow: Python ``if``/``while`` on a jitted function's traced
  parameters — a TracerBoolConversionError at best, silently-static control
  flow at worst (when a call site happens to bind the value concretely).
- donation-after-use: reading a buffer after passing it to a
  ``jax.jit(..., donate_argnums=...)`` callable — XLA may have reused the
  memory; also flags donating callables that escape module-local analysis
  (returned / stored in containers), where every call site carries an
  unverifiable contract.
- nondeterministic-rng: global ``random``/``np.random`` module state in
  library code (layouts/decisions diverge across ranks and reruns), and jax
  PRNG keys fed to two consumers without an intervening ``split``.
- raw-clock-in-serving: direct ``time.time()``/``time.monotonic()``/
  ``time.perf_counter()`` calls under ``inference/v2/`` — serving code must
  consume the engine's injectable clock seam (``clock=...``, default bound to
  ``time.monotonic`` WITHOUT calling it), or FakeClock-driven fault/deadline/
  tracing tests silently read real wall-time and stop being deterministic.
- silent-except: ``except Exception: pass`` — failures vanish instead of
  being logged once.
- float64-in-compute: explicit float64 dtypes that silently become float32
  under default x64-disabled JAX (and double memory/bandwidth if x64 is on).
- undeclared-config-key: string keys read from config dicts that no
  ``ConfigModel`` schema declares — a typo'd key silently falls back to its
  default instead of erroring.
- unknown-mesh-axis: a ``PartitionSpec``/``in_specs``/``axis_names`` axis
  literal no declared mesh defines — the typo class behind the PR 9 GSPMD
  kv-projection MISCOMPILE (wrong logits, no error); declared axes are
  pinned in a committed manifest (``.dslint-mesh-manifest.json``).
- sharding-dropped-at-boundary: a NamedSharding-placed value flowing into
  ``np.asarray``/``jax.device_get``/``jnp.asarray``-without-device or a
  fresh un-annotated ``device_put`` — the placement silently collapses to a
  single device (the exact gap keeping DeviceBatchState off the multichip
  fast path, engine_v2.py step()).
- spec-rank-mismatch: a PartitionSpec with more dimensions than the array it
  annotates provably has — GSPMD rejects it at runtime on the first
  multichip mesh, long after the single-chip tests went green.
- recompile-risk: request/batch-cardinality expressions (``len(...)``)
  reaching a jit static argument or a padded-shape construction under
  ``inference/v2/`` without passing through the bucketing helpers — each
  distinct value mints a fresh compiled program, breaking the zero-warm-
  recompiles invariant the fastpath smoke only observes after the fact.
- donation-sharding-mismatch: a donated argument rebound to a
  differently-specced placement — donation aliasing needs identical
  shardings, so the "in-place" update silently degrades to a copy.

The concurrency layer (ISSUE 18) reasons over a cross-module thread model
(``thread_model.py``): which functions run on spawned threads / HTTP handler
threads / collector callbacks / signal handlers, which attributes each plane
touches, and which locks are held at each touch:

- cross-thread-mutation: the same attribute written from two planes with no
  common lock — the bug class behind AsyncCheckpointEngine._error, where a
  worker-thread store raced the caller's read-and-clear swap and lost the
  error.
- atomic-publish: a shared attribute updated by augmented assignment,
  in-place container mutation, or a rebind to a freshly-built mutable
  container — readers on the other plane can observe half-applied state;
  the convention is one GIL-atomic pointer store of a complete immutable
  value (the OpsCache pattern).
- handler-holds-engine: an HTTP handler / collector / signal root that
  reaches an engine or manager object — handlers must read pre-rendered
  snapshots, never drive serving machinery from a foreign thread.
- blocking-under-lock: ``sleep``/``join``/``subprocess``/collective calls
  while holding a lock — stalls every thread contending on it (scrapes,
  health probes) for the full blocking duration.
- lock-order: two locks acquired in both A→B and B→A orders across the
  tree — the classic ABBA deadlock, invisible until two threads interleave.
"""

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .api_surface import (DEFAULT_MANIFEST_NAME, PACKAGE_PREFIX,
                          collect_api_surface, symbol_sites)
from .context import (COMPAT_PATH_FRAGMENT, ModuleInfo, ProjectContext, enclosing,
                      enclosing_statement, param_names, parent,
                      terminal_name as _terminal_name)
from .findings import Finding
from .mesh_model import (CREATION_FNS as MESH_CREATION_FNS,
                         DEFAULT_MESH_MANIFEST_NAME, SHARDING_FACTORY_METHODS,
                         UNRESOLVED, creation_rank,
                         is_sharding_factory as _is_sharding_factory,
                         shape_rank)

RULES: Dict[str, type] = {}

# the conventional numpy/jnp import aliases — ONE definition shared by every
# rule that matches module-qualified calls (host-sync, boundary, recompile)
NP_MODULE_NAMES = {"np", "numpy", "onp"}
JNP_MODULE_NAMES = {"jnp"}

# meta findings emitted by the runner itself (documented for --list-rules)
META_RULES = {
    "bad-suppression": "malformed dslint control comment or suppression without a reason",
    "unused-suppression": "suppression comment that matched no finding (stale — remove it)",
    "parse-error": "file failed to parse; nothing else can be checked",
}


def register(cls):
    RULES[cls.name] = cls
    return cls


class Rule:
    name = "rule"
    description = ""
    # most rules encode library contracts (hot-path syncs, config schemas, …)
    # that don't apply to test code; rules that DO police tests/ opt in and
    # the runner scopes the rest to package files
    scan_tests = False

    def check(self, module: ModuleInfo, ctx: ProjectContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, module: ModuleInfo, node: ast.AST, message: str,
                severity: str = "error") -> Finding:
        stmt = enclosing_statement(node)
        end = getattr(stmt, "end_lineno", None) or getattr(node, "end_lineno", 0) or 0
        return Finding(rule=self.name, path=module.relpath, line=node.lineno,
                       col=node.col_offset, message=message,
                       snippet=module.snippet(node.lineno), severity=severity,
                       end_line=end)


def _walk_skipping(root: ast.AST, skip: Set[int]) -> Iterator[ast.AST]:
    """ast.walk that does not descend into nodes whose id is in ``skip``."""
    stack = [root]
    while stack:
        node = stack.pop()
        if id(node) in skip and node is not root:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------------------------
@register
class HostSyncInHotPath(Rule):
    name = "host-sync-in-hot-path"
    description = ("device→host sync (float/.item/np.asarray/jax.device_get/"
                   "block_until_ready) inside per-step train/eval/serving code; "
                   "under inference/v2/ any direct np.asarray/np.array/"
                   "device_get/block_until_ready outside the sanctioned "
                   "fastpath.materialize() deferred-sync helper; in "
                   "runtime/heartbeat.py AND the ops plane (monitor/metrics.py, "
                   "monitor/exposition.py, monitor/ops_server.py) AND the "
                   "KV-pool observability layer (inference/v2/kv_metrics.py) "
                   "AND the serving perf observatory (monitor/perf.py) AND "
                   "the spec-decode layer (inference/v2/spec_decode.py) AND "
                   "the bench regression tooling (tools/benchtrack/) "
                   "any explicit device fetch (np.asarray/np.array/device_get/"
                   "block_until_ready/.item) anywhere in the file — liveness "
                   "stamps, metrics scrapes, pool census hooks, phase/compile/"
                   "roofline instruments and bench diffs are contractually "
                   "zero-device-sync (float() on host config "
                   "values stays legal there; float-of-device-value isn't "
                   "statically separable from it)")

    HOT_NAMES = {"train_batch", "_offload_train_batch", "eval_batch",
                 "decode_burst", "train_step"}
    ENGINE_METHOD_NAMES = {"step"}  # hot only when defined on an *Engine class
    NP_NAMES = NP_MODULE_NAMES
    # the v2 serving package defers every step-result fetch through
    # fastpath.materialize() (counted + auditable); a direct fetch anywhere
    # else in inference/v2/ is an unsanctioned host sync even outside the
    # classic hot-path function names
    V2_PATH_FRAGMENT = "inference/v2/"
    V2_SANCTIONED_FNS = {"materialize"}
    # the heartbeat seam's contract is ZERO device syncs — stamps are called
    # from the train hot loop and must only write values the host already
    # owns, so the WHOLE file is scanned (module level included) with the
    # full sync set, not just the hot-path function names
    HEARTBEAT_PATH_FRAGMENT = "runtime/heartbeat.py"
    # the ops plane inherits the same whole-file contract (ISSUE 11): a
    # scrape handler or registry adapter that fetches a device value turns
    # every Prometheus poll into a hidden device stall — these modules read
    # only host-side cached snapshots, and a fetch sneaking in is a lint
    # error, not a scrape-time surprise
    OPS_PATH_FRAGMENTS = ("monitor/metrics.py", "monitor/exposition.py",
                          "monitor/ops_server.py")
    # the KV-pool observability layer (ISSUE 12) makes the same promise: the
    # census/observatory/forecaster read only host ints the allocator and
    # ragged manager already own, and their hooks run inside the serve loop —
    # a device fetch here would charge every step a hidden sync, so the whole
    # file is scanned with the full explicit-fetch set
    KV_METRICS_PATH_FRAGMENT = "inference/v2/kv_metrics.py"
    # the serving perf observatory (ISSUE 16) runs INSIDE the serve loop
    # (phase marks at every iteration, ledger records at every compile seam):
    # it consumes only the engine's injectable clock and host ints the
    # engine already owns — a device fetch here would charge every serve
    # iteration a hidden sync, so the whole file is scanned
    PERF_PATH_FRAGMENT = "monitor/perf.py"
    # the bench regression tooling (ISSUE 16) must run on accelerator-free
    # CI hosts: it reads committed JSON records only, so ANY device fetch
    # (or jax/numpy dependency sneaking one in) is a contract break — the
    # fragment is a directory, matched anywhere in the relpath
    BENCHTRACK_PATH_FRAGMENT = "tools/benchtrack/"
    # the fleet router (ISSUE 17) holds the same whole-file promise, stricter
    # than the per-function v2 scan that would otherwise apply: routing and
    # failover decisions read health dicts and journal files only — a device
    # fetch in the front-end would stall EVERY request's admission, so the
    # full explicit-fetch set (plus .item()) applies module-wide
    ROUTER_PATH_FRAGMENT = "inference/v2/router.py"
    # speculative decoding (ISSUE 20) holds it too: drafters run on host ints
    # the engine already owns (n-gram) or entirely on device (draft model),
    # and accept/reject accumulation stays on device until the engine's
    # wave-boundary materialize — a fetch here would charge every verify
    # round a hidden stall, so the whole file is scanned
    SPEC_PATH_FRAGMENT = "inference/v2/spec_decode.py"

    def _is_hot(self, fn: ast.AST) -> bool:
        if fn.name in self.HOT_NAMES:
            return True
        if fn.name in self.ENGINE_METHOD_NAMES:
            cls = enclosing(fn, ast.ClassDef)
            return cls is not None and "Engine" in cls.name
        return False

    def check(self, module, ctx):
        jit_roots = ctx.jit_roots(module)
        relpath = module.relpath.replace("\\", "/")
        if relpath.endswith(self.HEARTBEAT_PATH_FRAGMENT):
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in runtime/heartbeat.py — heartbeat stamps are contractually "
                "zero-device-sync (they run in the train hot loop); stamp only "
                "host-native values")
            return
        if any(relpath.endswith(f) for f in self.OPS_PATH_FRAGMENTS):
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in the ops plane (monitor/metrics|exposition|ops_server) — "
                "scrape handlers and registry adapters are contractually "
                "zero-device-sync: they read host-side cached snapshots only, "
                "or every Prometheus poll becomes a hidden device stall")
            return
        if relpath.endswith(self.KV_METRICS_PATH_FRAGMENT):
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in inference/v2/kv_metrics.py — the KV-pool census/"
                "observatory/forecaster are contractually zero-device-sync: "
                "they consume host ints the allocator and ragged manager "
                "already own, and their hooks run inside the serve loop")
            return
        if relpath.endswith(self.PERF_PATH_FRAGMENT):
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in monitor/perf.py — the serving perf observatory (phase "
                "profiler / compile ledger / roofline) is contractually "
                "zero-device-sync: it consumes only the engine's injectable "
                "clock and host floats, and its hooks run inside the serve "
                "loop at every iteration and compile seam")
            return
        if self.BENCHTRACK_PATH_FRAGMENT in relpath:
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in tools/benchtrack/ — bench regression diffs are "
                "contractually zero-device-sync: they run on accelerator-free "
                "CI hosts over committed JSON records, so a device fetch "
                "here breaks the pure-stdlib contract")
            return
        if relpath.endswith(self.ROUTER_PATH_FRAGMENT):
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in inference/v2/router.py — the fleet router is "
                "contractually zero-device-sync: routing, health gating, and "
                "journal-transplant failover read host dicts and journal "
                "files only, or every request's admission stalls on a device "
                "round-trip")
            return
        if relpath.endswith(self.SPEC_PATH_FRAGMENT):
            yield from self._check_zero_sync_file(
                module, jit_roots,
                " in inference/v2/spec_decode.py — drafters and the rejection "
                "sampler are contractually zero-device-sync: accept/reject "
                "accumulation stays on device until the engine's "
                "wave-boundary fastpath.materialize(), or every verify round "
                "charges an extra host stall")
            return
        in_v2 = self.V2_PATH_FRAGMENT in relpath
        seen: Set[int] = set()  # a nested def is also walked via its parent
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if id(node) in jit_roots:
                continue
            hot = self._is_hot(node)
            v2_scan = in_v2 and not hot and node.name not in self.V2_SANCTIONED_FNS
            if not hot and not v2_scan:
                continue
            # nested jitted defs run on device — their bodies can't host-sync
            skip = {id(n) for n in ast.walk(node)
                    if id(n) in jit_roots and n is not node}
            for sub in _walk_skipping(node, skip):
                if not isinstance(sub, ast.Call) or id(sub) in seen:
                    continue
                msg = self._sync_call(sub) if hot else self._v2_sync_call(sub)
                if not msg:
                    continue
                seen.add(id(sub))
                if hot:
                    yield self.finding(module, sub, msg + f" inside hot path '{node.name}' "
                                       "— every occurrence stalls dispatch for a host "
                                       "round-trip; hoist it, batch it into one fetch, or "
                                       "suppress with a reason if this is the step's one "
                                       "deliberate sync")
                else:
                    yield self.finding(module, sub, msg + f" in '{node.name}' under "
                                       "inference/v2/ — serving step results must be "
                                       "fetched through fastpath.materialize() (the "
                                       "counted deferred-sync seam) so syncs stay "
                                       "observable and deferrable; route it through the "
                                       "helper or suppress with a reason if this is "
                                       "host-only data")

    def _check_zero_sync_file(self, module, jit_roots, suffix: str) -> Iterator[Finding]:
        """Whole-file scan with the full explicit-fetch set (heartbeat seam
        and the ops plane): these modules run inside hot loops or behind
        scrape endpoints, so a sync sneaking into ANY helper becomes a silent
        recurring stall — flag it everywhere, module level included."""
        for sub in _walk_skipping(module.tree, set(jit_roots)):
            if not isinstance(sub, ast.Call):
                continue
            # explicit-fetch set + .item(): float() on host config values is
            # legitimate and pervasive here (same reasoning as the v2 scan)
            msg = self._v2_sync_call(sub)
            if msg is None and isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "item":
                msg = ".item() forces a device value to host"
            if msg:
                yield self.finding(module, sub, msg + suffix)

    def _sync_call(self, call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Name) and f.id == "float" and call.args and \
                not isinstance(call.args[0], ast.Constant):
            return "float() forces a device value to host"
        if isinstance(f, ast.Attribute):
            if f.attr == "item":
                return ".item() forces a device value to host"
            if f.attr == "block_until_ready":
                return ".block_until_ready() blocks on device execution"
            if f.attr in ("asarray", "array") and isinstance(f.value, ast.Name) and \
                    f.value.id in self.NP_NAMES:
                return f"np.{f.attr}() copies a device value to host"
            if f.attr == "device_get" and isinstance(f.value, ast.Name) and \
                    f.value.id == "jax":
                return "jax.device_get() copies device values to host"
        return None

    def _v2_sync_call(self, call: ast.Call) -> Optional[str]:
        """The inference/v2-wide subset: explicit array fetches only.
        ``float()``/``.item()`` on host scalars are everywhere in gauge code
        and are not device fetches, so the package-wide scan skips them."""
        f = call.func
        if isinstance(f, ast.Attribute):
            if f.attr == "block_until_ready":
                return ".block_until_ready() blocks on device execution"
            if f.attr in ("asarray", "array") and isinstance(f.value, ast.Name) and \
                    f.value.id in self.NP_NAMES:
                return f"direct np.{f.attr}()"
            if f.attr == "device_get" and isinstance(f.value, ast.Name) and \
                    f.value.id == "jax":
                return "direct jax.device_get()"
        return None


# --------------------------------------------------------------------------
@register
class TracedControlFlow(Rule):
    name = "traced-control-flow"
    description = ("Python if/while on a traced parameter inside a jitted "
                   "function (trace error, or silently-static branching)")

    ALLOWED_CALLS = {"isinstance", "len", "getattr", "hasattr", "type", "callable"}

    def check(self, module, ctx):
        for root in ctx.jit_roots(module).values():
            fn = root.fn
            traced = set(param_names(fn)) - root.static_names
            for child in ast.iter_child_nodes(fn):
                yield from self._check_body(module, child, traced)

    def _check_body(self, module, node, traced: Set[str]):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # nested defs are traced too when called with traced values; their
            # params join the traced set for their own subtree (conservative)
            traced = traced | set(param_names(node))
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            bad = self._raw_traced_use(node.test, traced)
            if bad:
                sub = node
                kind = "while" if isinstance(sub, ast.While) else "if"
                yield self.finding(
                    module, sub,
                    f"Python `{kind}` on traced parameter '{bad}' of a jitted function — "
                    f"use jnp.where/lax.cond/lax.while_loop, mark the argument static "
                    f"(static_argnums / functools.partial before jit), or suppress with "
                    f"a reason documenting why every call site binds it concretely")
        for child in ast.iter_child_nodes(node):
            yield from self._check_body(module, child, traced)

    def _raw_traced_use(self, test: ast.expr, traced: Set[str]) -> Optional[str]:
        for name in ast.walk(test):
            if not (isinstance(name, ast.Name) and name.id in traced):
                continue
            if self._allowed(name, test):
                continue
            return name.id
        return None

    def _allowed(self, name: ast.Name, stop: ast.expr) -> bool:
        cur = parent(name)
        prev: ast.AST = name
        while cur is not None:
            if isinstance(cur, ast.Attribute) and cur.value is prev:
                return True  # x.shape / x.ndim / x.dtype — static under trace
            if isinstance(cur, ast.Call):
                f = cur.func
                if isinstance(f, ast.Name) and f.id in self.ALLOWED_CALLS:
                    return True
            if isinstance(cur, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in cur.ops):
                return True  # `x is None` — identity, not value
            if cur is stop:
                return False
            prev, cur = cur, parent(cur)
        return False


# --------------------------------------------------------------------------
@register
class DonationAfterUse(Rule):
    name = "donation-after-use"
    description = ("buffer read after being passed to a donate_argnums callable; "
                   "also donating callables escaping module-local verification")

    def check(self, module, ctx):
        for site in ctx.donation_sites(module):
            if site.binding == "immediate":
                call = parent(site.jit_call)
                yield from self._check_call(module, call, site.donated)
            elif site.binding == "local":
                fn = enclosing(site.jit_call, ast.FunctionDef, ast.AsyncFunctionDef)
                scope = fn if fn is not None else module.tree
                for call in self._calls_named(scope, site.name, attribute=False):
                    yield from self._check_call(module, call, site.donated)
            elif site.binding == "attribute":
                for call in self._calls_named(module.tree, site.name, attribute=True):
                    yield from self._check_call(module, call, site.donated)
            else:
                how = {"returned": "returned from its factory",
                       "container": "stored into a container"}.get(
                           site.binding, "bound in a way module-local analysis cannot follow")
                yield self.finding(
                    module, site.jit_call,
                    f"donating callable (donate_argnums={site.donated}) is {how} — call "
                    f"sites cannot be verified here; every caller must reassign the "
                    f"donated argument(s) from the result. Suppress with a reason "
                    f"naming the call sites that uphold the contract",
                    severity="warning")

    def _calls_named(self, scope: ast.AST, name: str, attribute: bool) -> Iterator[ast.Call]:
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if attribute and isinstance(f, ast.Attribute) and f.attr == name:
                yield node
            elif not attribute and isinstance(f, ast.Name) and f.id == name:
                yield node

    def _check_call(self, module, call: ast.Call, donated: Tuple[int, ...]):
        fn = enclosing(call, ast.FunctionDef, ast.AsyncFunctionDef)
        if fn is None:
            return
        stmt = enclosing_statement(call)
        end_line = getattr(stmt, "end_lineno", stmt.lineno)
        for idx in donated:
            if idx >= len(call.args):
                continue
            arg = call.args[idx]
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            expr = ast.unparse(arg)
            if self._stored_in(stmt, expr):
                continue  # reassigned from the result in the same statement
            reuse = self._first_load_before_store(fn, expr, after_line=end_line)
            if reuse is not None:
                yield self.finding(
                    module, reuse,
                    f"'{expr}' is read after being DONATED to a jitted callable at "
                    f"line {call.lineno} (donate_argnums includes position {idx}) — "
                    f"XLA may have already reused its buffer; reassign it from the "
                    f"call's result or drop the donation")

    def _stored_in(self, stmt: ast.stmt, expr: str) -> bool:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Store) and \
                    ast.unparse(node) == expr:
                return True
        return False

    def _first_load_before_store(self, fn, expr: str, after_line: int) -> Optional[ast.AST]:
        first_load = first_store = None
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Name, ast.Attribute)):
                continue
            if node.lineno <= after_line or ast.unparse(node) != expr:
                continue
            if isinstance(node.ctx, ast.Store):
                if first_store is None or node.lineno < first_store.lineno:
                    first_store = node
            elif isinstance(node.ctx, ast.Load):
                if first_load is None or node.lineno < first_load.lineno:
                    first_load = node
        if first_load is None:
            return None
        if first_store is not None and first_store.lineno < first_load.lineno:
            return None
        return first_load


# --------------------------------------------------------------------------
@register
class NondeterministicRNG(Rule):
    name = "nondeterministic-rng"
    description = ("global random/np.random module state in library code; "
                   "jax PRNG key fed to two consumers without split")

    GLOBAL_RANDOM_FNS = {"random", "randint", "sample", "choice", "choices",
                         "shuffle", "uniform", "gauss", "seed", "randrange",
                         "getrandbits", "betavariate", "expovariate"}
    NP_RANDOM_FNS = {"seed", "rand", "randn", "randint", "random", "choice",
                     "shuffle", "permutation", "standard_normal", "uniform",
                     "normal", "sample", "random_sample"}
    KEY_CONSUMERS = {"normal", "uniform", "bernoulli", "categorical", "randint",
                     "truncated_normal", "permutation", "choice", "gumbel",
                     "bits", "exponential", "laplace", "poisson", "gamma",
                     "beta", "dirichlet", "rademacher", "ball", "orthogonal"}

    def check(self, module, ctx):
        random_aliases = self._module_aliases(module.tree, "random")
        np_aliases = self._module_aliases(module.tree, "numpy") | \
            {a for a in ("np", ) if a in self._imported_names(module.tree)}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                f = node.func
                if isinstance(f.value, ast.Name) and f.value.id in random_aliases \
                        and f.attr in self.GLOBAL_RANDOM_FNS:
                    yield self.finding(
                        module, node,
                        f"global random.{f.attr}() in library code — layouts/decisions "
                        f"differ across ranks and reruns; use a seeded random.Random "
                        f"(or jax.random with a config-derived key)")
                elif isinstance(f.value, ast.Attribute) and f.value.attr == "random" and \
                        isinstance(f.value.value, ast.Name) and \
                        f.value.value.id in (np_aliases or {"np"}) and \
                        f.attr in self.NP_RANDOM_FNS:
                    yield self.finding(
                        module, node,
                        f"global np.random.{f.attr}() in library code — use "
                        f"np.random.default_rng(seed)")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_key_reuse(module, node)

    def _module_aliases(self, tree, mod_name: str) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == mod_name:
                        out.add(alias.asname or alias.name)
        return out

    def _imported_names(self, tree) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                out |= {a.asname or a.name for a in node.names}
        return out

    def _check_key_reuse(self, module, fn):
        """Linear scan: the same Name passed as the key to two jax.random
        consumers with no intervening reassignment."""
        events: List[Tuple[int, int, str, str, ast.AST]] = []
        nested = {id(n) for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not fn}
        for node in _walk_skipping(fn, nested):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                events.append((node.lineno, node.col_offset, "store", node.id, node))
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                f = node.func
                # jax.random.<dist> specifically — np.random.<fn> takes data,
                # not a PRNG key, and is covered by the global-state check
                if f.attr in self.KEY_CONSUMERS and isinstance(f.value, ast.Attribute) \
                        and f.value.attr == "random" \
                        and isinstance(f.value.value, ast.Name) \
                        and f.value.value.id == "jax" and node.args \
                        and isinstance(node.args[0], ast.Name):
                    events.append((node.lineno, node.col_offset, "consume",
                                   node.args[0].id, node))
        # within one line, consumes order BEFORE stores: in `k = consume(k)` the
        # RHS reads the old key, then the assignment rebinds — sorting by column
        # would process the col-0 Store first, missing a real line-2 reuse and
        # falsely flagging the legitimate post-rebind use
        events.sort(key=lambda e: (e[0], 0 if e[2] == "consume" else 1, e[1]))
        consumed: Dict[str, int] = {}
        for line, _col, kind, name, node in events:
            if kind == "store":
                consumed.pop(name, None)
            elif name in consumed:
                yield self.finding(
                    module, node,
                    f"PRNG key '{name}' already consumed by a jax.random call at line "
                    f"{consumed[name]} and reused here without jax.random.split — the "
                    f"two draws are perfectly correlated")
            else:
                consumed[name] = line


# --------------------------------------------------------------------------
@register
class RawClockInServing(Rule):
    name = "raw-clock-in-serving"
    description = ("direct time.time/monotonic/perf_counter CALL under "
                   "inference/v2/ — serving timestamps must flow through the "
                   "engine's injectable clock seam so FakeClock tests stay "
                   "deterministic (binding time.monotonic as a default is the "
                   "seam and stays legal)")

    V2_PATH_FRAGMENT = "inference/v2/"
    CLOCK_FNS = {"time", "monotonic", "perf_counter",
                 "time_ns", "monotonic_ns", "perf_counter_ns"}

    def check(self, module, ctx):
        if self.V2_PATH_FRAGMENT not in module.relpath.replace("\\", "/"):
            return
        time_aliases: Set[str] = set()
        from_imports: Dict[str, str] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "time":
                        time_aliases.add(alias.asname or alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name in self.CLOCK_FNS:
                        from_imports[alias.asname or alias.name] = alias.name
        if not time_aliases and not from_imports:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            hit = None
            if isinstance(f, ast.Attribute) and f.attr in self.CLOCK_FNS and \
                    isinstance(f.value, ast.Name) and f.value.id in time_aliases:
                hit = f"{f.value.id}.{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in from_imports:
                hit = f"time.{from_imports[f.id]}()"
            if hit is None:
                continue
            yield self.finding(
                module, node,
                f"direct {hit} under inference/v2/ — serving code must take "
                f"timestamps from the engine's injectable clock (the "
                f"``clock=...`` seam; binding time.monotonic as a DEFAULT is "
                f"fine, calling it directly is not), otherwise FakeClock-driven "
                f"deadline/trace tests read real wall-time and lose "
                f"determinism; thread the injected clock through, or suppress "
                f"with a reason if this is genuinely wall-clock-only code")


# --------------------------------------------------------------------------
@register
class SilentExcept(Rule):
    name = "silent-except"
    description = "broad `except: pass` — the failure vanishes without a log line"

    BROAD = {"Exception", "BaseException"}

    def check(self, module, ctx):
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if all(self._is_noop(stmt) for stmt in node.body):
                what = ast.unparse(node.type) if node.type else "bare except"
                yield self.finding(
                    module, node,
                    f"`except {what}` swallows the failure without logging — log once "
                    f"(utils.logging.warning_once) or suppress with a reason why "
                    f"silence is correct here")

    def _is_broad(self, t: Optional[ast.expr]) -> bool:
        if t is None:
            return True
        return _terminal_name(t) in self.BROAD

    def _is_noop(self, stmt: ast.stmt) -> bool:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            return True
        return isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant)


# --------------------------------------------------------------------------
@register
class Float64InCompute(Rule):
    name = "float64-in-compute"
    description = ("explicit float64 dtype — silently downcast to f32 under "
                   "default x64-disabled JAX")

    ATTR_OWNERS = {"np", "numpy", "jnp", "jax"}
    F64_ATTRS = {"float64", "double"}
    F64_STRINGS = {"float64", "f8", "<f8", ">f8"}
    DTYPE_CALLS = {"astype", "asarray", "array", "zeros", "ones", "full", "empty",
                   "arange", "linspace"}

    def check(self, module, ctx):
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute) and node.attr in self.F64_ATTRS and \
                    isinstance(node.value, ast.Name) and node.value.id in self.ATTR_OWNERS:
                yield self.finding(
                    module, node,
                    f"{node.value.id}.{node.attr}: float64 never survives into device "
                    f"compute (JAX default x64-disabled silently downcasts to f32) — "
                    f"use float32, or suppress with a reason if this is host-only data")
            elif isinstance(node, ast.Constant) and isinstance(node.value, str) and \
                    node.value in self.F64_STRINGS and self._dtype_position(node):
                yield self.finding(
                    module, node,
                    f'dtype "{node.value}": float64 never survives into device compute '
                    f"(JAX default x64-disabled silently downcasts to f32) — use "
                    f"float32, or suppress with a reason if this is host-only data")

    def _dtype_position(self, node: ast.Constant) -> bool:
        up = parent(node)
        if isinstance(up, ast.keyword) and up.arg == "dtype":
            return True
        if isinstance(up, ast.Call) and node in up.args:
            name = _terminal_name(up.func)
            return name in self.DTYPE_CALLS
        return False


# --------------------------------------------------------------------------
@register
class UndeclaredConfigKey(Rule):
    name = "undeclared-config-key"
    description = ("string key read from a config dict that no ConfigModel "
                   "schema declares — typos silently fall back to defaults")

    EXACT_NAMES = {"config", "cfg", "ds_config", "user_config", "param_dict",
                   "config_dict"}
    SUFFIXES = ("_config", "_cfg")

    def _is_config_ref(self, node: ast.AST) -> bool:
        name = _terminal_name(node)
        if name is None:
            return False
        return name in self.EXACT_NAMES or name.endswith(self.SUFFIXES)

    def check(self, module, ctx):
        declared = ctx.declared_config_keys
        for node in ast.walk(module.tree):
            key_node = None
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "get" and self._is_config_ref(node.func.value) and \
                    node.args and isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                key_node = node.args[0]
            elif isinstance(node, ast.Subscript) and self._is_config_ref(node.value) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.slice, ast.Constant) and \
                    isinstance(node.slice.value, str):
                # Load only: a WRITE establishes a key (it can't "fall back to
                # a default"), so derived-key assignment stays legal
                key_node = node.slice
            if key_node is None:
                continue
            key = key_node.value
            if key in declared or not key:
                continue
            yield self.finding(
                module, node,
                f"config key '{key}' is not declared by any ConfigModel schema or the "
                f"DECLARED_EXTRA_KEYS registry (runtime/config.py) — a typo here "
                f"silently falls back to the default; declare the key or fix the "
                f"spelling")


# --------------------------------------------------------------------------
@register
class DirectShimmedImport(Rule):
    name = "direct-shimmed-import"
    description = ("import or attribute use of a jax symbol shimmed by "
                   "deepspeed_tpu/compat outside compat/ itself — the banned "
                   "spellings are read from compat's SHIMMED_SYMBOLS registry "
                   "(by AST, never import), so the rule can't go stale; "
                   "scans tests/ too")
    # the one rule that polices test files as well: a drifted test import is a
    # lint error, not a silent collection failure
    scan_tests = True

    def check(self, module, ctx):
        if COMPAT_PATH_FRAGMENT in module.relpath:
            return
        # banned fully-qualified spelling -> (exported name, "module:attr")
        banned: Dict[str, Tuple[str, str]] = {}
        for exported, specs in ctx.shimmed_symbols.items():
            for spec in specs:
                mod_name, _, attr = spec.partition(":")
                banned[f"{mod_name}.{attr}"] = (exported, spec)
        if not banned:
            return
        roots = {spec.partition(":")[0].split(".")[0]
                 for _, spec in banned.values()}
        for symbol, node in symbol_sites(module, roots=roots):
            hit = next((b for b in banned
                        if symbol == b or symbol.startswith(b + ".")), None)
            if hit is None:
                continue
            exported, spec = banned[hit]
            yield self.finding(
                module, node,
                f"direct use of '{hit}' — this symbol is version-shimmed; "
                f"``from deepspeed_tpu.compat import {exported}`` instead "
                f"(SHIMMED_SYMBOLS['{exported}'] lists the spelling "
                f"'{spec}'), so the next upstream rename lands as one edit "
                f"to compat/ instead of red call sites")


# --------------------------------------------------------------------------
@register
class JaxApiSurface(Rule):
    name = "jax-api-surface"
    description = ("external jax.* symbol used by the package but not pinned "
                   "in the committed api-surface manifest "
                   f"({DEFAULT_MANIFEST_NAME}) — after a deliberate surface "
                   "change, regenerate with bin/dstpu-lint "
                   "--update-api-surface; upstream drift then lands as one "
                   "reviewable manifest diff")

    def __init__(self):
        self._missing_reported = False
        self._stale_reported = False

    def check(self, module, ctx):
        if not module.relpath.startswith(PACKAGE_PREFIX):
            return
        if ctx.api_surface is None:
            if not self._missing_reported:
                self._missing_reported = True
                yield Finding(
                    rule=self.name, path=DEFAULT_MANIFEST_NAME, line=1, col=0,
                    message=f"api-surface manifest {DEFAULT_MANIFEST_NAME} does "
                            f"not exist — generate it once with "
                            f"'bin/dstpu-lint --update-api-surface' and commit "
                            f"it; without it the package's external jax surface "
                            f"is unpinned and upstream drift lands as red tests")
            return
        if not self._stale_reported:
            self._stale_reported = True
            # ctx covers the whole package even on subset lints (the runner
            # guarantees it), so staleness is computed against the full tree
            stale = sorted(ctx.api_surface - collect_api_surface(ctx.modules))
            if stale:
                shown = ", ".join(stale[:5]) + ("…" if len(stale) > 5 else "")
                yield Finding(
                    rule=self.name, path=DEFAULT_MANIFEST_NAME, line=1, col=0,
                    message=f"{len(stale)} pinned symbol(s) no longer used by "
                            f"the package ({shown}) — the manifest must stay "
                            f"exact; regenerate with 'bin/dstpu-lint "
                            f"--update-api-surface'",
                    severity="warning")
        for symbol, node in symbol_sites(module):
            if symbol in ctx.api_surface:
                continue
            yield self.finding(
                module, node,
                f"jax symbol '{symbol}' is not pinned in {DEFAULT_MANIFEST_NAME} "
                f"— every external jax touch must be manifest-pinned so version "
                f"drift is a one-file diff; if this use is deliberate, "
                f"regenerate the manifest with 'bin/dstpu-lint "
                f"--update-api-surface' (and review the diff)")


# -------------------------------------------------------- sharding dataflow
# callables that PLACE a value with an explicit sharding; "place" is this
# repo's own pytree placement helper (inference/v2/tp.py)
PLACEMENT_FNS = {"device_put", "make_array_from_callback", "place"}


def _is_sharding_expr(node: ast.AST, sharding_names: Set[str]) -> bool:
    if _is_sharding_factory(node):
        return True
    if isinstance(node, (ast.Name, ast.Attribute)):
        return ast.unparse(node) in sharding_names
    return False


def _placement_value(node: ast.AST, sharding_names: Set[str]) -> bool:
    """True when ``node`` is a call that places its input with an explicit
    sharding: ``jax.device_put(x, <sharding>)``, ``make_array_from_callback``
    with a sharding argument, or the repo's ``place(topology, tree, specs)``."""
    if not isinstance(node, ast.Call):
        return False
    t = _terminal_name(node.func)
    if t == "device_put":
        if len(node.args) >= 2 and _is_sharding_expr(node.args[1], sharding_names):
            return True
        return any(kw.arg in ("device", "sharding") and
                   _is_sharding_expr(kw.value, sharding_names)
                   for kw in node.keywords)
    if t == "make_array_from_callback":
        return any(_is_sharding_expr(a, sharding_names) for a in node.args) or \
            any(_is_sharding_expr(kw.value, sharding_names) for kw in node.keywords)
    # tp.py's place(topology, tree, specs) — the arity keeps unrelated
    # .place() helpers (a grid placement, a scheduler slot) from matching
    return t == "place" and len(node.args) >= 3


def _calls_of_name(scope: ast.AST, name: str, attribute: bool) -> Iterator[ast.Call]:
    """Calls of a local (``fn(...)``) or attribute (``self.fn(...)``) binding."""
    for node in ast.walk(scope):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if attribute and isinstance(f, ast.Attribute) and f.attr == name:
            yield node
        elif not attribute and isinstance(f, ast.Name) and f.id == name:
            yield node


def _scopes(tree: ast.Module) -> Iterator[ast.AST]:
    """The module itself plus every function def (each analyzed with its
    nested defs skipped, so one statement belongs to exactly one scope)."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Walk ``scope`` without descending into nested function defs."""
    nested = {id(n) for n in ast.walk(scope)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and n is not scope}
    yield from _walk_skipping(scope, nested)


# --------------------------------------------------------------------------
@register
class UnknownMeshAxis(Rule):
    name = "unknown-mesh-axis"
    description = ("PartitionSpec/in_specs/axis_names axis literal no declared "
                   "mesh defines (alias-aware: *_AXIS constants resolve "
                   "cross-module) — the typo class behind the PR 9 GSPMD "
                   "kv-projection miscompile; declared axes are pinned in the "
                   f"committed {DEFAULT_MESH_MANIFEST_NAME} manifest "
                   "(regenerate after a deliberate mesh change with "
                   "bin/dstpu-lint --update-mesh-manifest)")

    def __init__(self):
        self._missing_reported = False
        self._sync_reported = False

    def check(self, module, ctx):
        info = ctx.mesh_model.module_info(module)
        uses = [u for site in info.spec_sites for u in site.axis_uses()]
        uses += list(info.axis_name_uses)
        declared = ctx.mesh_model.declared_axis_names()
        if ctx.mesh_manifest is None:
            if uses and not self._missing_reported:
                self._missing_reported = True
                # the three manifest-level findings share rule+path+line, so
                # each carries a distinct snippet: fingerprints must differ or
                # one baseline entry / SARIF upload dedup swallows another
                yield Finding(
                    rule=self.name, path=DEFAULT_MESH_MANIFEST_NAME, line=1, col=0,
                    snippet="mesh-manifest-missing",
                    message=f"mesh manifest {DEFAULT_MESH_MANIFEST_NAME} does not "
                            f"exist — generate it once with 'bin/dstpu-lint "
                            f"--update-mesh-manifest' and commit it; without it "
                            f"the tree's mesh axis names are unpinned and an "
                            f"axis typo lands as a silent GSPMD miscompile "
                            f"instead of a lint error")
            return
        if not self._sync_reported:
            self._sync_reported = True
            unpinned = sorted(declared - ctx.mesh_manifest)
            if unpinned:
                yield Finding(
                    rule=self.name, path=DEFAULT_MESH_MANIFEST_NAME, line=1, col=0,
                    snippet="mesh-manifest-unpinned",
                    message=f"mesh axis(es) declared in the tree but not pinned "
                            f"in {DEFAULT_MESH_MANIFEST_NAME}: "
                            f"{', '.join(unpinned)} — after a deliberate mesh "
                            f"change regenerate with 'bin/dstpu-lint "
                            f"--update-mesh-manifest' (and review the diff)")
            stale = sorted(ctx.mesh_manifest - declared)
            if stale:
                yield Finding(
                    rule=self.name, path=DEFAULT_MESH_MANIFEST_NAME, line=1, col=0,
                    snippet="mesh-manifest-stale",
                    message=f"{len(stale)} pinned mesh axis(es) no longer "
                            f"declared anywhere in the tree "
                            f"({', '.join(stale)}) — the manifest must stay "
                            f"exact; regenerate with 'bin/dstpu-lint "
                            f"--update-mesh-manifest'",
                    severity="warning")
        # module-local declarations count too: an ad-hoc Mesh in a script or
        # bench file validates that file's own specs without entering the
        # package manifest
        known = declared | ctx.mesh_manifest | set(info.declarations)
        for u in uses:
            if u.axis == UNRESOLVED or u.axis in known:
                continue
            via = f" (via constant {u.via})" if u.via else ""
            yield self.finding(
                module, u.node,
                f"mesh axis '{u.axis}'{via} is not declared by any Mesh/"
                f"make_mesh construction or *_AXIS constant "
                f"(declared: {', '.join(sorted(known)) or 'none'}) — an axis "
                f"typo in a PartitionSpec does not error at trace time, it "
                f"silently changes the GSPMD partitioning (the PR 9 "
                f"kv-projection miscompile class); fix the spelling, or "
                f"declare the axis and re-pin with 'bin/dstpu-lint "
                f"--update-mesh-manifest'")


# --------------------------------------------------------------------------
@register
class ShardingDroppedAtBoundary(Rule):
    name = "sharding-dropped-at-boundary"
    description = ("NamedSharding-placed value flowing into np.asarray/"
                   "jax.device_get/jnp.asarray-without-device or a fresh "
                   "un-annotated device_put — the placement silently collapses "
                   "to a single device (the exact gap keeping DeviceBatchState "
                   "off the multichip fast path)")

    def check(self, module, ctx):
        sharding_names = ctx.mesh_model.module_info(module).sharding_var_names
        for scope in _scopes(module.tree):
            yield from self._check_locals(module, scope, sharding_names)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class_attrs(module, node, sharding_names)

    def _drop_call(self, call: ast.Call):
        """(dropped-arg node, message) when ``call`` collapses a placement."""
        f = call.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and call.args):
            return None
        owner = f.value.id
        if f.attr in ("asarray", "array") and owner in NP_MODULE_NAMES:
            return call.args[0], f"{owner}.{f.attr}() pulls the placed value to host"
        if f.attr == "device_get" and owner == "jax":
            return call.args[0], "jax.device_get() pulls the placed value to host"
        has_device = any(kw.arg in ("device", "sharding") for kw in call.keywords)
        if f.attr == "asarray" and owner in JNP_MODULE_NAMES and not has_device:
            return call.args[0], ("jnp.asarray() without device= re-commits the "
                                  "value without its NamedSharding")
        if f.attr == "device_put" and owner == "jax" and len(call.args) == 1 \
                and not has_device:
            return call.args[0], ("jax.device_put() without a sharding commits "
                                  "the value to the default single device")
        return None

    def _finding(self, module, node, expr, how, placed_line):
        return self.finding(
            module, node,
            f"{how}: '{expr}' was placed with a NamedSharding (line "
            f"{placed_line}) and this boundary silently collapses it to "
            f"single-device — under a TP/DP mesh the next sharded computation "
            f"either gathers the world or miscompiles (the DeviceBatchState "
            f"commit-path gap that forces tp>1 serving onto the slow path); "
            f"carry the sharding across the boundary (device=..., an explicit "
            f"NamedSharding arg) or suppress with a reason if this collapse "
            f"is deliberate (checkpoint-save host serialization, init-time "
            f"staging)")

    def _check_locals(self, module, scope, sharding_names):
        """Linear scan: placement stores, unbinding stores, drop calls —
        within one line drops (loads of the old value) order before stores.
        ANY store of a name (for target, with-as, tuple unpack) unbinds it:
        a placed name reused as a loop variable is no longer the placement."""
        events = []
        modeled: Set[int] = set()
        for node in _own_nodes(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                modeled.add(id(node.targets[0]))
                kind = "place" if _placement_value(node.value, sharding_names) \
                    else "unbind"
                events.append((node.lineno, kind, node.targets[0].id, node))
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    id(node) not in modeled:
                # parents precede children in the walk, so modeled targets
                # are already excluded here
                events.append((node.lineno, "unbind", node.id, node))
            elif isinstance(node, ast.Call):
                hit = self._drop_call(node)
                if hit is not None:
                    arg, how = hit
                    if isinstance(arg, ast.Name):
                        events.append((node.lineno, "drop", arg.id, (node, how)))
        events.sort(key=lambda e: (e[0], 0 if e[1] == "drop" else 1))
        placed: Dict[str, int] = {}
        for line, kind, name, payload in events:
            if kind == "place":
                placed[name] = line
            elif kind == "unbind":
                placed.pop(name, None)
            elif name in placed:
                node, how = payload
                yield self._finding(module, node, name, how, placed[name])

    def _check_class_attrs(self, module, cls, sharding_names):
        """Cross-method attribute flow: ``self.x`` placed in one method (the
        __init__-placement / step-drop split is where the real serving bug
        lives) and collapsed in another — no line ordering, the placement is
        the attribute's steady state."""
        placed: Dict[str, int] = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Attribute) and \
                    _placement_value(node.value, sharding_names):
                placed.setdefault(ast.unparse(node.targets[0]), node.lineno)
        if not placed:
            return
        for node in ast.walk(cls):
            if not isinstance(node, ast.Call):
                continue
            hit = self._drop_call(node)
            if hit is None:
                continue
            arg, how = hit
            if not isinstance(arg, ast.Attribute):
                continue
            expr = ast.unparse(arg)
            if expr in placed:
                yield self._finding(module, node, expr, how, placed[expr])


# --------------------------------------------------------------------------
@register
class SpecRankMismatch(Rule):
    name = "spec-rank-mismatch"
    description = ("PartitionSpec with more dimensions than the annotated "
                   "array's statically-known rank — over-ranked specs are a "
                   "runtime error on the first real multichip mesh, long "
                   "after single-chip tests went green")

    def check(self, module, ctx):
        info = ctx.mesh_model.module_info(module)
        site_rank = {id(s.call): s.rank for s in info.spec_sites}
        for scope in _scopes(module.tree):
            yield from self._check_scope(module, scope, site_rank)

    def _spec_rank(self, expr, site_rank, spec_vars, shard_vars):
        """Rank of a spec/sharding expression, else None."""
        if isinstance(expr, ast.Call):
            t = _terminal_name(expr.func)
            if t == "NamedSharding" and len(expr.args) >= 2:
                return self._spec_rank(expr.args[1], site_rank, spec_vars,
                                       shard_vars)
            if id(expr) in site_rank:
                return site_rank[id(expr)]
            return None
        if isinstance(expr, ast.Name):
            if expr.id in spec_vars:
                return spec_vars[expr.id]
            return shard_vars.get(expr.id)
        return None

    def _check_scope(self, module, scope, site_rank):
        value_rank: Dict[str, int] = {}
        spec_vars: Dict[str, int] = {}
        shard_vars: Dict[str, int] = {}
        # ONE source-ordered linear scan (the tree walk is not source-ordered):
        # spec-variable chains resolve, and a rebind to an unknown-rank value
        # INVALIDATES the name instead of leaving a stale "provable" rank —
        # within a line, calls order before stores (args evaluate first).
        # ANY other store of the name (for target, with-as, tuple unpack,
        # augmented assign) also invalidates: kinds call=0, invalidate=1,
        # modeled-assign=2
        events = []
        modeled: Set[int] = set()
        for node in _own_nodes(scope):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                modeled.add(id(node.targets[0]))
                events.append((node.lineno, 2, node))
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)) and \
                    id(node) not in modeled:
                # parents precede children in the walk, so a modeled assign's
                # own target Name is already excluded here
                events.append((node.lineno, 1, node))
            elif isinstance(node, ast.Call) and \
                    _terminal_name(node.func) in ("device_put",
                                                  "make_array_from_callback") \
                    and len(node.args) >= 2:
                events.append((node.lineno, 0, node))
        for _, kind, node in sorted(events, key=lambda e: (e[0], e[1])):
            if kind == 1:
                for table in (value_rank, spec_vars, shard_vars):
                    table.pop(node.id, None)
                continue
            if kind == 2:
                tgt, val = node.targets[0].id, node.value
                for table in (value_rank, spec_vars, shard_vars):
                    table.pop(tgt, None)
                rank = creation_rank(val)
                if rank is not None:
                    value_rank[tgt] = rank
                    continue
                srank = self._spec_rank(val, site_rank, spec_vars, shard_vars)
                if srank is not None:
                    if isinstance(val, ast.Call) and \
                            _terminal_name(val.func) == "NamedSharding":
                        shard_vars[tgt] = srank
                    else:
                        spec_vars[tgt] = srank
                continue
            if _terminal_name(node.func) == "device_put":
                vrank = self._value_rank(node.args[0], value_rank)
            else:
                vrank = shape_rank(node.args[0])
            srank = self._spec_rank(node.args[1], site_rank, spec_vars,
                                    shard_vars)
            if vrank is None or srank is None or srank <= vrank:
                continue
            yield self.finding(
                module, node,
                f"PartitionSpec names {srank} dimension(s) but the annotated "
                f"array is provably rank {vrank} — an over-ranked spec is "
                f"rejected at placement time on a real multichip mesh (and "
                f"nothing catches it on the single-device CPU lane); trim the "
                f"spec — trailing dimensions replicate implicitly")

    def _value_rank(self, expr, value_rank) -> Optional[int]:
        rank = creation_rank(expr)
        if rank is not None:
            return rank
        if isinstance(expr, ast.Name):
            return value_rank.get(expr.id)
        return None


# --------------------------------------------------------------------------
@register
class RecompileRisk(Rule):
    name = "recompile-risk"
    description = ("request/batch-cardinality expression (len/sum of runtime "
                   "state) reaching a jit static argument or a padded-shape "
                   "array construction under inference/v2/ without passing "
                   "through the bucketing helpers — each distinct value mints "
                   "a fresh compiled program, breaking the zero-warm-"
                   "recompiles invariant the fastpath smoke only observes "
                   "after the fact")

    V2_PATH_FRAGMENT = "inference/v2/"
    DYNAMIC_CALLS = {"len", "sum"}
    # the sanctioned cardinality->shape launders: one shared pow2 bucketer +
    # the engine's table-width stepper (engine_v2/fastpath)
    SANCTIFIERS = {"round_up_pow2", "_bucket", "_stepped_width"}
    CREATION_OWNERS = NP_MODULE_NAMES | JNP_MODULE_NAMES
    CREATION_FNS = MESH_CREATION_FNS  # one definition of "array creation"

    def check(self, module, ctx):
        if self.V2_PATH_FRAGMENT not in module.relpath.replace("\\", "/"):
            return
        yield from self._check_static_args(module, ctx)
        yield from self._check_shape_constructions(module)

    # ---- leg a: static jit arguments
    def _check_static_args(self, module, ctx):
        for site in ctx.static_jit_sites(module):
            offset = 0
            if site.binding == "local":
                fn = enclosing(site.jit_call, ast.FunctionDef, ast.AsyncFunctionDef)
                scope = fn if fn is not None else module.tree
                calls = _calls_of_name(scope, site.name, attribute=False)
            elif site.binding == "attribute":
                calls = _calls_of_name(module.tree, site.name, attribute=True)
            elif site.binding == "decorated":
                # @jax.jit(...)-decorated def: calls bind the decorated NAME —
                # bare for a module-level function, self.<name> for a method
                # (where bound calls shift static_argnums left past `self`)
                is_method = enclosing(site.fn_node, ast.ClassDef) is not None
                offset = 1 if is_method else 0
                calls = _calls_of_name(module.tree, site.name,
                                       attribute=is_method)
            else:
                continue
            for call in calls:
                if call is site.jit_call:
                    continue
                for pos in site.static_positions:
                    if pos - offset >= 0 and pos - offset < len(call.args):
                        yield from self._check_expr(module, call.args[pos - offset],
                                                    f"static position {pos}")
                for kw in call.keywords:
                    if kw.arg in site.static_names:
                        yield from self._check_expr(module, kw.value,
                                                    f"static argument '{kw.arg}'")

    def _check_expr(self, module, expr, where: str):
        dyn = self._dynamic_node(expr)
        if dyn is None:
            return
        yield self.finding(
            module, dyn,
            f"'{ast.unparse(dyn)}' — a runtime-cardinality value — reaches "
            f"{where} of a jitted callable: every distinct value traces and "
            f"compiles a FRESH program, so steady-state serving recompiles "
            f"exactly when load shifts (the warm-recompile stall the fastpath "
            f"smoke's zero-warm-recompiles counter only observes after the "
            f"fact); bucket it through round_up_pow2/_bucket/_stepped_width "
            f"first, or make the argument traced")

    # ---- leg b: padded-shape constructions
    def _check_shape_constructions(self, module):
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute) and f.attr in self.CREATION_FNS
                    and isinstance(f.value, ast.Name)
                    and f.value.id in self.CREATION_OWNERS):
                continue
            dyn = self._dynamic_node(node.args[0])
            if dyn is None:
                continue
            yield self.finding(
                module, dyn,
                f"array shape derived from raw runtime cardinality "
                f"'{ast.unparse(dyn)}' — this buffer's shape changes with "
                f"load, and every new shape that reaches a jitted program is "
                f"a fresh compile; pad through round_up_pow2/_bucket/"
                f"_stepped_width (the shared bucketing primitives) instead")

    def _dynamic_node(self, expr) -> Optional[ast.AST]:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and \
                    _terminal_name(node.func) in self.DYNAMIC_CALLS and \
                    not self._sanctified(node, expr):
                return node
        return None

    def _sanctified(self, node, stop) -> bool:
        """A bucketing call strictly WITHIN the checked expression encloses
        ``node``.  The walk must not escape ``stop``: bucketing the RESULT of
        a jitted call (``round_up_pow2(fn(len(x)))``) does nothing for the
        static argument inside it."""
        if node is stop:
            return False
        cur = parent(node)
        while cur is not None:
            if isinstance(cur, ast.Call) and \
                    _terminal_name(cur.func) in self.SANCTIFIERS:
                return True
            if cur is stop:
                return False
            cur = parent(cur)
        return False


# --------------------------------------------------------------------------
@register
class DonationShardingMismatch(Rule):
    name = "donation-sharding-mismatch"
    description = ("argument donated to a jitted callable rebound to a "
                   "differently-specced placement — donation aliasing needs "
                   "identical shardings, so the in-place update silently "
                   "degrades to a copy (and a recompile)")

    def check(self, module, ctx):
        info = ctx.mesh_model.module_info(module)
        sharding_names = info.sharding_var_names
        site_key = {id(s.call): self._site_key(s) for s in info.spec_sites}
        donated = self._donated_exprs(module, ctx)
        if not donated:
            return
        for scope in _scopes(module.tree):
            yield from self._check_scope(module, scope, donated, site_key,
                                         sharding_names, attr_mode=False)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_scope(module, node, donated, site_key,
                                             sharding_names, attr_mode=True)

    def _site_key(self, site):
        """Canonical identity of a spec: resolved axis tuples with trailing
        replicated dims stripped (PartitionSpec('x') == PartitionSpec('x',
        None)); unresolved entries fall back to textual identity."""
        if site.rank is None or any(u.axis == UNRESOLVED
                                    for u in site.axis_uses()):
            return ast.unparse(site.call)
        dims = [tuple(u.axis for u in dim) for dim in site.entries]
        while dims and not dims[-1]:
            dims.pop()
        return tuple(dims)

    def _donated_exprs(self, module, ctx) -> Set[str]:
        out: Set[str] = set()
        for site in ctx.donation_sites(module):
            if site.binding == "local":
                fn = enclosing(site.jit_call, ast.FunctionDef, ast.AsyncFunctionDef)
                scope = fn if fn is not None else module.tree
                attribute = False
            elif site.binding == "attribute":
                scope, attribute = module.tree, True
            else:
                continue
            for call in _calls_of_name(scope, site.name, attribute=attribute):
                for idx in site.donated:
                    if idx < len(call.args) and \
                            isinstance(call.args[idx], (ast.Name, ast.Attribute)):
                        out.add(ast.unparse(call.args[idx]))
        return out

    def _placement_key(self, value, site_key, sharding_names):
        """Spec identity of a placement expression, else None."""
        if not _placement_value(value, sharding_names):
            return None
        t = _terminal_name(value.func)
        if t == "device_put" and len(value.args) >= 2:
            return self._sharding_key(value.args[1], site_key)
        if t == "make_array_from_callback":
            for a in list(value.args) + [kw.value for kw in value.keywords]:
                key = self._sharding_key(a, site_key)
                if key is not None:
                    return key
        return None

    def _sharding_key(self, expr, site_key):
        if isinstance(expr, ast.Call):
            t = _terminal_name(expr.func)
            if t == "NamedSharding" and len(expr.args) >= 2:
                spec = expr.args[1]
                if isinstance(spec, ast.Call) and id(spec) in site_key:
                    return site_key[id(spec)]
                return None  # spec via a variable/attr: the model never guesses
            if t in SHARDING_FACTORY_METHODS and expr.args:
                spec = expr.args[0]
                if isinstance(spec, ast.Call) and id(spec) in site_key:
                    return site_key[id(spec)]
                return None
        return None

    def _check_scope(self, module, scope, donated, site_key, sharding_names,
                     attr_mode: bool):
        placements: Dict[str, Tuple[object, int]] = {}  # expr -> (key, line)
        nodes = ast.walk(scope) if attr_mode else _own_nodes(scope)
        # the tree walks are not source-ordered — sort, or the finding anchors
        # on the ORIGINAL placement and cites the rebind as "its placement"
        assigns = sorted(
            (n for n in nodes
             if isinstance(n, ast.Assign) and len(n.targets) == 1),
            key=lambda n: n.lineno)
        for node in assigns:
            tgt = node.targets[0]
            if attr_mode and not isinstance(tgt, ast.Attribute):
                continue
            if not attr_mode and not isinstance(tgt, ast.Name):
                continue
            expr = ast.unparse(tgt)
            if expr not in donated:
                continue
            key = self._placement_key(node.value, site_key, sharding_names)
            if key is None:
                continue
            prev = placements.get(expr)
            # flag only when BOTH specs resolved to canonical axis tuples —
            # a textual fallback key (unresolved spec site) can't prove a
            # genuine mismatch against a resolved one
            if prev is not None and prev[0] != key and \
                    isinstance(prev[0], tuple) and isinstance(key, tuple):
                yield self.finding(
                    module, node.value,
                    f"'{expr}' is DONATED to a jitted callable but rebound "
                    f"here with a different sharding than its placement at "
                    f"line {prev[1]} — XLA only aliases a donated buffer when "
                    f"the sharding matches the compiled expectation, so this "
                    f"donation silently degrades to a full copy (plus a "
                    f"recompile for the new layout); keep one spec for the "
                    f"donated value's lifetime or drop the donation")
            else:
                placements[expr] = (key, node.lineno)


# --------------------------------------------------------------------------
# Concurrency rules (threadcheck).  All five consume ctx.thread_model — the
# cross-module thread plane built by thread_model.py (thread roots,
# reachability, attribute events with held-lock sets, lock-order edges).
# The model is global but rules report per-module: each rule runs the
# project-wide analysis once per context and replays the findings that land
# in the module being linted.


class _ThreadRule(Rule):
    """Base: one project-wide analysis per ProjectContext, findings replayed
    per module (the runner lints module-by-module; a cross-module race must
    surface in whichever file is being checked)."""

    def check(self, module, ctx):
        if getattr(self, "_ctx_id", None) != id(ctx):
            self._ctx_id = id(ctx)
            self._by_module: Dict[str, List] = {}
            for relpath, node, message in self._analyze(ctx.thread_model):
                self._by_module.setdefault(relpath, []).append((node, message))
            for findings in self._by_module.values():
                findings.sort(key=lambda t: (t[0].lineno, t[0].col_offset))
        for node, message in self._by_module.get(module.relpath, []):
            yield self.finding(module, node, message)

    def _analyze(self, tm):
        raise NotImplementedError


def _root_phrase(tm, key) -> str:
    root = tm.root_for(key, ("thread", "handler", "collector"))
    return f" (thread-entered via {root.label})" if root is not None else ""


@register
class CrossThreadMutation(_ThreadRule):
    name = "cross-thread-mutation"
    description = ("shared attribute written from a thread-reachable function "
                   "AND written (or read-modify-written) from the main "
                   "serve/train path with no common lock — a lost-update race "
                   "outside the sanctioned single-writer atomic-publish "
                   "pattern (the AsyncCheckpointEngine._error class of bug)")

    def _analyze(self, tm):
        from .thread_model import AttrEvent  # noqa: F401 (documentation)
        for (owner, attr), events in sorted(tm.attr_events.items()):
            if tm.is_threadsafe_attr(owner, attr):
                continue
            evs = [e for e in events
                   if not e.in_init and tm.plane_of(e.func) != "signal"]
            thread = [e for e in evs if tm.plane_of(e.func) == "thread"]
            main = [e for e in evs if tm.plane_of(e.func) == "main"]
            if not thread or not main:
                continue
            reported: Set[int] = set()

            def report(e, other, why):
                if id(e.node) in reported:
                    return ()
                reported.add(id(e.node))
                return ((e.relpath, e.node,
                         f"'{owner}.{attr}' {why} — the other side is at "
                         f"{other.relpath}:{other.node.lineno}"
                         f"{_root_phrase(tm, e.func if tm.plane_of(e.func) == 'thread' else other.func)}; "
                         f"hold one common lock on both sides, or restructure "
                         f"so a single thread owns every write and publishes "
                         f"whole immutable values (the OpsCache pattern)"), )

            t_writes = [e for e in thread if e.kind in ("rebind", "augassign")]
            m_writes = [e for e in main if e.kind in ("rebind", "augassign")]
            for tw in t_writes:
                for mw in m_writes:
                    if tw.locks & mw.locks:
                        continue
                    yield from report(
                        tw, mw, "is written from a thread entrypoint here and "
                        "also written on the main plane with no common lock "
                        "(concurrent writes lose updates)")
                    yield from report(
                        mw, tw, "is written on the main plane here and also "
                        "written from a thread entrypoint with no common lock "
                        "(concurrent writes lose updates)")
            for aug, others in ((e, main) for e in thread
                                if e.kind == "augassign"):
                for o in others:
                    if aug.locks & o.locks:
                        continue
                    yield from report(
                        aug, o, "is read-modify-written (+=/-=) from a thread "
                        "entrypoint here while the main plane touches it — "
                        "augmented assignment is not atomic even under the GIL")
            for aug, others in ((e, thread) for e in main
                                if e.kind == "augassign"):
                for o in others:
                    if aug.locks & o.locks:
                        continue
                    yield from report(
                        aug, o, "is read-modify-written (+=/-=) on the main "
                        "plane here while a thread entrypoint touches it — "
                        "augmented assignment is not atomic even under the GIL")


@register
class AtomicPublish(_ThreadRule):
    name = "atomic-publish"
    description = ("cross-thread published state must be a whole-attribute "
                   "rebind of an immutable value: on a class instances of "
                   "which are touched from BOTH the thread plane and the main "
                   "plane, in-place container mutation / subscript stores / "
                   "augmented assignment on an unlocked attribute is a "
                   "finding — this makes the OpsCache \"GIL-atomic whole-"
                   "string assignment\" convention a checked contract")

    def _analyze(self, tm):
        from .thread_model import INPLACE_KINDS, is_mutable_value
        planes_by_class: Dict[str, Set[str]] = {}
        for (owner, _attr), events in tm.attr_events.items():
            for e in events:
                if not e.in_init:
                    planes_by_class.setdefault(owner, set()).add(
                        tm.plane_of(e.func))
        shared = {c for c, planes in planes_by_class.items()
                  if "thread" in planes and "main" in planes}
        for (owner, attr), events in sorted(tm.attr_events.items()):
            if owner not in shared or tm.is_threadsafe_attr(owner, attr):
                continue
            evs = [e for e in events
                   if not e.in_init and tm.plane_of(e.func) != "signal"]
            for e in evs:
                other = [o for o in evs
                         if tm.plane_of(o.func) != tm.plane_of(e.func)]
                # lock-disciplined attrs are exempt: the event holds a lock
                # every other-plane access of this attr also holds
                if e.locks and all(e.locks & o.locks for o in other):
                    continue
                if e.kind == "augassign" and not other:
                    # (with other-plane access this is cross-thread-mutation's
                    # finding; here the attr itself never crosses, but it
                    # rides on an object that DOES — same publish contract)
                    yield (e.relpath, e.node,
                           f"'{owner}.{attr}' is read-modify-written (+=) on "
                           f"an instance shared across threads — not an "
                           f"atomic publish; rebind a complete immutable "
                           f"value instead, or move the counter off the "
                           f"shared object")
                elif e.kind in INPLACE_KINDS:
                    yield (e.relpath, e.node,
                           f"in-place mutation of '{owner}.{attr}' on an "
                           f"instance shared across threads — a concurrent "
                           f"reader can observe the half-applied mutation; "
                           f"the atomic-publish contract requires building "
                           f"the new value privately and rebinding the whole "
                           f"attribute (one GIL-atomic pointer store)")
                elif e.kind == "rebind" and is_mutable_value(e.value) and \
                        any(o.kind == "read" for o in other):
                    yield (e.relpath, e.node,
                           f"'{owner}.{attr}' publishes a freshly-built "
                           f"MUTABLE container to a cross-thread reader — "
                           f"later in-place edits through this attribute "
                           f"race those readers; publish an immutable "
                           f"rendering (str/bytes/tuple) instead")


@register
class HandlerHoldsEngine(_ThreadRule):
    name = "handler-holds-engine"
    description = ("ops handlers, thread targets, collector callbacks and "
                   "signal handlers may not capture or reach an engine/"
                   "manager reference — the scrape-safety contract: a "
                   "thread-entered function touching the engine can sync a "
                   "device or race a step; hand it pre-rendered host state "
                   "(the OpsCache pattern) instead")

    KIND_LABEL = {"thread": "thread target", "handler": "HTTP handler",
                  "collector": "collector callback", "signal": "signal handler"}

    def _analyze(self, tm):
        done: Set[Tuple] = set()
        for root in tm.roots:
            key = root.key
            if key is None or key not in tm.functions or \
                    (key, root.kind) in done:
                continue
            done.add((key, root.kind))
            fn = tm.functions[key]
            label = self.KIND_LABEL.get(root.kind, root.kind)
            refs = tm.engine_refs.get(key)
            if refs:
                node, cls = refs[0]
                yield (fn.relpath, node,
                       f"{label} '{fn.key[1]}' holds a reference to "
                       f"engine/manager class '{cls}' — thread-entered code "
                       f"must not capture or reach the engine (it could sync "
                       f"a device or race a step); pass pre-rendered host "
                       f"state instead")
                continue
            hit = self._reachable_engine_ref(tm, key)
            if hit is not None:
                hk, cls = hit
                yield (fn.relpath, fn.node,
                       f"{label} '{fn.key[1]}' reaches engine/manager class "
                       f"'{cls}' through '{hk[1]}' ({hk[0]}) — thread-entered "
                       f"code must not reach the engine; pass pre-rendered "
                       f"host state instead")

    def _reachable_engine_ref(self, tm, key):
        seen, todo = set(), sorted(tm.functions[key].resolved_callees)
        while todo:
            k = todo.pop(0)
            if k in seen or k not in tm.functions:
                continue
            seen.add(k)
            refs = tm.engine_refs.get(k)
            if refs:
                return k, refs[0][1]
            todo.extend(sorted(tm.functions[k].resolved_callees))
        return None


@register
class BlockingUnderLock(_ThreadRule):
    name = "blocking-under-lock"
    description = ("sleep / thread-or-queue join / fsync / subprocess / "
                   "collective entry / device sync while holding a lock — "
                   "every other thread contending for that lock stalls for "
                   "the full blocking duration (and a collective under a "
                   "lock deadlocks the fleet if any peer needs the lock to "
                   "reach its own collective)")

    def _analyze(self, tm):
        for bc in tm.blocking_calls:
            locks = ", ".join(sorted(bc.locks))
            yield (bc.relpath, bc.node,
                   f"blocking call ({bc.what}) while holding lock(s) "
                   f"[{locks}] — move the blocking work outside the critical "
                   f"section (compute under the lock, block outside it)")


@register
class LockOrder(_ThreadRule):
    name = "lock-order"
    description = ("inconsistent lock-acquisition order across the project — "
                   "somewhere lock A is taken under lock B while elsewhere B "
                   "is taken under A: the classic ABBA deadlock; pick one "
                   "global order (document it where the locks are defined)")

    def _analyze(self, tm):
        edges: Dict[Tuple[str, str], List] = {}
        for e in tm.lock_edges:
            edges.setdefault((e.outer, e.inner), []).append(e)
        seen_pairs: Set[frozenset] = set()
        for (a, b), sites in sorted(edges.items()):
            if a == b or (b, a) not in edges:
                continue
            pair = frozenset((a, b))
            if pair in seen_pairs:
                continue
            seen_pairs.add(pair)
            rev = edges[(b, a)]
            for e in sites:
                yield (e.relpath, e.node,
                       f"lock '{b}' acquired while holding '{a}' here, but "
                       f"{rev[0].relpath}:{rev[0].node.lineno} acquires "
                       f"'{a}' while holding '{b}' — inconsistent ordering "
                       f"is an ABBA deadlock waiting for contention; pick "
                       f"one project-wide order")
            for e in rev:
                yield (e.relpath, e.node,
                       f"lock '{a}' acquired while holding '{b}' here, but "
                       f"{sites[0].relpath}:{sites[0].node.lineno} acquires "
                       f"'{b}' while holding '{a}' — inconsistent ordering "
                       f"is an ABBA deadlock waiting for contention; pick "
                       f"one project-wide order")


def build_rules(enabled: Optional[Iterable[str]] = None,
                disabled: Iterable[str] = ()) -> List[Rule]:
    names = list(RULES) if enabled is None else list(enabled)
    unknown = [n for n in list(names) + list(disabled) if n not in RULES]
    if unknown:
        raise KeyError(f"unknown rule(s): {', '.join(unknown)}; known: {', '.join(RULES)}")
    return [RULES[n]() for n in names if n not in set(disabled)]
