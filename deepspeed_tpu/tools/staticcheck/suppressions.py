"""Inline suppression comments.

Grammar (the reason is REQUIRED — a suppression without one is inert and is
itself reported, so every grandfathered pattern carries a written
justification):

    x = float(loss)  # dslint: disable=host-sync-in-hot-path  # one sync/step by design
    # dslint: disable-next-line=silent-except  # interpreter-shutdown teardown
    # dslint: disable-file=nondeterministic-rng  # fuzz harness, randomness is the point

Comments are located with ``tokenize`` (never by regexing raw lines), so
string literals that merely look like suppressions are ignored.
"""

import dataclasses
import io
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .findings import Finding

_PATTERN = re.compile(
    r"dslint:\s*(?P<kind>disable(?:-next-line|-file)?)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_-]+(?:\s*,\s*[A-Za-z0-9_-]+)*)"
    r"(?:\s*#\s*(?P<reason>\S.*?))?\s*$")


@dataclasses.dataclass
class Suppression:
    kind: str  # disable | disable-next-line | disable-file
    rules: Tuple[str, ...]
    reason: Optional[str]
    line: int  # line the COMMENT sits on
    col: int
    hits: int = 0

    @property
    def target_line(self) -> Optional[int]:
        if self.kind == "disable":
            return self.line
        if self.kind == "disable-next-line":
            return self.line + 1
        return None  # file-level


def parse_suppressions(source: str, path: str) -> Tuple[List[Suppression], List[Finding]]:
    """Extract suppressions from ``source``.  Malformed ones (missing reason)
    come back as ``bad-suppression`` findings and suppress nothing."""
    suppressions: List[Suppression] = []
    problems: List[Finding] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [t for t in tokens if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return [], []
    lines = source.splitlines()
    for tok in comments:
        if "dslint:" not in tok.string:
            continue
        m = _PATTERN.search(tok.string)
        line, col = tok.start
        snippet = lines[line - 1].strip() if line <= len(lines) else ""
        if not m:
            problems.append(Finding(
                rule="bad-suppression", path=path, line=line, col=col,
                message="unparsable dslint control comment; expected "
                        "'# dslint: disable[-next-line|-file]=<rule>[,<rule>]  # reason'",
                snippet=snippet))
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        reason = m.group("reason")
        if not reason:
            problems.append(Finding(
                rule="bad-suppression", path=path, line=line, col=col,
                message=f"suppression of {', '.join(rules)} has no reason; append "
                        f"'  # <why this finding is acceptable>' (the suppression is inert)",
                snippet=snippet))
            continue
        suppressions.append(Suppression(kind=m.group("kind"), rules=rules,
                                        reason=reason, line=line, col=col))
    return suppressions, problems


class SuppressionIndex:
    """Answers 'is finding F suppressed?' and tracks which suppressions fired."""

    def __init__(self, suppressions: Iterable[Suppression]):
        self.file_level: List[Suppression] = []
        self.by_line: Dict[int, List[Suppression]] = {}
        self.all: List[Suppression] = list(suppressions)
        for s in self.all:
            target = s.target_line
            if target is None:
                self.file_level.append(s)
            else:
                self.by_line.setdefault(target, []).append(s)

    def suppresses(self, finding: Finding) -> bool:
        candidates = list(self.file_level)
        for line in range(finding.line, max(finding.end_line, finding.line) + 1):
            candidates.extend(self.by_line.get(line, []))
        for s in candidates:
            if finding.rule in s.rules:
                s.hits += 1
                return True
        return False

    def unused(self, ran_rules: Set[str]) -> List[Suppression]:
        """Suppressions that matched nothing — but only for rules that actually
        ran this invocation (a ``--disable``d rule doesn't orphan its
        suppressions)."""
        return [s for s in self.all
                if s.hits == 0 and all(r in ran_rules for r in s.rules)]
