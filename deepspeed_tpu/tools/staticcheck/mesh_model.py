"""Cross-module mesh/axis model + the committed mesh manifest.

The multichip work (sharded ``DeviceBatchState``, shard_mapped serve loop)
lives or dies by axis annotations agreeing with the mesh they assume — and
PR 9's GSPMD kv-projection miscompile proved the failure mode is *silent*
(wrong logits, no error).  This module gives the rules a static model of
every mesh/axis fact in the tree:

- **declared axes** — every axis-name literal a mesh construction pins:
  ``Mesh(grid, axis_names=("data", ...))``, ``jax.make_mesh(shape, names)``,
  and the canonical module-level ``<NAME>_AXIS = "literal"`` constants
  (parallel/mesh.py builds its Mesh's axis_names dynamically from exactly
  these constants, so they ARE the static declaration);
- **axis references** — every ``PartitionSpec(...)`` construction (bare,
  inside ``NamedSharding``, inside ``shard_map``/pjit ``in_specs``/
  ``out_specs`` trees) with its per-dimension entries resolved alias-aware:
  a ``Name`` in axis position resolves through import aliases to the
  ``*_AXIS`` constant table, so ``PartitionSpec(TENSOR_AXIS)`` in another
  module resolves to ``"tensor"``; plus ``shard_map(..., axis_names={...})``
  manual-axis sets;
- the committed manifest (``.dslint-mesh-manifest.json``) of declared axis
  names, analogous to the API-surface manifest: the ``unknown-mesh-axis``
  rule keeps it exactly equal to the tree, so a new/renamed mesh axis lands
  as one reviewable manifest diff.

Everything is pure AST (no imports — the analyzer keeps working when jax is
broken).  Entries that static analysis cannot resolve (computed expressions,
``*splat``, function parameters) are marked :data:`UNRESOLVED` and skipped
by the rules — the model never guesses.
"""

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .context import ModuleInfo, terminal_name as _terminal_name

MESH_MANIFEST_VERSION = 1
DEFAULT_MESH_MANIFEST_NAME = ".dslint-mesh-manifest.json"
# only package files declare mesh axes for manifest purposes (tests build
# ad-hoc meshes freely and are not scanned by the mesh rules)
PACKAGE_PREFIX = "deepspeed_tpu/"

# canonical axis-constant convention: module-level NAME_AXIS = "literal"
AXIS_CONST_SUFFIX = "_AXIS"

# sentinel for an axis position whose value static analysis cannot resolve
UNRESOLVED = "?"

# mesh-constructing callables whose axis_names are DECLARATIONS, not uses
MESH_CTORS = {"Mesh", "make_mesh", "AbstractMesh"}
# spec-consuming callables whose axis_names are manual-axis REFERENCES
SHARD_MAP_NAMES = {"shard_map", "pjit"}
# MeshTopology helpers returning a NamedSharding (parallel/mesh.py)
SHARDING_FACTORY_METHODS = {"sharding", "replicated"}


def is_sharding_factory(node: ast.AST) -> bool:
    """``NamedSharding(...)`` or a topology factory producing one."""
    if not isinstance(node, ast.Call):
        return False
    t = _terminal_name(node.func)
    return t == "NamedSharding" or t in SHARDING_FACTORY_METHODS


def _str_elts(node: ast.AST) -> List[Tuple[str, ast.AST]]:
    """(literal, node) for every string constant in a tuple/list/set literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [(node.value, node)]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append((el.value, el))
        return out
    return []


@dataclasses.dataclass
class AxisUse:
    """One resolved axis-name reference inside a spec/axis_names position."""
    axis: str  # literal axis name, or UNRESOLVED
    node: ast.AST  # anchor for findings
    via: Optional[str] = None  # constant name it resolved through, if any


@dataclasses.dataclass
class SpecSite:
    """One ``PartitionSpec(...)`` construction."""
    call: ast.Call
    entries: List[List[AxisUse]]  # per dim; [] = None (replicated)
    rank: Optional[int]  # len(args), or None when *splat defeats arity

    def axis_uses(self) -> Iterable[AxisUse]:
        for dim in self.entries:
            yield from dim


@dataclasses.dataclass
class MeshModuleInfo:
    """Per-module mesh facts (alias-resolved against the global model)."""
    spec_sites: List[SpecSite]
    axis_name_uses: List[AxisUse]  # shard_map(axis_names={...}) references
    # axis -> declaring nodes IN THIS MODULE (mesh ctors + *_AXIS constants):
    # unknown-mesh-axis honors these even outside the package, so an ad-hoc
    # mesh in a script/bench file validates its own specs
    declarations: Dict[str, List[ast.AST]]
    # names/attrs assigned from a NamedSharding-producing expression anywhere
    # in the module (``rep = NamedSharding(mesh, spec)``) — shared by the
    # sharding-dataflow rules, collected once here instead of per rule
    sharding_var_names: Set[str]


class MeshModel:
    """Project-wide mesh/axis facts shared by the sharding rules."""

    def __init__(self, modules: List[ModuleInfo]):
        # CONST_NAME -> axis literal, from module-level *_AXIS = "..." (any
        # module in context: names are project-unique by convention)
        self.axis_constants: Dict[str, str] = {}
        # axis literal -> [(relpath, lineno)] declaration sites (package only)
        self.declared_axes: Dict[str, List[Tuple[str, int]]] = {}
        # relpath -> axis -> declaring nodes there (EVERY module, package or
        # not — module-local declarations validate that module's own specs)
        self._module_decls: Dict[str, Dict[str, List[ast.AST]]] = {}
        self._infos: Dict[str, MeshModuleInfo] = {}
        for mod in modules:
            self._collect_declarations(mod)
        for mod in modules:
            self._infos[mod.relpath] = self._collect_uses(mod)

    # ------------------------------------------------------------ declarations
    def _collect_declarations(self, mod: ModuleInfo) -> None:
        in_package = mod.relpath.startswith(PACKAGE_PREFIX)
        local = self._module_decls.setdefault(mod.relpath, {})

        def declare(axis: str, node: ast.AST) -> None:
            local.setdefault(axis, []).append(node)
            if in_package:
                self.declared_axes.setdefault(axis, []).append(
                    (mod.relpath, node.lineno))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id.endswith(AXIS_CONST_SUFFIX) and \
                    isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, str):
                # canonical axis constant — both an alias source and (in
                # package code) a declaration
                self.axis_constants.setdefault(node.targets[0].id,
                                               node.value.value)
                declare(node.value.value, node.value)
            elif isinstance(node, ast.Call) and \
                    _terminal_name(node.func) in MESH_CTORS:
                for axis, anode in self._ctor_axis_names(node):
                    declare(axis, anode)

    def _ctor_axis_names(self, call: ast.Call) -> List[Tuple[str, ast.AST]]:
        """Literal axis names a Mesh/make_mesh construction declares."""
        out: List[Tuple[str, ast.AST]] = []
        for kw in call.keywords:
            if kw.arg == "axis_names":
                out.extend(_str_elts(kw.value))
        # positional: Mesh(devices, names) / make_mesh(shape, names)
        if len(call.args) >= 2:
            out.extend(_str_elts(call.args[1]))
        return out

    # -------------------------------------------------------------------- uses
    def _collect_uses(self, mod: ModuleInfo) -> MeshModuleInfo:
        import_aliases: Dict[str, str] = {}  # local name -> imported name
        ps_names: Set[str] = {"PartitionSpec"}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name.endswith(AXIS_CONST_SUFFIX):
                        import_aliases[alias.asname or alias.name] = alias.name
                    elif alias.name == "PartitionSpec" and alias.asname:
                        ps_names.add(alias.asname)

        def resolve(node: ast.AST) -> Optional[AxisUse]:
            if isinstance(node, ast.Constant):
                if node.value is None:
                    return None
                if isinstance(node.value, str):
                    return AxisUse(axis=node.value, node=node)
                return AxisUse(axis=UNRESOLVED, node=node)
            name = _terminal_name(node)
            if name is not None:
                const = import_aliases.get(name, name)
                literal = self.axis_constants.get(const)
                if literal is not None:
                    return AxisUse(axis=literal, node=node, via=const)
            return AxisUse(axis=UNRESOLVED, node=node)

        def parse_entry(arg: ast.AST) -> List[AxisUse]:
            if isinstance(arg, (ast.Tuple, ast.List)):
                uses = []
                for el in arg.elts:
                    use = resolve(el)
                    if use is not None:
                        uses.append(use)
                return uses
            use = resolve(arg)
            return [use] if use is not None else []

        spec_sites: List[SpecSite] = []
        axis_name_uses: List[AxisUse] = []
        sharding_vars: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], (ast.Name, ast.Attribute)) and \
                    is_sharding_factory(node.value):
                sharding_vars.add(ast.unparse(node.targets[0]))
                continue
            if not isinstance(node, ast.Call):
                continue
            fname = _terminal_name(node.func)
            if fname in ps_names:
                entries: List[List[AxisUse]] = []
                rank: Optional[int] = len(node.args)
                for arg in node.args:
                    if isinstance(arg, ast.Starred):
                        rank = None  # *dims defeats static arity
                        continue
                    entries.append(parse_entry(arg))
                spec_sites.append(SpecSite(call=node, entries=entries, rank=rank))
            elif fname in SHARD_MAP_NAMES:
                for kw in node.keywords:
                    if kw.arg != "axis_names":
                        continue
                    if isinstance(kw.value, (ast.Tuple, ast.List, ast.Set)):
                        for el in kw.value.elts:
                            use = resolve(el)
                            if use is not None:
                                axis_name_uses.append(use)
                    else:
                        use = resolve(kw.value)
                        if use is not None:
                            axis_name_uses.append(use)
        return MeshModuleInfo(spec_sites=spec_sites,
                              axis_name_uses=axis_name_uses,
                              declarations=self._module_decls.get(mod.relpath, {}),
                              sharding_var_names=sharding_vars)

    # --------------------------------------------------------------- accessors
    def module_info(self, module: ModuleInfo) -> MeshModuleInfo:
        info = self._infos.get(module.relpath)
        if info is None:  # module outside the context set (shouldn't happen)
            self._collect_declarations(module)
            info = self._collect_uses(module)
            self._infos[module.relpath] = info
        return info

    def declared_axis_names(self) -> Set[str]:
        return set(self.declared_axes)


def collect_mesh_axes(modules: Iterable[ModuleInfo]) -> Set[str]:
    """The package's declared mesh axes (manifest regeneration)."""
    return MeshModel(list(modules)).declared_axis_names()


def load_mesh_manifest(path: str) -> Optional[Set[str]]:
    """Pinned axis names; None when the manifest has never been generated."""
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != MESH_MANIFEST_VERSION:
        raise ValueError(f"{path}: not a dslint mesh manifest "
                         f"(expected version={MESH_MANIFEST_VERSION})")
    return set(data.get("axes", []))


def save_mesh_manifest(path: str, axes: Set[str]) -> None:
    with open(path, "w") as fh:
        json.dump({"version": MESH_MANIFEST_VERSION, "axes": sorted(axes)},
                  fh, indent=1)
        fh.write("\n")


# ------------------------------------------------------- static shape helpers
# array-creation callables whose first argument is the shape — the only rank
# source spec-rank-mismatch trusts (everything else is rank-unknown, skipped)
CREATION_FNS = {"zeros", "ones", "empty", "full"}


def shape_rank(shape: ast.AST) -> Optional[int]:
    """Rank implied by a literal shape expression — the ONE definition of
    "statically-known shape" (creation calls, make_array_from_callback)."""
    if isinstance(shape, (ast.Tuple, ast.List)):
        if any(isinstance(el, ast.Starred) for el in shape.elts):
            return None
        return len(shape.elts)
    if isinstance(shape, ast.Constant) and isinstance(shape.value, int):
        return 1
    return None


def creation_rank(expr: ast.AST) -> Optional[int]:
    """Statically-known rank of an array-creation expression, else None."""
    if not isinstance(expr, ast.Call):
        return None
    name = _terminal_name(expr.func)
    if name in CREATION_FNS and expr.args:
        return shape_rank(expr.args[0])
    if name == "arange":
        return 1
    return None
