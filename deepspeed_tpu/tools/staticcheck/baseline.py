"""Committed baseline of grandfathered findings.

The baseline is a JSON file mapping finding fingerprints (rule + path +
source-line text — line-number-drift-proof, see findings.py) to an allowed
count.  ``dstpu-lint`` subtracts baselined findings and exits non-zero only on
NEW ones, so the tool can land on a codebase with known debt while ratcheting:
fixing a flagged line retires its entry automatically (the fingerprint changes
with the text), and ``--update-baseline`` rewrites the file from the current
findings.  Policy: the baseline only ever shrinks — new code suppresses with a
written reason instead of baselining.
"""

import json
import os
from collections import Counter
from typing import Dict, List, Tuple

from .findings import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE_NAME = ".dslint-baseline.json"


def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> allowed count; missing file means an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION:
        raise ValueError(f"{path}: not a dslint baseline (expected "
                         f"version={BASELINE_VERSION})")
    counts: Dict[str, int] = {}
    for entry in data.get("findings", []):
        counts[entry["fingerprint"]] = counts.get(entry["fingerprint"], 0) + \
            int(entry.get("count", 1))
    return counts


def load_baseline_entries(path: str) -> List[dict]:
    """Raw baseline entries (for merging on partial updates); [] when absent."""
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        data = json.load(fh)
    return list(data.get("findings", []))


def save_baseline(path: str, findings: List[Finding],
                  preserve_entries: List[dict] = ()) -> None:
    """Write the baseline from ``findings``; ``preserve_entries`` carries
    forward raw entries from a previous baseline (files OUTSIDE the scope of a
    partial run) so a subset update never deletes other files' entries."""
    counts = Counter(f.fingerprint for f in findings)
    by_fp = {}
    for f in sorted(findings, key=Finding.sort_key):
        by_fp.setdefault(f.fingerprint, f)
    entries = [{"fingerprint": fp,
                "rule": by_fp[fp].rule,
                "path": by_fp[fp].path,
                "snippet": by_fp[fp].snippet,
                "count": counts[fp]}
               for fp in sorted(counts, key=lambda fp: by_fp[fp].sort_key())]
    merged = sorted(list(preserve_entries) + entries,
                    key=lambda e: (e.get("path", ""), e.get("rule", ""),
                                   e.get("fingerprint", "")))
    with open(path, "w") as fh:
        json.dump({"version": BASELINE_VERSION, "findings": merged}, fh, indent=1)
        fh.write("\n")


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, int]) -> Tuple[List[Finding], List[Finding]]:
    """Split into (new, baselined).  Counts matter: a fingerprint allowed twice
    suppresses at most two occurrences."""
    remaining = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in sorted(findings, key=Finding.sort_key):
        if remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
