"""External-API-surface extraction + the committed manifest.

``jax-api-surface`` drift-proofs the WHOLE external jax surface, not just the
symbols that already burned us: every ``jax.*`` symbol the package touches —
import or attribute chain — is extracted here and pinned in a committed
manifest (``.dslint-api-surface.json``).  A symbol in the tree that the
manifest doesn't pin is a per-call-site lint finding, so the next upstream
rename/removal surfaces as ONE reviewable manifest diff
(``bin/dstpu-lint --update-api-surface``) instead of a scatter of red tests.

Extraction is alias-aware and purely syntactic (no imports — the analyzer
must keep working when jax itself is broken):

- ``import jax.numpy as jnp`` + ``jnp.mean(...)``       → ``jax.numpy.mean``
- ``from jax import lax`` + ``lax.cond(...)``           → ``jax.lax.cond``
- ``from jax.sharding import NamedSharding``            → ``jax.sharding.NamedSharding``
- ``from jax.experimental import multihost_utils``      → pins the module path
- attribute chains report only their LONGEST spelling (``jax.random.split``,
  not also ``jax.random``), so one call site is one symbol.
"""

import ast
import json
import os
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .context import ModuleInfo, annotate_parents, parent

MANIFEST_VERSION = 1
DEFAULT_MANIFEST_NAME = ".dslint-api-surface.json"
# only files under the package pin surface: tests exercise jax freely and are
# covered by direct-shimmed-import instead
PACKAGE_PREFIX = "deepspeed_tpu/"

JAX_ROOTS = frozenset({"jax"})


def _tracked(mod_name: str, roots: Iterable[str]) -> bool:
    return any(mod_name == r or mod_name.startswith(r + ".") for r in roots)


def symbol_sites(module: ModuleInfo,
                 roots: Iterable[str] = JAX_ROOTS) -> Iterator[Tuple[str, ast.AST]]:
    """Every (fully-qualified symbol, AST node) the module touches under the
    given root modules.  Yields import statements AND the longest attribute
    chain at each use site."""
    tree = module.tree
    annotate_parents(tree)  # idempotent; callers outside ProjectContext need it
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if not _tracked(alias.name, roots):
                    continue
                if alias.asname:
                    aliases[alias.asname] = alias.name
                else:
                    top = alias.name.split(".")[0]
                    aliases.setdefault(top, top)
                yield alias.name, node
        elif isinstance(node, ast.ImportFrom):
            if node.level or not node.module or not _tracked(node.module, roots):
                continue
            for alias in node.names:
                full = f"{node.module}.{alias.name}"
                aliases[alias.asname or alias.name] = full
                yield full, node
    for node in ast.walk(tree):
        if not isinstance(node, ast.Attribute):
            continue
        up = parent(node)
        if isinstance(up, ast.Attribute) and up.value is node:
            continue  # an inner link of a longer chain — report the chain once
        chain: List[str] = []
        cur: ast.AST = node
        while isinstance(cur, ast.Attribute):
            chain.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name) or cur.id not in aliases:
            continue
        yield ".".join([aliases[cur.id]] + chain[::-1]), node


def collect_api_surface(modules: Iterable[ModuleInfo]) -> Set[str]:
    """The package's full jax surface (files under ``deepspeed_tpu/`` only)."""
    surface: Set[str] = set()
    for mod in modules:
        if not mod.relpath.startswith(PACKAGE_PREFIX):
            continue
        surface.update(sym for sym, _ in symbol_sites(mod))
    return surface


def load_api_surface(path: str) -> Optional[Set[str]]:
    """Pinned symbols; None when the manifest has never been generated."""
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or data.get("version") != MANIFEST_VERSION:
        raise ValueError(f"{path}: not a dslint api-surface manifest "
                         f"(expected version={MANIFEST_VERSION})")
    return set(data.get("symbols", []))


def save_api_surface(path: str, symbols: Set[str]) -> None:
    with open(path, "w") as fh:
        json.dump({"version": MANIFEST_VERSION, "symbols": sorted(symbols)},
                  fh, indent=1)
        fh.write("\n")
