"""Developer tooling that ships with the library (static analysis, etc.)."""
