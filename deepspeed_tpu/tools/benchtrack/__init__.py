"""benchtrack — the BENCH_*.json trajectory as a regression gate (ISSUE 16).

The repo commits one ``BENCH_rNN.json`` per release round: a command-wrapper
record ``{n, cmd, rc, tail, parsed}`` whose ``tail`` holds the bench run's
final output (when the run finished, a metrics JSON; when it timed out, log
lines).  Until now that trajectory was a hand-read artifact; benchtrack turns
it into a gate: ``bin/dstpu-benchdiff`` diffs two bench records (or a fresh
run against the committed trajectory) under the per-metric direction +
tolerance policy committed in ``benchtrack.json`` and exits 1 on regression.

Pure stdlib, and scanned by dslint's ``host-sync-in-hot-path`` whole-file
zero-device-sync contract: a bench diff must be runnable on any host (CI
included) without touching an accelerator.
"""

from .diffcore import (VERDICT_IMPROVEMENT, VERDICT_MISSING, VERDICT_REGRESSION,
                       VERDICT_WITHIN_BAND, diff_metrics, extract_metrics,
                       load_bench, load_policy)

__all__ = ["VERDICT_IMPROVEMENT", "VERDICT_MISSING", "VERDICT_REGRESSION",
           "VERDICT_WITHIN_BAND", "diff_metrics", "extract_metrics",
           "load_bench", "load_policy"]
