"""dstpu-benchdiff CLI: diff two bench records under the committed policy.

Exit codes: 0 — no regression (improvements / within-band / missing are all
fine); 1 — at least one policy metric regressed past its tolerance band;
2 — usage/load error (unreadable record, malformed policy).
"""

import argparse
import json
import os
import sys
from typing import List, Optional

from .diffcore import (VERDICT_IMPROVEMENT, VERDICT_MISSING, VERDICT_REGRESSION,
                       diff_metrics, load_bench, load_policy)

_VERDICT_GLYPH = {VERDICT_REGRESSION: "✗", VERDICT_IMPROVEMENT: "✓",
                  VERDICT_MISSING: "·"}


def _find_policy(explicit: Optional[str], base_path: str) -> str:
    """Policy resolution: --policy wins; else benchtrack.json next to the
    base record, else in the cwd."""
    if explicit:
        return explicit
    for candidate in (os.path.join(os.path.dirname(os.path.abspath(base_path)),
                                   "benchtrack.json"),
                      "benchtrack.json"):
        if os.path.exists(candidate):
            return candidate
    raise FileNotFoundError(
        "no benchtrack.json found next to the base record or in the cwd "
        "(pass --policy explicitly)")


def _fmt(value) -> str:
    if value is None:
        return "-"
    return f"{value:.4g}"


def render_text(rows: List[dict], base: dict, cand: dict) -> str:
    lines = [f"benchdiff: {base['path']} (rc={base['rc']}) -> "
             f"{cand['path']} (rc={cand['rc']})"]
    w = max((len(r["metric"]) for r in rows), default=6)
    for r in rows:
        glyph = _VERDICT_GLYPH.get(r["verdict"], " ")
        pct = r.get("pct_change")
        pct_s = f"{pct:+7.2f}%" if pct is not None else "       -"
        note = f"  ({r['note']})" if r.get("note") else ""
        lines.append(f"  {glyph} {r['metric']:<{w}}  {_fmt(r['base']):>10} -> "
                     f"{_fmt(r['candidate']):>10}  {pct_s}  "
                     f"[{r['direction']} ±{r['tolerance_pct']:g}%]  "
                     f"{r['verdict']}{note}")
    counts = {}
    for r in rows:
        counts[r["verdict"]] = counts.get(r["verdict"], 0) + 1
    summary = ", ".join(f"{n} {v}" for v, n in sorted(counts.items()))
    lines.append(f"  -- {summary or 'no metrics judged'}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="dstpu-benchdiff",
        description="Diff two BENCH_*.json records (or a fresh bench run vs "
                    "the committed trajectory) under the benchtrack.json "
                    "direction+tolerance policy; exit 1 on regression.")
    parser.add_argument("base", help="baseline record (e.g. BENCH_r04.json)")
    parser.add_argument("candidate", help="candidate record (e.g. BENCH_r05.json)")
    parser.add_argument("--policy", default=None,
                        help="policy file (default: benchtrack.json next to "
                             "the base record, then ./benchtrack.json)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the verdict rows as JSON instead of text")
    args = parser.parse_args(argv)
    try:
        base = load_bench(args.base)
        cand = load_bench(args.candidate)
        policy = load_policy(_find_policy(args.policy, args.base))
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"dstpu-benchdiff: {e}", file=sys.stderr)
        return 2
    rows = diff_metrics(base["metrics"], cand["metrics"], policy)
    regressed = [r for r in rows if r["verdict"] == VERDICT_REGRESSION]
    if args.as_json:
        print(json.dumps({"base": base["path"], "candidate": cand["path"],
                          "rows": rows, "regressions": len(regressed),
                          "ok": not regressed}, indent=2))
    else:
        print(render_text(rows, base, cand))
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
