"""Bench-record loading + policy diff for dstpu-benchdiff.

Input shapes accepted by :func:`load_bench`, most-specific first:

- a plain metrics JSON object ``{"metric": value, ...}`` (a fresh ``bench.py``
  run piped to a file);
- the committed command-wrapper shape ``{n, cmd, rc, tail, parsed}``: when
  ``parsed`` is a dict it wins; otherwise numeric ``"key": value`` pairs are
  regex-extracted from ``tail`` (first occurrence wins — committed tails are
  front-truncated, so the surviving suffix is the most-final output).  A
  timed-out round (rc=124, log-only tail) legitimately yields ZERO metrics;
  every policy metric then reports ``missing``, which never fails the gate —
  a gap in the trajectory is a fact to display, not a regression.

The policy (``benchtrack.json``) declares, per metric, which direction is
good and how much movement is noise::

    {"default_tolerance_pct": 5.0,
     "metrics": {"serving_mixed_tok_s": {"direction": "higher",
                                         "tolerance_pct": 10.0}, ...}}

Only metrics named in the policy are judged: bench emits dozens of
context numbers (params_m, bench_elapsed_s) that must not gate anything.
"""

import json
import math
import re
from typing import Any, Dict, List, Optional

VERDICT_REGRESSION = "regression"
VERDICT_IMPROVEMENT = "improvement"
VERDICT_WITHIN_BAND = "within-band"
VERDICT_MISSING = "missing"

# "key": <number> — int/float/scientific; booleans and strings are not
# judgeable metrics and are skipped by extraction
_METRIC_RE = re.compile(r'"([A-Za-z0-9_]+)"\s*:\s*'
                        r'(-?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)(?=[,}\s])')


def extract_metrics(text: str) -> Dict[str, float]:
    """Numeric ``"key": value`` pairs from (possibly truncated) JSON text,
    first occurrence winning."""
    out: Dict[str, float] = {}
    for key, value in _METRIC_RE.findall(text or ""):
        if key not in out:
            out[key] = float(value)
    return out


def load_bench(path: str) -> Dict[str, Any]:
    """Load one bench record; returns {path, rc, metrics}."""
    with open(path) as fh:
        data = json.load(fh)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: expected a JSON object, got {type(data).__name__}")
    rc = data.get("rc")
    if "tail" in data or "parsed" in data:  # committed command-wrapper shape
        parsed = data.get("parsed")
        if isinstance(parsed, dict):
            metrics = {k: float(v) for k, v in _flatten(parsed).items()
                       if isinstance(v, (int, float)) and not isinstance(v, bool)
                       and math.isfinite(float(v))}
        else:
            metrics = extract_metrics(data.get("tail") or "")
    else:  # plain metrics JSON (a fresh bench run)
        metrics = {k: float(v) for k, v in _flatten(data).items()
                   if isinstance(v, (int, float)) and not isinstance(v, bool)
                   and math.isfinite(float(v))}
        rc = rc if isinstance(rc, int) else 0
    return {"path": path, "rc": rc, "metrics": metrics}


def _flatten(obj: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    """One-level-deep flatten: bench.py nests sections ({"serving": {...}});
    leaf keys are unique across sections so the bare name stays the policy
    spelling, with the prefixed spelling available for disambiguation."""
    out: Dict[str, Any] = {}
    for key, value in obj.items():
        if isinstance(value, dict):
            for k2, v2 in value.items():
                if not isinstance(v2, dict):
                    out.setdefault(k2, v2)
                    out[f"{key}.{k2}"] = v2
        else:
            out.setdefault(key, value)
    return out


def load_policy(path: str) -> Dict[str, Any]:
    with open(path) as fh:
        policy = json.load(fh)
    metrics = policy.get("metrics")
    if not isinstance(metrics, dict) or not metrics:
        raise ValueError(f"{path}: policy needs a non-empty 'metrics' object")
    for name, spec in metrics.items():
        direction = spec.get("direction")
        if direction not in ("higher", "lower"):
            raise ValueError(f"{path}: metric {name}: direction must be "
                             f"'higher' or 'lower', got {direction!r}")
        tol = spec.get("tolerance_pct", policy.get("default_tolerance_pct", 5.0))
        if not isinstance(tol, (int, float)) or tol < 0:
            raise ValueError(f"{path}: metric {name}: tolerance_pct must be "
                             f">= 0, got {tol!r}")
    return policy


def diff_metrics(base: Dict[str, float], cand: Dict[str, float],
                 policy: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Judge every policy metric; returns one row per metric, policy order.

    ``pct_change`` is signed toward the metric's GOOD direction (positive =
    better), so a single ``< -tolerance`` test spells regression for both
    higher-is-better and lower-is-better metrics.
    """
    default_tol = float(policy.get("default_tolerance_pct", 5.0))
    rows: List[Dict[str, Any]] = []
    for name, spec in policy["metrics"].items():
        tol = float(spec.get("tolerance_pct", default_tol))
        b, c = base.get(name), cand.get(name)
        row: Dict[str, Any] = {"metric": name, "direction": spec["direction"],
                               "tolerance_pct": tol, "base": b, "candidate": c}
        if b is None or c is None:
            row["verdict"] = VERDICT_MISSING
            row["note"] = ("absent from both" if b is None and c is None else
                           "absent from base" if b is None else
                           "absent from candidate")
        elif b == 0.0:
            # no baseline magnitude to take a percentage of: judge by sign
            # of movement toward the good direction, any movement is reported
            good = (c - b) if spec["direction"] == "higher" else (b - c)
            row["pct_change"] = None
            row["verdict"] = (VERDICT_WITHIN_BAND if good == 0.0 else
                              VERDICT_IMPROVEMENT if good > 0.0 else
                              VERDICT_REGRESSION)
        else:
            pct = (c - b) / abs(b) * 100.0
            if spec["direction"] == "lower":
                pct = -pct
            row["pct_change"] = pct
            row["verdict"] = (VERDICT_REGRESSION if pct < -tol else
                              VERDICT_IMPROVEMENT if pct > tol else
                              VERDICT_WITHIN_BAND)
        rows.append(row)
    return rows
