"""Ulysses sequence parallelism.

Analog of deepspeed/sequence/layer.py (``single_all_to_all:15``, ``_SeqAllToAll:44``,
``DistributedAttention:60``): inputs arrive sequence-sharded [B, S/P, H, D]; an
all-to-all swaps the shard dim so each rank holds the FULL sequence for H/P heads;
any local attention runs; a reverse all-to-all restores sequence sharding.  Comm
volume per link is O(S/P) vs O(S) for Megatron-style SP (blog analysis
blogs/deepspeed-ulysses/README.md:100-130).

Two TPU-native forms are provided:

- ``ulysses_attention`` — GSPMD form: sharding *constraints* around a local
  attention; XLA lowers the resharding to ICI all-to-alls.  Use inside pjit-ted
  models (this is what models.* wire in via ``attention_fn``).
- ``DistributedAttention`` — explicit shard_map form with ``lax.all_to_all``,
  mirroring the reference module for users composing their own shard_map programs.
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from ..parallel.mesh import SEQUENCE_AXIS, MeshTopology, get_topology
from ..utils.logging import logger


def single_all_to_all(x, scatter_idx: int, gather_idx: int, axis_name: str = SEQUENCE_AXIS):
    """In-graph all-to-all (reference single_all_to_all, sequence/layer.py:15):
    scatter local dim ``scatter_idx`` across the axis, gather the axis into dim
    ``gather_idx``.  Call under shard_map."""
    return lax.all_to_all(x, axis_name, split_axis=scatter_idx, concat_axis=gather_idx, tiled=True)


def ulysses_attention(local_attn: Optional[Callable] = None,
                      topo: Optional[MeshTopology] = None,
                      seq_axis: str = SEQUENCE_AXIS):
    """Wrap a local attention fn (q,k,v:[B,S,H,D] -> [B,S,H,D]) with Ulysses
    head-scatter/seq-gather resharding, expressed as GSPMD constraints.

    Returns an ``attention_fn(q, k, v, causal=..., mask=...)`` drop-in for
    models.transformer.attention_block.  Outside a mesh with a >1 'sequence'
    axis it degrades to the plain local attention.
    """
    from ..models.transformer import sdpa
    attn = local_attn or sdpa

    def attention_fn(q, k, v, causal=True, mask=None, **kw):
        t = topo or get_topology()
        if t.axis_size(seq_axis) <= 1:
            return attn(q, k, v, causal=causal, mask=mask, **kw)
        mesh = t.mesh
        # [B, S(sharded), H, D] -> [B, S, H(sharded), D]: all-to-all via resharding
        head_sharded = NamedSharding(mesh, PartitionSpec(None, None, seq_axis, None))
        seq_sharded = NamedSharding(mesh, PartitionSpec(None, seq_axis, None, None))
        q2 = lax.with_sharding_constraint(q, head_sharded)
        k2 = lax.with_sharding_constraint(k, head_sharded)
        v2 = lax.with_sharding_constraint(v, head_sharded)
        out = attn(q2, k2, v2, causal=causal, mask=mask, **kw)
        return lax.with_sharding_constraint(out, seq_sharded)

    return attention_fn


class DistributedAttention:
    """Explicit shard_map form (reference DistributedAttention, sequence/layer.py:60).

    __call__(q, k, v) with locally-sharded [B, s/P, H, D] blocks inside a
    shard_map over ``seq_axis``; runs all-to-all (heads scattered, seq gathered),
    the local attention on [B, S, H/P, D], and the reverse all-to-all.
    """

    def __init__(self, local_attention: Callable, seq_axis: str = SEQUENCE_AXIS,
                 scatter_idx: int = 2, gather_idx: int = 1):
        self.local_attn = local_attention
        self.seq_axis = seq_axis
        self.scatter_idx = scatter_idx  # heads dim
        self.gather_idx = gather_idx  # seq dim

    def __call__(self, query, key, value, *args, **kwargs):
        a2a = functools.partial(single_all_to_all, scatter_idx=self.scatter_idx,
                                gather_idx=self.gather_idx, axis_name=self.seq_axis)
        q = a2a(query)
        k = a2a(key)
        v = a2a(value)
        context = self.local_attn(q, k, v, *args, **kwargs)
        # reverse: scatter seq, gather heads
        return single_all_to_all(context, scatter_idx=self.gather_idx, gather_idx=self.scatter_idx,
                                 axis_name=self.seq_axis)
