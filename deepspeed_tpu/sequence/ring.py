"""Ring attention — blockwise context parallelism over the sequence axis.

Beyond-reference long-context capability (the reference snapshot has NO ring/
blockwise CP — SURVEY §5.7; long context = Ulysses only): q/k/v stay sequence-
sharded [B, s/P, H, D]; K/V blocks rotate around the ring (``lax.ppermute`` →
ICI neighbor exchange) while each rank accumulates blockwise online-softmax
attention of its local queries — memory O(s/P) per chip, comm O(s/P) per link
per step, fully overlapped by XLA with the block matmuls.

Comm volume matches Ulysses per link but removes the all-to-all's full-mesh
traffic pattern (pure neighbor exchange — ideal for TPU ICI rings), and scales
to sequence lengths where even one rank's full-sequence heads (Ulysses) no
longer fit.  Composes with GQA (kv heads broadcast locally).
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from ..parallel.mesh import SEQUENCE_AXIS, MeshTopology, get_topology

NEG_INF = -1e30


def _ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                          softmax_scale: Optional[float] = None):
    """Runs INSIDE shard_map. q/k/v: local [B, s, H, D] shards (kv heads may be
    fewer — GQA).  Returns local [B, s, H, D] output shard."""
    P = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, hq, d = q.shape
    hk = k.shape[2]
    if hk != hq:
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(d)

    qf = q.astype(jnp.float32).transpose(0, 2, 1, 3)  # [B, H, s, D]
    acc = jnp.zeros((b, hq, s, d), jnp.float32)
    m = jnp.full((b, hq, s, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hq, s, 1), jnp.float32)

    perm = [(r, (r + 1) % P) for r in range(P)]
    k_cur, v_cur = k, v
    qpos = my * s + jnp.arange(s)  # global query positions

    for step in range(P):
        src = (my - step) % P  # which global block k_cur holds
        kf = k_cur.astype(jnp.float32).transpose(0, 2, 1, 3)
        vf = v_cur.astype(jnp.float32).transpose(0, 2, 1, 3)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
        if causal:
            kpos = src * s + jnp.arange(s)
            mask = kpos[None, :] <= qpos[:, None]  # [s, s]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhqk,bhkd->bhqd", p, vf)
        m = m_new
        if step < P - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    l_safe = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (causal prefix)
    out = (acc / l_safe).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(local_attn_unused: Optional[Callable] = None,
                   topo: Optional[MeshTopology] = None,
                   seq_axis: str = SEQUENCE_AXIS):
    """attention_fn factory: drop-in for models.transformer.attention_block.

    Inputs arrive sequence-sharded by GSPMD ([B, S, H, D] global view); the
    wrapper shard_maps the ring over the 'sequence' mesh axis.  Degrades to
    plain sdpa when the axis is 1."""
    from ..models.transformer import sdpa

    def attention_fn(q, k, v, causal=True, mask=None, **kw):
        t = topo or get_topology()
        P = t.axis_size(seq_axis)
        if P <= 1 or mask is not None:
            return sdpa(q, k, v, causal=causal, mask=mask, **kw)
        body = functools.partial(_ring_attention_local, axis_name=seq_axis, causal=causal,
                                 softmax_scale=kw.get("softmax_scale"))
        spec = PartitionSpec(None, seq_axis, None, None)
        return jax.shard_map(body, mesh=t.mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    return attention_fn
