"""Ring attention — blockwise context parallelism over the sequence axis.

Beyond-reference long-context capability (the reference snapshot has NO ring/
blockwise CP — SURVEY §5.7; long context = Ulysses only): q/k/v stay sequence-
sharded [B, s/P, H, D]; K/V blocks rotate around the ring (``lax.ppermute`` →
ICI neighbor exchange) while each rank merges per-block attention via saved
logsumexp — memory O(s/P) per chip, comm O(s/P) per link per step, overlapped
by XLA with the block matmuls.

v2 (round 4, VERDICT r3 #5):
- GQA runs grouped (q reshaped [B, s, hk, g, d]) — no ``jnp.repeat`` KV
  materialization.
- The inner block attention is a chunked online-softmax scan with
  flash-equivalent O(s·chunk) live memory, differentiable end-to-end (see
  _block_attention for why a raw pallas_call fwd can't be the default here).
- Causal rings skip fully-masked steps: at step j only ranks my >= j compute
  (``lax.cond`` on the block source), so aggregate FLOPs drop ~2x; the
  ppermute still runs every step (it's the collective schedule).
- Per-block (out, lse) pairs merge in the numerically-stable weighted form,
  so the inner attention can be ANY kernel that returns logsumexp.

Comm volume matches Ulysses per link but removes the all-to-all's full-mesh
traffic pattern (pure neighbor exchange — ideal for TPU ICI rings), and scales
to sequence lengths where even one rank's full-sequence heads (Ulysses) no
longer fit: Ulysses activations scale O(S·H/P·D) per chip, ring O(S/P·H·D).
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from ..parallel.mesh import SEQUENCE_AXIS, MeshTopology, get_topology

NEG_INF = -1e30


def _block_attention(q, k, v, causal: bool, scale: float, chunk: int = 1024):
    """One block-pair attention returning (out [B,s,hq,d] fp32 — normalized
    within the block, lse [B,s,hq,1] fp32).

    Flash-equivalent memory in pure XLA: an online-softmax ``lax.scan`` over
    K-chunks keeps live scores at O(s·chunk) instead of O(s²) — so the ring's
    per-chip activation memory really is O(s/P·chunk), and the whole ring
    stays differentiable (a raw pallas_call fwd would not be; the chunk body
    is ``jax.checkpoint``ed so the backward recomputes per chunk rather than
    saving every chunk's probabilities).  GQA stays grouped (q reshaped to
    [B,s,hk,g,d]) — no repeated-KV materialization.  A fused Pallas ring
    kernel (block compute + ppermute in one kernel) is the remaining perf
    lever; this form already MXU-tiles via the chunk matmuls."""
    b, s, hq, d = q.shape
    hk = k.shape[2]
    g = hq // hk
    C = min(chunk, s)
    n_chunks = -(-s // C)
    pad = n_chunks * C - s
    qf = q.astype(jnp.float32).reshape(b, s, hk, g, d)
    kf = jnp.pad(k.astype(jnp.float32), ((0, 0), (0, pad), (0, 0), (0, 0)))
    vf = jnp.pad(v.astype(jnp.float32), ((0, 0), (0, pad), (0, 0), (0, 0)))
    kc = kf.reshape(b, n_chunks, C, hk, d).transpose(1, 0, 2, 3, 4)  # [n, b, C, hk, d]
    vc = vf.reshape(b, n_chunks, C, hk, d).transpose(1, 0, 2, 3, 4)
    qpos = jnp.arange(s)

    def body(carry, inp):
        acc, l, m = carry
        k_blk, v_blk, c_idx = inp
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_blk) * scale  # [b,hk,g,s,C]
        kpos = c_idx * C + jnp.arange(C)
        live = kpos[None, :] < s  # pad keys masked
        if causal:  # same-block diagonal: local positions align
            live = jnp.logical_and(live, kpos[None, :] <= qpos[:, None])
        scores = jnp.where(live[None, None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhgqk,bkhd->bhgqd", p, v_blk)
        return (acc, l, m_new), None

    acc0 = jnp.zeros((b, hk, g, s, d), jnp.float32)
    l0 = jnp.zeros((b, hk, g, s, 1), jnp.float32)
    m0 = jnp.full((b, hk, g, s, 1), NEG_INF, jnp.float32)
    (acc, l, m), _ = lax.scan(jax.checkpoint(body), (acc0, l0, m0),
                              (kc, vc, jnp.arange(n_chunks)))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l_safe).transpose(0, 3, 1, 2, 4).reshape(b, s, hq, d)
    lse = (m + jnp.log(l_safe)).transpose(0, 3, 1, 2, 4).reshape(b, s, hq, 1)
    return o, lse


def _ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                          softmax_scale: Optional[float] = None):
    """Runs INSIDE shard_map. q/k/v: local [B, s, H, D] shards (kv heads may be
    fewer — GQA).  Returns local [B, s, H, D] output shard."""
    P = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, hq, d = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(d)

    acc = jnp.zeros((b, s, hq, d), jnp.float32)
    den = jnp.zeros((b, s, hq, 1), jnp.float32)
    m_run = jnp.full((b, s, hq, 1), NEG_INF, jnp.float32)

    perm = [(r, (r + 1) % P) for r in range(P)]
    k_cur, v_cur = k, v

    for step in range(P):
        src = (my - step) % P  # which global block k_cur holds

        def merge(carry, k_blk, v_blk, blk_causal):
            acc, den, m_run = carry
            o, lse = _block_attention(q, k_blk, v_blk, blk_causal, scale)
            m_new = jnp.maximum(m_run, lse)
            w_old = jnp.exp(m_run - m_new)
            w_blk = jnp.exp(lse - m_new)
            return (acc * w_old + o * w_blk, den * w_old + w_blk, m_new)

        if not causal:
            acc, den, m_run = merge((acc, den, m_run), k_cur, v_cur, False)
        elif step == 0:
            # diagonal block: always live, causally masked within the block
            acc, den, m_run = merge((acc, den, m_run), k_cur, v_cur, True)
        else:
            # block src is fully BELOW the causal line iff src < my (compute
            # unmasked); fully above iff src > my (skip — this is the ~2x
            # aggregate FLOPs saving for causal rings).  src == my only at
            # step 0.  lax.cond keeps the skip a runtime branch per rank.
            acc, den, m_run = lax.cond(
                src < my,
                lambda c, kb, vb: merge(c, kb, vb, False),
                lambda c, kb, vb: c,
                (acc, den, m_run), k_cur, v_cur)
        if step < P - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.where(den == 0.0, 1.0, den)
    return out.astype(q.dtype)


def ring_attention(local_attn_unused: Optional[Callable] = None,
                   topo: Optional[MeshTopology] = None,
                   seq_axis: str = SEQUENCE_AXIS):
    """attention_fn factory: drop-in for models.transformer.attention_block.

    Inputs arrive sequence-sharded by GSPMD ([B, S, H, D] global view); the
    wrapper shard_maps the ring over the 'sequence' mesh axis.  Degrades to
    plain sdpa when the axis is 1."""
    from ..models.transformer import sdpa

    def attention_fn(q, k, v, causal=True, mask=None, **kw):
        t = topo or get_topology()
        P = t.axis_size(seq_axis)
        if P <= 1 or mask is not None:
            return sdpa(q, k, v, causal=causal, mask=mask, **kw)
        body = functools.partial(_ring_attention_local, axis_name=seq_axis, causal=causal,
                                 softmax_scale=kw.get("softmax_scale"))
        spec = PartitionSpec(None, seq_axis, None, None)
        return jax.shard_map(body, mesh=t.mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    return attention_fn
