"""Ring attention — blockwise context parallelism over the sequence axis.

Beyond-reference long-context capability (the reference snapshot has NO ring/
blockwise CP — SURVEY §5.7; long context = Ulysses only): q/k/v stay sequence-
sharded [B, s/P, H, D]; K/V blocks rotate around the ring (``lax.ppermute`` →
ICI neighbor exchange) while each rank merges per-block attention via saved
logsumexp — memory O(s/P) per chip, comm O(s/P) per link per step, overlapped
by XLA with the block matmuls.

v2 (round 4, VERDICT r3 #5):
- GQA runs grouped (q reshaped [B, s, hk, g, d]) — no ``jnp.repeat`` KV
  materialization.
- Causal rings skip fully-masked steps via ``lax.cond`` (aggregate FLOPs
  ~2x down — kept as the odd-local-seq fallback).
- Per-block (out, lse) pairs merge in the numerically-stable weighted form,
  so the inner attention can be ANY kernel that returns logsumexp.

v3 (round 5, VERDICT r4 #3):
- The inner block attention IS the Pallas flash kernel on TPU
  (flash_attention_with_lse): fused fwd, and a backward whose lse cotangent
  folds into the existing delta term — ring gradients run at flash-kernel
  speed.  The chunked online-softmax scan remains the CPU/parity fallback.
- Causal rings use the ZIGZAG layout (rank r holds global half-chunks
  (r, 2P-1-r)): v2's cond-skip saved aggregate FLOPs but rank P-1 still
  computed every step, so wall-clock didn't move; zigzag gives every rank
  the same s x s/2 live area per step — causal wall-clock ~halves.

Comm volume matches Ulysses per link but removes the all-to-all's full-mesh
traffic pattern (pure neighbor exchange — ideal for TPU ICI rings), and scales
to sequence lengths where even one rank's full-sequence heads (Ulysses) no
longer fit: Ulysses activations scale O(S·H/P·D) per chip, ring O(S/P·H·D).
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec

from ..compat import axis_size, shard_map
from ..parallel.mesh import SEQUENCE_AXIS, MeshTopology, get_topology

NEG_INF = -1e30


def _block_attention(q, k, v, causal: bool, scale: float, chunk: int = 1024):
    """One block-pair attention returning (out [B,sq,hq,d] fp32 — normalized
    within the block, lse [B,sq,hq,1] fp32).  Supports sq != sk with the
    flash-kernel convention: queries sit at the END of the key sequence
    (causal offset sk - sq) — the zigzag schedule's high-chunk diagonal.

    On TPU this IS the Pallas flash kernel (ops/attention/flash.py
    flash_attention_with_lse — VERDICT r4 #3: the ring's inner loop fused;
    the lse cotangent folds into the kernel's delta term so the ring stays
    differentiable end-to-end at kernel speed).  Off-TPU the chunked
    online-softmax ``lax.scan`` below is the numerically-identical fallback:
    O(s·chunk) live memory, ``jax.checkpoint``ed chunk body, grouped GQA (no
    repeated-KV materialization)."""
    from ..ops import _pallas as _p
    b, sq_len, hq, d = q.shape
    sk_len = k.shape[1]
    if _p.use_pallas():
        from ..ops.attention.flash import flash_attention_with_lse
        o, lse = flash_attention_with_lse(q, k, v, causal=causal, softmax_scale=scale)
        return (o.astype(jnp.float32),
                lse.transpose(0, 2, 1)[..., None].astype(jnp.float32))
    s, hk = sq_len, k.shape[2]
    g = hq // hk
    C = min(chunk, sk_len)
    n_chunks = -(-sk_len // C)
    pad = n_chunks * C - sk_len
    qf = q.astype(jnp.float32).reshape(b, s, hk, g, d)
    kf = jnp.pad(k.astype(jnp.float32), ((0, 0), (0, pad), (0, 0), (0, 0)))
    vf = jnp.pad(v.astype(jnp.float32), ((0, 0), (0, pad), (0, 0), (0, 0)))
    kc = kf.reshape(b, n_chunks, C, hk, d).transpose(1, 0, 2, 3, 4)  # [n, b, C, hk, d]
    vc = vf.reshape(b, n_chunks, C, hk, d).transpose(1, 0, 2, 3, 4)
    qpos = jnp.arange(s) + (sk_len - s)  # absolute positions in the key frame

    def body(carry, inp):
        acc, l, m = carry
        k_blk, v_blk, c_idx = inp
        scores = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_blk) * scale  # [b,hk,g,s,C]
        kpos = c_idx * C + jnp.arange(C)
        live = kpos[None, :] < sk_len  # pad keys masked
        if causal:
            live = jnp.logical_and(live, kpos[None, :] <= qpos[:, None])
        scores = jnp.where(live[None, None, None], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(scores - m_new)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.einsum("bhgqk,bkhd->bhgqd", p, v_blk)
        return (acc, l, m_new), None

    acc0 = jnp.zeros((b, hk, g, s, d), jnp.float32)
    l0 = jnp.zeros((b, hk, g, s, 1), jnp.float32)
    m0 = jnp.full((b, hk, g, s, 1), NEG_INF, jnp.float32)
    (acc, l, m), _ = lax.scan(jax.checkpoint(body), (acc0, l0, m0),
                              (kc, vc, jnp.arange(n_chunks)))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l_safe).transpose(0, 3, 1, 2, 4).reshape(b, s, hq, d)
    lse = (m + jnp.log(l_safe)).transpose(0, 3, 1, 2, 4).reshape(b, s, hq, 1)
    return o, lse


def _merge_block(acc, den, m_run, o, lse):
    """Numerically-stable online-softmax merge of one (normalized out, lse)
    block pair into the running accumulators — shared by every ring schedule
    so the NEG_INF/underflow handling lives in exactly one place."""
    m_new = jnp.maximum(m_run, lse)
    w_old = jnp.exp(m_run - m_new)
    w_blk = jnp.exp(lse - m_new)
    return acc * w_old + o * w_blk, den * w_old + w_blk, m_new


def _ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                          softmax_scale: Optional[float] = None):
    """Runs INSIDE shard_map. q/k/v: local [B, s, H, D] shards (kv heads may be
    fewer — GQA).  Returns local [B, s, H, D] output shard."""
    P = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, hq, d = q.shape
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(d)

    acc = jnp.zeros((b, s, hq, d), jnp.float32)
    den = jnp.zeros((b, s, hq, 1), jnp.float32)
    m_run = jnp.full((b, s, hq, 1), NEG_INF, jnp.float32)

    perm = [(r, (r + 1) % P) for r in range(P)]
    k_cur, v_cur = k, v

    for step in range(P):
        src = (my - step) % P  # which global block k_cur holds

        def merge(carry, k_blk, v_blk, blk_causal):
            acc, den, m_run = carry
            o, lse = _block_attention(q, k_blk, v_blk, blk_causal, scale)
            return _merge_block(acc, den, m_run, o, lse)

        if not causal:
            acc, den, m_run = merge((acc, den, m_run), k_cur, v_cur, False)
        elif step == 0:
            # diagonal block: always live, causally masked within the block
            acc, den, m_run = merge((acc, den, m_run), k_cur, v_cur, True)
        else:
            # block src is fully BELOW the causal line iff src < my (compute
            # unmasked); fully above iff src > my (skip — this is the ~2x
            # aggregate FLOPs saving for causal rings).  src == my only at
            # step 0.  lax.cond keeps the skip a runtime branch per rank.
            acc, den, m_run = lax.cond(
                src < my,
                lambda c, kb, vb: merge(c, kb, vb, False),
                lambda c, kb, vb: c,
                (acc, den, m_run), k_cur, v_cur)
        if step < P - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.where(den == 0.0, 1.0, den)
    return out.astype(q.dtype)


def _zigzag_perms(P: int):
    """Half-chunk re-layout permutations.  Global half-chunks 0..2P-1 live
    contiguously (rank r holds 2r, 2r+1); zigzag wants rank r to hold
    (r, 2P-1-r).  Two ppermutes do it: the lo-perm routes chunk 2r, the
    hi-perm routes chunk 2r+1, each to the rank that owns it in zigzag."""
    def dest(c: int) -> int:
        return c if c < P else 2 * P - 1 - c

    perm_lo = [(r, dest(2 * r)) for r in range(P)]
    perm_hi = [(r, dest(2 * r + 1)) for r in range(P)]
    return perm_lo, perm_hi


def _ring_attention_zigzag(q, k, v, axis_name: str,
                           softmax_scale: Optional[float] = None):
    """Causal ring with the ZIGZAG layout (VERDICT r4 #3: stop paying wire and
    wall-clock for skipped causal steps).

    v2's cond-skip saved AGGREGATE FLOPs but not wall-clock: with contiguous
    blocks, rank P-1 computes at every step, so the ring's critical path is
    still P full block-pairs.  Zigzag re-layouts each rank to hold global
    half-chunks (r, 2P-1-r): at every rotation step each rank finds exactly
    ONE causally-live half-chunk pairing per received block — either its full
    local queries against the received low half (src < my) or its high
    queries against the full received block (src > my) — so every rank does
    the same s x s/2 work each step and causal wall-clock is ~half of the
    non-causal ring.  Comm per step is unchanged (the full local KV rotates
    once forward, as in v2); re-layout costs 3 half-chunk ppermute pairs in
    (q, k, v) plus one inverse for the output — amortized over P-1 steps.

    Requires even local seq; callers fall back to the v2 cond-skip path
    otherwise."""
    P = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, hq, d = q.shape
    half = s // 2
    scale = softmax_scale if softmax_scale is not None else 1.0 / np.sqrt(d)
    perm_lo, perm_hi = _zigzag_perms(P)
    even = (my % 2) == 0

    def relayout(x):
        a = lax.ppermute(x[:, :half], axis_name, perm_lo)
        c = lax.ppermute(x[:, half:], axis_name, perm_hi)
        # chunk parity: even ranks get their zigzag-lo via the lo-perm,
        # odd ranks via the hi-perm (see _zigzag_perms wiring)
        return jnp.where(even, a, c), jnp.where(even, c, a)

    q_lo, q_hi = relayout(q)
    k_lo, k_hi = relayout(k)
    v_lo, v_hi = relayout(v)
    qz = jnp.concatenate([q_lo, q_hi], axis=1)

    # ---- step 0 (diagonal): q_lo sees its own chunk causally; q_hi sees the
    # low chunk fully + its own chunk causally — one offset-causal call
    o1, l1 = _block_attention(q_lo, k_lo, v_lo, True, scale)
    k_cur = jnp.concatenate([k_lo, k_hi], axis=1)
    v_cur = jnp.concatenate([v_lo, v_hi], axis=1)
    o2, l2 = _block_attention(q_hi, k_cur, v_cur, True, scale)  # offset = half
    acc = jnp.concatenate([o1, o2], axis=1)
    den = jnp.ones((b, s, hq, 1), jnp.float32)
    m_run = jnp.concatenate([l1, l2], axis=1)

    perm = [(r, (r + 1) % P) for r in range(P)]
    zeros_lo = jnp.zeros((b, half, hq, d), jnp.float32)
    ninf_lo = jnp.full((b, half, hq, 1), NEG_INF, jnp.float32)

    for step in range(1, P):
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        src = (my - step) % P  # zigzag-lo chunk id of the received block

        def low_branch(kb, vb):
            # received low chunk (global id src < my): visible to ALL local
            # queries (q_lo chunk my > src, q_hi chunk 2P-1-my > src)
            o, l = _block_attention(qz, kb[:, :half], vb[:, :half], False, scale)
            return o, l

        def high_branch(kb, vb):
            # src > my: only q_hi (chunk 2P-1-my) sees the received block —
            # and it sees BOTH halves (src < P <= 2P-1-my, and
            # 2P-1-src < 2P-1-my); q_lo rows stay empty (lse = -inf)
            o, l = _block_attention(q_hi, kb, vb, False, scale)
            return (jnp.concatenate([zeros_lo, o], axis=1),
                    jnp.concatenate([ninf_lo, l], axis=1))

        o_blk, lse_blk = lax.cond(src < my, low_branch, high_branch, k_cur, v_cur)
        acc, den, m_run = _merge_block(acc, den, m_run, o_blk, lse_blk)

    out = (acc / jnp.where(den == 0.0, 1.0, den)).astype(q.dtype)
    # ---- inverse re-layout: zigzag (my, 2P-1-my) back to contiguous (2r, 2r+1)
    inv_lo = [(d_, s_) for (s_, d_) in perm_lo]
    inv_hi = [(d_, s_) for (s_, d_) in perm_hi]
    z_lo, z_hi = out[:, :half], out[:, half:]
    # a rank's zigzag-lo returns via the inverse of whichever perm delivered it
    a = lax.ppermute(jnp.where(even, z_lo, z_hi), axis_name, inv_lo)
    c = lax.ppermute(jnp.where(even, z_hi, z_lo), axis_name, inv_hi)
    return jnp.concatenate([a, c], axis=1)


def ring_attention(local_attn_unused: Optional[Callable] = None,
                   topo: Optional[MeshTopology] = None,
                   seq_axis: str = SEQUENCE_AXIS):
    """attention_fn factory: drop-in for models.transformer.attention_block.

    Inputs arrive sequence-sharded by GSPMD ([B, S, H, D] global view); the
    wrapper shard_maps the ring over the 'sequence' mesh axis.  Degrades to
    plain sdpa when the axis is 1."""
    from ..models.transformer import sdpa

    def attention_fn(q, k, v, causal=True, mask=None, **kw):
        t = topo or get_topology()
        P = t.axis_size(seq_axis)
        if P <= 1 or mask is not None:
            return sdpa(q, k, v, causal=causal, mask=mask, **kw)
        s_local = q.shape[1] // P
        if causal and s_local % 2 == 0:
            # zigzag: balanced causal schedule — every rank computes the same
            # s x s/2 area per step, halving causal ring wall-clock
            body = functools.partial(_ring_attention_zigzag, axis_name=seq_axis,
                                     softmax_scale=kw.get("softmax_scale"))
        else:
            body = functools.partial(_ring_attention_local, axis_name=seq_axis,
                                     causal=causal,
                                     softmax_scale=kw.get("softmax_scale"))
        spec = PartitionSpec(None, seq_axis, None, None)
        return shard_map(body, mesh=t.mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    return attention_fn
