from .layer import DistributedAttention, single_all_to_all, ulysses_attention
from .ring import ring_attention
