"""Cluster launcher CLI.

Analog of the reference launcher (deepspeed/launcher/runner.py:main:388 +
launch.py, bin/deepspeed): parses a hostfile, filters resources with
--include/--exclude, encodes the world info, and launches the training script.

TPU-native topology: ONE process per HOST (the JAX runtime owns all local
chips — unlike the reference's one-process-per-GPU fork), with
``jax.distributed.initialize`` coordinated through env vars
(COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID).  Multi-node runners mirror the
reference's MultiNodeRunner hierarchy (multinode_runner.py:18-375) with pdsh
and ssh backends; single-node just execs locally.
"""

import argparse
import base64
import json
import os
import shutil
import subprocess
import sys
from typing import Dict, List, Optional

from ..utils.logging import logger

DEFAULT_SSH_PORT = 22


def fetch_hostfile(path: str) -> Dict[str, int]:
    """Parse 'hostname slots=N' lines (reference runner.fetch_hostfile:200)."""
    if not os.path.isfile(path):
        raise FileNotFoundError(f"hostfile {path} not found")
    resources: Dict[str, int] = {}
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    try:
                        slots = int(p[len("slots="):])
                    except ValueError:
                        raise ValueError(f"{path}:{lineno}: bad slots in {line!r}")
            if host in resources:
                raise ValueError(f"{path}:{lineno}: duplicate host {host}")
            resources[host] = slots
    if not resources:
        raise ValueError(f"hostfile {path} is empty")
    return resources


def parse_inclusion_exclusion(resources: Dict[str, int], include: str = "",
                              exclude: str = "") -> Dict[str, int]:
    """--include/--exclude 'host1@host2:0,2' filtering (reference :255).

    For TPU hosts the per-host slot selection selects CHIP COUNT, not device
    ids (the JAX runtime claims local chips as one process)."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")

    def parse_spec(spec: str) -> Dict[str, Optional[List[int]]]:
        out: Dict[str, Optional[List[int]]] = {}
        for part in spec.split("@"):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                host, ids = part.split(":")
                out[host] = [int(i) for i in ids.split(",")]
            else:
                out[part] = None
        return out

    if include:
        spec = parse_spec(include)
        filtered = {}
        for host, ids in spec.items():
            if host not in resources:
                raise ValueError(f"--include host {host} not in hostfile")
            filtered[host] = len(ids) if ids is not None else resources[host]
        return filtered
    if exclude:
        spec = parse_spec(exclude)
        filtered = dict(resources)
        for host, ids in spec.items():
            if host not in filtered:
                raise ValueError(f"--exclude host {host} not in hostfile")
            if ids is None:
                del filtered[host]
            else:
                filtered[host] = max(0, filtered[host] - len(ids))
        return {h: s for h, s in filtered.items() if s > 0}
    return dict(resources)


def encode_world_info(resources: Dict[str, int]) -> str:
    """base64 world info env payload (reference runner.py:353)."""
    return base64.urlsafe_b64encode(json.dumps(resources).encode()).decode()


def decode_world_info(encoded: str) -> Dict[str, int]:
    return json.loads(base64.urlsafe_b64decode(encoded.encode()).decode())


class MultiNodeRunner:
    """Base remote runner (reference multinode_runner.py:18)."""
    name = "base"
    # env for the spawned launcher process; None = inherit os.environ.  Set by
    # runners whose transport can't inline every variable (Slurm comma values).
    spawn_env: Optional[Dict[str, str]] = None

    def __init__(self, args, world_info: Dict[str, int]):
        self.args = args
        self.world_info = world_info

    def backend_exists(self) -> bool:
        raise NotImplementedError

    def get_cmd(self, environment: Dict[str, str], active_resources: Dict[str, int]) -> List[str]:
        raise NotImplementedError

    @property
    def user_arguments(self) -> List[str]:
        return [self.args.user_script] + list(self.args.user_args)


class PDSHRunner(MultiNodeRunner):
    """pdsh fan-out (reference multinode_runner.py:51)."""
    name = "pdsh"

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        env_exports = [f"export {k}={v};" for k, v in sorted(environment.items())]
        hosts = ",".join(active_resources.keys())
        remote_cmd = " ".join(env_exports + [sys.executable, "-u", "-m",
                                             "deepspeed_tpu.launcher.launch"] + self.user_arguments)
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, remote_cmd]


class SSHRunner(MultiNodeRunner):
    """Plain-ssh loop fallback when pdsh is absent."""
    name = "ssh"

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmds(self, environment, active_resources):
        cmds = []
        for rank, host in enumerate(active_resources):
            env = dict(environment, PROCESS_ID=str(rank))
            exports = [f"export {k}={v};" for k, v in sorted(env.items())]
            remote = " ".join(exports + [sys.executable, "-u", "-m",
                                         "deepspeed_tpu.launcher.launch"] + self.user_arguments)
            cmds.append(["ssh", "-o", "StrictHostKeyChecking=no", host, remote])
        return cmds

    def get_cmd(self, environment, active_resources):
        return self.get_cmds(environment, active_resources)[0]


class OpenMPIRunner(MultiNodeRunner):
    """mpirun (Open MPI dialect) — reference multinode_runner.py:117.  One
    rank per host; env rides ``-x`` exports; JAX's coordinator address comes
    from the same payload the other runners use."""
    name = "openmpi"

    def backend_exists(self) -> bool:
        return shutil.which("ompi_info") is not None and shutil.which("mpirun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        exports = []
        for k, v in sorted(environment.items()):
            exports += ["-x", f"{k}={v}"]
        hosts = ",".join(active_resources.keys())
        cmd = ["mpirun", "-n", str(total), "--host", hosts, "--mca", "btl", "^openib"]
        iface = getattr(self.args, "mpi_interface", "")
        if iface:  # only pin the NIC when the user names one (eth0 is not universal)
            cmd += ["--mca", "btl_tcp_if_include", iface]
        return (cmd + exports
                + [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch"]
                + self.user_arguments)


class MPICHRunner(MultiNodeRunner):
    """mpirun (MPICH dialect, ``-genv`` exports) — reference :170."""
    name = "mpich"

    def backend_exists(self) -> bool:
        # Open MPI also installs an 'mpirun'; require an MPICH marker so the
        # MPICH-only flags (-hosts/-genv) don't fail cryptically at runtime
        if shutil.which("mpiexec.hydra") is not None:
            return True
        mpirun = shutil.which("mpirun")
        if mpirun is None:
            return False
        try:
            out = subprocess.run([mpirun, "--version"], capture_output=True,
                                 text=True, timeout=5).stdout
        except Exception:
            return False
        return "mpich" in out.lower() or "hydra" in out.lower()

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        exports = []
        for k, v in sorted(environment.items()):
            exports += ["-genv", k, str(v)]
        hosts = ",".join(active_resources.keys())
        return (["mpirun", "-n", str(total), "-hosts", hosts] + exports
                + [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch"]
                + self.user_arguments)


class SlurmRunner(MultiNodeRunner):
    """srun allocation launch — reference :327.  Env propagates via
    ``--export`` (Slurm forwards it to every task)."""
    name = "slurm"

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        total = len(active_resources)
        # --export splits on commas with no escape syntax: a comma-containing
        # value inlined as K=V would silently corrupt every later pair.  Those
        # values ride the parent environment instead — srun forwards it under
        # the leading ALL (the launcher spawns srun with os.environ inherited).
        inline, via_parent = {}, {}
        for k, v in sorted(environment.items()):
            (via_parent if "," in str(v) else inline)[k] = str(v)
        self.spawn_env = {**os.environ, **via_parent} if via_parent else None
        exports = "ALL" + "".join(f",{k}={v}" for k, v in inline.items())
        cmd = ["srun", "-n", str(total)]
        if active_resources:
            cmd += ["-w", ",".join(active_resources.keys())]
        if getattr(self.args, "slurm_comment", ""):
            cmd += ["--comment", self.args.slurm_comment]
        cmd += [f"--export={exports}", sys.executable, "-u", "-m",
                "deepspeed_tpu.launcher.launch"] + self.user_arguments
        return cmd


class MVAPICHRunner(MultiNodeRunner):
    """mpirun_rsh (MVAPICH2) — reference :375; env as KEY=VALUE operands.
    mpirun_rsh wants a bare-hostname file (no ``slots=N`` tokens), so the
    runner writes one from the already include/exclude-filtered resources —
    the reference writes /tmp/mvapich_hostfile the same way (:392)."""
    name = "mvapich"

    def backend_exists(self) -> bool:
        return shutil.which("mpirun_rsh") is not None

    def get_cmd(self, environment, active_resources):
        import tempfile
        total = len(active_resources)
        fh = tempfile.NamedTemporaryFile("w", prefix="dstpu_mvapich_hosts_",
                                         suffix=".txt", delete=False)
        fh.write("\n".join(active_resources.keys()) + "\n")
        fh.close()
        import atexit
        atexit.register(lambda p=fh.name: os.path.exists(p) and os.unlink(p))
        env_kv = [f"{k}={v}" for k, v in sorted(environment.items())]
        return (["mpirun_rsh", "-np", str(total), "-hostfile", fh.name]
                + env_kv + [sys.executable, "-u", "-m", "deepspeed_tpu.launcher.launch"]
                + self.user_arguments)


RUNNER_CLASSES = {cls.name: cls for cls in
                  (PDSHRunner, SSHRunner, OpenMPIRunner, MPICHRunner,
                   SlurmRunner, MVAPICHRunner)}


def build_launch_env(resources: Dict[str, int], master_addr: str, master_port: int) -> Dict[str, str]:
    return {
        "DSTPU_WORLD_INFO": encode_world_info(resources),
        "COORDINATOR_ADDRESS": f"{master_addr}:{master_port}",
        "NUM_PROCESSES": str(len(resources)),
    }


def run_elastic(args) -> int:
    """Single-host elastic supervision (``--elastic N``): the agent spawns N
    copies of the user script, watches exit codes AND per-rank heartbeats,
    and on failure rescales to the next valid world size, pinning the new
    generation to the newest checkpoint tag valid across all of its ranks."""
    import tempfile

    from ..elasticity import DSElasticAgent

    elastic_config = None
    if args.ds_config:
        with open(args.ds_config) as fh:
            elastic_config = json.load(fh).get("elasticity")
    kwargs = {}
    if args.heartbeat_timeout is not None:
        kwargs.update(heartbeat_dir=tempfile.mkdtemp(prefix="dstpu_hb_"),
                      heartbeat_timeout_s=args.heartbeat_timeout)
    if args.collective_timeout is not None:
        kwargs.update(collective_timeout_s=args.collective_timeout)
    if args.ops_port is not None:
        # merged fleet /metrics + /healthz over the agent (workers publish
        # per-rank snapshots via the DSTPU_OPS_DIR export; monitor/ops_server)
        kwargs.update(ops_port=args.ops_port)
    agent = DSElasticAgent(
        [sys.executable, "-u", args.user_script] + list(args.user_args),
        world_size=args.elastic, elastic_config=elastic_config,
        max_restarts=args.max_restarts, checkpoint_dir=args.checkpoint_dir,
        per_rank_checkpoints=args.per_rank_checkpoints,
        verify_checkpoint_integrity=args.verify_checkpoint_integrity,
        **kwargs)
    logger.info(f"launching {args.elastic} workers under the elastic agent "
                f"(max_restarts={args.max_restarts})")
    rc = agent.run()
    hb_dir = kwargs.get("heartbeat_dir")
    if hb_dir:
        if rc == 0:
            shutil.rmtree(hb_dir, ignore_errors=True)  # don't leak /tmp stamps
        else:
            logger.warning(f"keeping heartbeat stamps for postmortem: {hb_dir}")
    return rc


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu launcher (reference bin/deepspeed)")
    parser.add_argument("-H", "--hostfile", default="/job/hostfile")
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=29500)
    parser.add_argument("--launcher", default="pdsh",
                        choices=("pdsh", "ssh", "local", "openmpi", "mpich", "slurm", "mvapich"))
    parser.add_argument("--slurm_comment", default="")
    parser.add_argument("--mpi_interface", default="",
                        help="NIC for Open MPI's TCP BTL (omit to let OMPI pick)")
    parser.add_argument("--force_multi", action="store_true")
    parser.add_argument("--elastic", type=int, default=0, metavar="N",
                        help="supervise N local worker processes under the elastic "
                             "agent (heartbeat liveness, hang detection, rescale + "
                             "checkpoint-pinned restart) instead of one exec")
    parser.add_argument("--max_restarts", type=int, default=3)
    parser.add_argument("--checkpoint_dir", default=None,
                        help="with --elastic: restart generations resume from the "
                             "newest tag valid across all ranks (DSTPU_RESUME_TAG)")
    parser.add_argument("--per_rank_checkpoints", action="store_true",
                        help="with --elastic: workers save node-locally to "
                             "<checkpoint_dir>/rank<R>/ — consensus must walk every "
                             "rank's dir (without this the walk sees the rank<R> "
                             "subdirs as invalid tags and pins nothing)")
    parser.add_argument("--verify_checkpoint_integrity", action="store_true",
                        help="with --elastic: consensus tag selection also CRC-checks "
                             "every rank's copy (a size-only check can pin a tag a "
                             "worker's own verify_integrity pass then rejects)")
    parser.add_argument("--heartbeat_timeout", type=float, default=None,
                        help="with --elastic: a rank whose heartbeat stamp is older "
                             "than this many seconds is treated as hung")
    parser.add_argument("--ops_port", type=int, default=None, metavar="PORT",
                        help="with --elastic: serve merged fleet metrics + health "
                             "on this port (Prometheus /metrics, JSON /healthz and "
                             "/statez; 0 picks an ephemeral port).  Workers publish "
                             "per-rank snapshots via the agent-exported "
                             "DSTPU_OPS_DIR; counters stay monotone across worker "
                             "restarts")
    parser.add_argument("--collective_timeout", type=float, default=None,
                        help="with --elastic: wall-clock bound (seconds) exported to "
                             "workers (DSTPU_COLLECTIVE_TIMEOUT_S) so a wedged host "
                             "collective raises CollectiveTimeoutError instead of "
                             "deadlocking the generation")
    parser.add_argument("--ds_config", default=None,
                        help="with --elastic: ds config JSON whose 'elasticity' "
                             "section constrains the valid world sizes")
    parser.add_argument("user_script")
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.elastic > 0:
        return run_elastic(args)

    # --launcher local always runs on this host, hostfile or not
    multi_node = (os.path.isfile(args.hostfile) or args.force_multi) and args.launcher != "local"
    if not multi_node:
        logger.info("launching locally (single host, all local chips)")
        cmd = [sys.executable, "-u", args.user_script] + list(args.user_args)
        return subprocess.call(cmd)

    resources = fetch_hostfile(args.hostfile)
    resources = parse_inclusion_exclusion(resources, args.include, args.exclude)
    master_addr = args.master_addr or next(iter(resources))
    env = build_launch_env(resources, master_addr, args.master_port)

    runner: MultiNodeRunner
    if args.launcher == "pdsh":
        runner = PDSHRunner(args, resources)
        if not runner.backend_exists():
            logger.warning("pdsh not found; falling back to ssh")
            runner = SSHRunner(args, resources)
    elif args.launcher in RUNNER_CLASSES and args.launcher != "ssh":
        runner = RUNNER_CLASSES[args.launcher](args, resources)
    else:
        runner = SSHRunner(args, resources)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend '{runner.name}' not available")

    if isinstance(runner, SSHRunner):
        procs = [subprocess.Popen(c) for c in runner.get_cmds(env, resources)]
        rc = 0
        for p in procs:
            rc |= p.wait()
        return rc
    cmd = runner.get_cmd(env, resources)
    logger.info(f"launching: {' '.join(cmd)}")
    return subprocess.call(cmd, env=runner.spawn_env)


if __name__ == "__main__":
    sys.exit(main())
