"""Per-node launch entry (reference launcher/launch.py:main:132).

The reference forks one process per local GPU with RANK/LOCAL_RANK env; on TPU
the JAX runtime owns all local chips from ONE process, so this entry resolves
the node's PROCESS_ID from the world info, exports the jax.distributed
coordination env, and execs the user script in-process.
"""

import os
import runpy
import socket
import sys

from ..utils.logging import logger
from .runner import decode_world_info


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        raise SystemExit("usage: python -m deepspeed_tpu.launcher.launch <script> [args...]")
    world = decode_world_info(os.environ.get("DSTPU_WORLD_INFO", "e30="))
    if "PROCESS_ID" not in os.environ and world:
        hostname = socket.gethostname()
        hosts = list(world)
        matches = [i for i, h in enumerate(hosts) if h in (hostname, hostname.split(".")[0])]
        if matches:
            os.environ["PROCESS_ID"] = str(matches[0])
        else:
            logger.warning(f"host {hostname} not in world info {hosts}; defaulting PROCESS_ID=0")
            os.environ.setdefault("PROCESS_ID", "0")
    script, args = argv[0], argv[1:]
    sys.argv = [script] + list(args)
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
