"""Launcher / cluster tooling (reference deepspeed/launcher/)."""
from .runner import (MultiNodeRunner, PDSHRunner, SSHRunner, encode_world_info, decode_world_info,
                     fetch_hostfile, parse_inclusion_exclusion)
