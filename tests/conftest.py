"""Test harness configuration.

Analog of the reference's distributed-test harness (tests/unit/common.py): the
reference launches N real ranks on one host; on TPU-native JAX we instead simulate
an 8-device mesh on CPU via XLA host-platform device partitioning — the pattern the
reference's accelerator-portable suite enables (tests/unit/common.py:111).

MUST run before any jax import, hence module-level env mutation in conftest.
"""

import os

# Force CPU for tests even when the session env preselects the TPU platform
# (JAX_PLATFORMS=axon); bench.py / production use the real chip.  sitecustomize
# may import jax before this file runs, so env alone isn't enough — backend init
# is lazy, so flipping jax.config before the first device query still works.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_global_topology():
    yield
    from deepspeed_tpu.parallel import reset_topology
    reset_topology()
    from deepspeed_tpu.models.transformer import set_default_attention
    set_default_attention(None)


@pytest.fixture
def mesh8():
    """An 8-device (data=8) topology."""
    from deepspeed_tpu.parallel import MeshTopology
    return MeshTopology.from_axis_dict({"data": 8})


@pytest.fixture
def mesh_2x4():
    from deepspeed_tpu.parallel import MeshTopology
    return MeshTopology.from_axis_dict({"data": 2, "tensor": 4})
