"""utils/wal.py tests: the CRC-framed WAL primitives shared by the
checkpoint layer (PR 2) and the serving request journal (PR 8) — frame
round-trips, torn-tail truncation, bit-flip rejection, and the
checkpointing aliases staying bound to the single implementation."""

import os

import pytest

from deepspeed_tpu.utils import wal


def _write(path, payloads):
    with open(path, "ab") as fh:
        for p in payloads:
            wal.append_frame(fh, p)


# ------------------------------------------------------------------- frames
def test_frame_roundtrip(tmp_path):
    path = str(tmp_path / "log.wal")
    payloads = [b'{"a":1}', b"", b"\x00\x01binary\xff", b"x" * 4096]
    _write(path, payloads)
    got, good, tail = wal.scan_frames(path)
    assert got == payloads
    assert tail is None
    assert good == os.path.getsize(path)


def test_missing_file_reads_empty():
    got, good, tail = wal.scan_frames("/nonexistent/definitely/not.wal")
    assert got == [] and good == 0 and tail is None


def test_torn_header_tail_detected_and_truncated(tmp_path):
    path = str(tmp_path / "log.wal")
    _write(path, [b"one", b"two"])
    clean_size = os.path.getsize(path)
    with open(path, "ab") as fh:
        fh.write(wal.FRAME_MAGIC + b"\x07")  # header fragment
    got, good, tail = wal.scan_frames(path)
    assert got == [b"one", b"two"] and good == clean_size
    assert tail is not None and "torn header" in tail
    assert wal.truncate_torn_tail(path) is not None
    assert os.path.getsize(path) == clean_size
    assert wal.truncate_torn_tail(path) is None  # already clean: no-op


def test_torn_payload_tail(tmp_path):
    path = str(tmp_path / "log.wal")
    _write(path, [b"one"])
    clean_size = os.path.getsize(path)
    frame = wal.encode_frame(b"payload-that-gets-cut")
    with open(path, "ab") as fh:
        fh.write(frame[:-5])  # payload never fully landed
    got, good, tail = wal.scan_frames(path)
    assert got == [b"one"] and good == clean_size
    assert "torn or corrupt frame" in tail


def test_bit_flip_invalidates_frame_and_tail(tmp_path):
    path = str(tmp_path / "log.wal")
    _write(path, [b"first", b"second", b"third"])
    data = open(path, "rb").read()
    # flip one payload byte of the SECOND frame: CRC must reject it, and the
    # third frame becomes unreachable (no reliable resync past a bad frame)
    second_start = len(wal.encode_frame(b"first"))
    flip = second_start + wal.HEADER_SIZE
    damaged = data[:flip] + bytes([data[flip] ^ 0x01]) + data[flip + 1:]
    with open(path, "wb") as fh:
        fh.write(damaged)
    got, good, tail = wal.scan_frames(path)
    assert got == [b"first"]
    assert good == second_start
    assert tail is not None


def test_append_after_truncation_extends_clean_prefix(tmp_path):
    path = str(tmp_path / "log.wal")
    _write(path, [b"keep"])
    with open(path, "ab") as fh:
        fh.write(b"garbage-not-a-frame")
    wal.truncate_torn_tail(path)
    _write(path, [b"appended"])
    got, _, tail = wal.scan_frames(path)
    assert got == [b"keep", b"appended"] and tail is None


def test_foreign_bytes_reported_as_bad_magic(tmp_path):
    path = str(tmp_path / "log.wal")
    with open(path, "wb") as fh:
        fh.write(b"this was never a WAL file at all")
    got, good, tail = wal.scan_frames(path)
    assert got == [] and good == 0 and "bad magic" in tail


# ------------------------------------------------------------ durable-IO kit
def test_atomic_write_text_replaces_whole_file(tmp_path):
    path = str(tmp_path / "latest")
    wal.atomic_write_text(path, "tag_a")
    wal.atomic_write_text(path, "tag_b")
    assert open(path).read() == "tag_b"
    assert not os.path.exists(path + ".tmp")


def test_file_crc32_matches_zlib(tmp_path):
    import zlib
    path = str(tmp_path / "blob")
    data = os.urandom(3000)
    with open(path, "wb") as fh:
        fh.write(data)
    assert wal.file_crc32(path, chunk=512) == zlib.crc32(data)


def test_checkpointing_aliases_are_the_shared_implementation():
    from deepspeed_tpu.runtime import checkpointing as ckpt
    assert ckpt._fsync_file is wal.fsync_file
    assert ckpt._fsync_dir is wal.fsync_dir
    assert ckpt._atomic_write_text is wal.atomic_write_text
    assert ckpt._file_crc32 is wal.file_crc32
