"""Comm facade tests — analog of tests/unit/comm/test_dist.py: real collectives
over an 8-device mesh (no mocks), numeric parity against local numpy."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.compat import shard_map
from deepspeed_tpu import comm
from deepspeed_tpu.parallel import MeshTopology
from deepspeed_tpu.utils.comms_logging import calc_bw_log, get_comms_logger


@pytest.fixture
def mesh(mesh8):
    return mesh8.mesh


def _shmap(mesh, fn, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec, check_vma=False)


def test_all_reduce_sum(mesh):
    x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
    f = _shmap(mesh, lambda v: comm.all_reduce(v, "data"), P("data"), P())
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.full((1, 1), x.sum()), rtol=1e-6)


def test_all_reduce_max(mesh):
    x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
    f = _shmap(mesh, lambda v: comm.all_reduce(v, "data", op="max"), P("data"), P())
    assert np.asarray(f(x)).item() == 7.0


def test_all_gather(mesh):
    x = np.arange(16.0, dtype=np.float32).reshape(8, 2)
    f = _shmap(mesh, lambda v: comm.all_gather(v, "data"), P("data"), P())
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, x)  # gather reassembles the full array


def test_reduce_scatter(mesh):
    # each device holds a full (8, 8) contribution; after reduce-scatter each
    # device keeps a 1-row shard of the sum across devices
    x = np.ones((8, 8), dtype=np.float32)
    f = _shmap(mesh, lambda v: comm.reduce_scatter(v, "data"), P(None, None), P("data", None))
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, np.full((8, 8), 8.0))


def test_all_to_all(mesh):
    # Ulysses layout swap: [seq_shard, heads] <-> [seq, head_shard]
    x = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    f = _shmap(mesh, lambda v: comm.all_to_all(v, "data", split_dim=1, concat_dim=0), P("data", None), P(None, "data"))
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out, x.reshape(8, 8))  # global value preserved, layout swapped


def test_ppermute_ring(mesh):
    x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
    perm = [(i, (i + 1) % 8) for i in range(8)]
    f = _shmap(mesh, lambda v: comm.ppermute(v, "data", perm), P("data"), P("data"))
    out = np.asarray(f(x)).ravel()
    np.testing.assert_array_equal(out, np.roll(np.arange(8.0), 1))


def test_broadcast(mesh):
    x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
    f = _shmap(mesh, lambda v: comm.broadcast(v, "data", src=3), P("data"), P("data"))
    out = np.asarray(f(x)).ravel()
    np.testing.assert_array_equal(out, np.full(8, 3.0))


def test_rank_and_world():
    assert comm.get_rank() == 0
    assert comm.get_world_size() == 1
    comm.barrier()  # must not hang single-host


def test_broadcast_inf_on_non_src_rank_does_not_poison(mesh):
    # fp16-overflow shape: a non-src rank holds inf; broadcast must still deliver
    # the src rank's value (select-based, not multiply-based masking)
    x = np.arange(8.0, dtype=np.float32).reshape(8, 1)
    x[5] = np.inf
    f = _shmap(mesh, lambda v: comm.broadcast(v, "data", src=3), P("data"), P("data"))
    out = np.asarray(f(x)).ravel()
    np.testing.assert_array_equal(out, np.full(8, 3.0))


def test_host_all_reduce_ops(mesh8):
    from deepspeed_tpu.parallel import set_topology
    set_topology(mesh8)
    x = jnp.asarray(np.array([[1.0], [0.0], [0.0], [5.0]]))
    assert float(comm.host_all_reduce(x, op="max")[0]) == 5.0
    assert float(comm.host_all_reduce(x, op="sum")[0]) == 6.0
    with pytest.raises(ValueError):
        comm.host_all_reduce(jnp.float32(1.0))
    with pytest.raises(ValueError):
        comm.host_all_reduce(x, op="xor")


def test_calc_bw_log_formulas():
    # 1 GB allreduce in 1s on 8 ranks: algbw = 8 Gbps, busbw = 8 * 2*(7/8) = 14 Gbps
    alg, bus = calc_bw_log("all_reduce", 10**9, 1.0, 8)
    assert abs(alg - 8.0) < 1e-6
    assert abs(bus - 14.0) < 1e-6
    alg, bus = calc_bw_log("all_gather", 10**9, 1.0, 8)
    assert abs(bus - 7.0) < 1e-6


def test_comms_logger_records(mesh8):
    cl = get_comms_logger()
    cl.enabled = True
    cl.comms_dict.clear()
    try:
        from deepspeed_tpu.parallel import set_topology
        set_topology(mesh8)
        x = jnp.ones((8, 4))
        comm.host_all_reduce(x)
        assert "all_reduce" in cl.comms_dict
        summary = cl.log_summary()
        assert "all_reduce" in summary
    finally:
        cl.enabled = False


def test_collective_bandwidth_microbench(mesh8):
    """ds_bench analog: the sweep runs real collectives over the 8-dev mesh and
    reports sane numbers (BASELINE.json tracks allgather bucket bandwidth)."""
    from deepspeed_tpu.comm.benchmark import collective_bandwidth, run_sweep
    r = collective_bandwidth("all_gather", elems=8 * 1024, axis="data",
                             topology=mesh8, iters=2)
    assert r["world"] == 8
    assert r["algbw_gbps"] > 0
    assert abs(r["busbw_gbps"] - r["algbw_gbps"] * 7 / 8) < 1e-9
    results = run_sweep(ops=("all_reduce", "reduce_scatter"), elems=8 * 1024,
                        topology=mesh8, iters=1)
    assert [r["op"] for r in results] == ["all_reduce", "reduce_scatter"]
    assert all(x["time_ms"] > 0 for x in results)


def test_allgather_bandwidth_microbench(mesh8):
    """Bandwidth measurement machinery (BASELINE.json allgather bucket
    bandwidth): both dispatch modes of collective_bandwidth produce finite
    busbw on the CPU mesh (numbers are meaningless here; shape is the test)."""
    from deepspeed_tpu.comm.benchmark import collective_bandwidth
    res = collective_bandwidth("all_gather", elems=1 << 14, dtype=jnp.float32,
                               topology=mesh8, iters=2)
    assert res["world"] == 8 and np.isfinite(res["busbw_gbps"]) and res["busbw_gbps"] > 0
    res2 = collective_bandwidth("all_gather", elems=1 << 14, dtype=jnp.float32,
                                topology=mesh8, iters=2, compiled_loop=True)
    assert np.isfinite(res2["busbw_gbps"]) and res2["busbw_gbps"] > 0
    assert res2["bytes"] == res["bytes"]


# ------------------------------------------------------------- process groups
def test_process_group_sizes_and_accessors(mesh_2x4):
    from deepspeed_tpu.comm import (ProcessGroup, get_data_parallel_group,
                                    get_model_parallel_group, get_world_group,
                                    get_rank, get_world_size, new_group)
    dp = get_data_parallel_group(mesh_2x4)
    tp = get_model_parallel_group(mesh_2x4)
    assert get_world_size(dp) == 2  # data=2, fsdp=1
    assert get_world_size(tp) == 4
    assert get_world_size(get_world_group(mesh_2x4)) == 8
    assert get_rank(dp) == 0  # single-process: first device sits at origin
    g = new_group(axes=("data", "tensor"), topology=mesh_2x4)
    assert g.size() == 8
    with pytest.raises(NotImplementedError, match="mesh axis"):
        new_group(ranks=[0, 1])
    with pytest.raises(ValueError):
        ProcessGroup("bogus", mesh_2x4)


def test_process_group_in_graph_collectives(mesh_2x4):
    """ProcessGroup passes straight into the collective wrappers in-graph,
    including multi-axis groups (psum over data x tensor)."""
    from deepspeed_tpu import comm
    from deepspeed_tpu.comm import ProcessGroup
    g_all = ProcessGroup(("data", "tensor"), mesh_2x4)
    g_tp = ProcessGroup("tensor", mesh_2x4)

    def fn(x):
        total = comm.all_reduce(x, g_all)            # sums over all 8 shards
        tp_ranks = comm.axis_index(g_tp).reshape(1, 1)
        return total, tp_ranks

    out, ranks = jax.jit(shard_map(fn, mesh=mesh_2x4.mesh,
                                   in_specs=P("data", "tensor"),
                                   out_specs=(P(), P("data", "tensor")),
                                   check_vma=False))(jnp.ones((2, 4)))
    np.testing.assert_array_equal(np.asarray(out), np.full((1, 1), 8.0))
    np.testing.assert_array_equal(np.asarray(ranks).ravel(), [0, 1, 2, 3, 0, 1, 2, 3])
