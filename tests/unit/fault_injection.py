"""Failpoint-style fault injection for checkpoint resilience tests.

The harness plays the role of a dying host / flaky filesystem at the
checkpoint-engine seam (every leaf write funnels through
``CheckpointEngine.save`` once streaming is disabled), plus post-hoc
corruption helpers for damage that happens AFTER a save completes (bit rot,
partial deletion).  Used by test_checkpoint_resilience.py and the
``make resilience-smoke`` CI target to prove the crash-safe save protocol:
a kill at any point never moves ``latest`` off the previous complete
checkpoint, and transient IO errors are absorbed by the retry loop.
"""

import io
import os

import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import NativeCheckpointEngine
from deepspeed_tpu.runtime.checkpointing import METADATA_FILE


class SimulatedCrash(BaseException):
    """Simulated process death mid-save.  Deliberately a BaseException: the
    save retry loop (and any ``except Exception`` cleanup) must NOT absorb it,
    exactly like a real SIGKILL wouldn't run those handlers."""


class FaultyCheckpointEngine(NativeCheckpointEngine):
    """A checkpoint engine that fails on command.

    ``kill_after_bytes``  — write leaf bytes until the budget runs out, leave
                            the current file truncated, then raise
                            :class:`SimulatedCrash` (preemption mid-save).
    ``kill_after_leaves`` — die cleanly between leaf N and N+1.
    ``transient_errors``  — raise ``OSError`` for the first N ``save()`` calls,
                            then behave normally (flaky NFS/GCS mount).
    ``corrupt_key``       — flip bytes in any leaf whose filename starts with
                            this key, keeping the byte size (only a CRC check
                            can catch it).
    """

    # force every leaf through save() so the failpoints always fire (the
    # streaming path writes via memmap and would bypass them)
    supports_streaming_save = False

    def __init__(self, kill_after_bytes=None, kill_after_leaves=None,
                 transient_errors=0, corrupt_key=None):
        self.kill_after_bytes = kill_after_bytes
        self.kill_after_leaves = kill_after_leaves
        self.transient_errors = int(transient_errors)
        self.corrupt_key = corrupt_key
        self.saves_completed = 0
        self.bytes_written = 0
        self.transients_raised = 0

    def save(self, arr: np.ndarray, path: str) -> None:
        if self.transients_raised < self.transient_errors:
            self.transients_raised += 1
            raise OSError(f"injected transient IO error "
                          f"#{self.transients_raised}/{self.transient_errors} ({path})")
        if (self.kill_after_leaves is not None
                and self.saves_completed >= self.kill_after_leaves):
            raise SimulatedCrash(f"killed save before leaf #{self.saves_completed + 1}")
        buf = io.BytesIO()
        np.save(buf, np.asarray(arr))
        data = buf.getvalue()
        if (self.kill_after_bytes is not None
                and self.bytes_written + len(data) > self.kill_after_bytes):
            budget = max(self.kill_after_bytes - self.bytes_written, 0)
            with open(path, "wb") as fh:
                fh.write(data[:budget])  # the truncated file a dying host leaves
            self.bytes_written += budget
            raise SimulatedCrash(f"killed save after {self.bytes_written} bytes "
                                 f"(mid-write of {os.path.basename(path)})")
        if self.corrupt_key and os.path.basename(path).startswith(self.corrupt_key):
            data = _flip_tail_bytes(data)
        with open(path, "wb") as fh:
            fh.write(data)
        self.saves_completed += 1
        self.bytes_written += len(data)


def _flip_tail_bytes(data: bytes, n: int = 4) -> bytes:
    """Invert the last ``n`` bytes (payload, not the .npy header) — same size,
    different content."""
    tail = bytes(b ^ 0xFF for b in data[-n:])
    return data[:-n] + tail


# -------------------------------------------------- post-hoc corruption helpers
def corrupt_leaf(ckpt_dir: str, key: str, n: int = 4) -> str:
    """Flip payload bytes of ``<ckpt_dir>/<key>.npy`` in place, preserving the
    file size (detectable only via CRC32 verification)."""
    path = os.path.join(ckpt_dir, key + ".npy")
    with open(path, "rb") as fh:
        data = fh.read()
    with open(path, "wb") as fh:
        fh.write(_flip_tail_bytes(data, n))
    return path


def truncate_leaf(ckpt_dir: str, key: str, keep_bytes: int = 64) -> str:
    """Truncate ``<ckpt_dir>/<key>.npy`` to ``keep_bytes`` (size-check
    detectable)."""
    path = os.path.join(ckpt_dir, key + ".npy")
    os.truncate(path, keep_bytes)
    return path


def drop_metadata(ckpt_dir: str) -> str:
    """Delete ``metadata.json`` from a finalized tag (external damage; a crash
    can no longer produce this state since the rename is atomic)."""
    path = os.path.join(ckpt_dir, METADATA_FILE)
    os.remove(path)
    return path
