"""Pipeline-parallel tests — analog of tests/unit/runtime/pipe/: pipelined
forward/backward must match the plain layer stack numerically, and training
must work end-to-end over a pipe mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel import MeshTopology, set_topology
from deepspeed_tpu.runtime.pipe.module import PipelineModule, partition_layers, pipe_rules, restack_for_pipeline

HIDDEN = 16
LAYERS = 8


def _layer_fn(lp, x):
    return jnp.tanh(x @ lp["w"] + lp["b"])


def _init_layers(key, layers=LAYERS, hidden=HIDDEN):
    ks = jax.random.split(key, layers)
    return {
        "w": jnp.stack([jax.random.normal(k, (hidden, hidden)) * 0.5 for k in ks]),
        "b": jnp.zeros((layers, hidden)),
    }


def _reference_forward(layer_params, x):
    def body(h, lp):
        return _layer_fn(lp, h), None
    out, _ = jax.lax.scan(body, x, layer_params)
    return out


def test_partition_layers():
    assert partition_layers(8, 4) == 2
    with pytest.raises(ValueError):
        partition_layers(7, 4)


def test_restack():
    params = _init_layers(jax.random.PRNGKey(0))
    stacked = restack_for_pipeline(params, 4)
    assert stacked["w"].shape == (4, 2, HIDDEN, HIDDEN)


def test_pipeline_forward_matches_plain():
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(0))
    stacked = restack_for_pipeline(params, 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    M, mb = 8, 4
    x = jnp.asarray(np.random.default_rng(0).normal(size=(M, mb, HIDDEN)).astype(np.float32))
    out = jax.jit(lambda p, v: pipe(p, v))(stacked, x)
    expected = jax.vmap(lambda v: _reference_forward(params, v))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_pipeline_backward_matches_plain():
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(1))
    stacked = restack_for_pipeline(params, 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 2, HIDDEN)).astype(np.float32))

    def loss_pipe(p):
        return jnp.mean(pipe(p, x)**2)

    def loss_plain(p):
        flat = jax.tree_util.tree_map(lambda l: l.reshape(-1, *l.shape[2:]), p)
        return jnp.mean(jax.vmap(lambda v: _reference_forward(flat, v))(x)**2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_plain = jax.jit(jax.grad(loss_plain))(stacked)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]), np.asarray(g_plain["w"]), rtol=1e-4, atol=1e-6)


def test_pipeline_single_stage_degenerates():
    topo = MeshTopology.from_axis_dict({"data": 8})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(0))
    stacked = restack_for_pipeline(params, 1)
    pipe = PipelineModule(_layer_fn, num_stages=1, topo=topo)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, HIDDEN)).astype(np.float32))
    out = pipe(stacked, x)
    expected = jax.vmap(lambda v: _reference_forward(params, v))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5)


def test_pipeline_requires_enough_microbatches():
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    stacked = restack_for_pipeline(_init_layers(jax.random.PRNGKey(0)), 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    x = jnp.zeros((2, 2, HIDDEN))  # only 2 micro-batches for 4 stages
    with pytest.raises(ValueError):
        pipe(stacked, x)


def test_pipeline_training_with_engine():
    """Pipelined model trains through the full engine (pipe x data mesh,
    pipe-sharded params via pipe_rules)."""
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)

    params = {"pipe_layers": restack_for_pipeline(_init_layers(jax.random.PRNGKey(0)), 4),
              "head": jnp.zeros((HIDDEN, HIDDEN))}

    def loss_fn(p, batch, rng):
        x = batch["x"]
        xm = x.reshape(4, x.shape[0] // 4, HIDDEN)  # [M, mb, H] pipeline micro-batches
        out = pipe(p["pipe_layers"], xm).reshape(x.shape)
        pred = out @ p["head"].astype(out.dtype)
        return jnp.mean((pred - batch["y"].astype(pred.dtype))**2).astype(jnp.float32)

    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=loss_fn,
        model_parameters=params,
        topology=topo,
        tp_rules=pipe_rules,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": False},
        })
    # params sharded over pipe on dim0
    w = engine.state.params["pipe_layers"]["w"]
    assert "pipe" in str(w.sharding.spec), w.sharding.spec

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(HIDDEN, HIDDEN)).astype(np.float32) * 0.2

    losses = []
    for s in range(6):
        x = rng.normal(size=(engine.train_batch_size, HIDDEN)).astype(np.float32)
        y = np.tanh(x @ w_true)
        m = engine.train_batch({"x": x, "y": y})
        losses.append(float(m.loss))
    assert losses[-1] < losses[0], losses
