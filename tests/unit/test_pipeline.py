"""Pipeline-parallel tests — analog of tests/unit/runtime/pipe/: pipelined
forward/backward must match the plain layer stack numerically, and training
must work end-to-end over a pipe mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel import MeshTopology, set_topology
from deepspeed_tpu.runtime.pipe.module import PipelineModule, partition_layers, pipe_rules, restack_for_pipeline

HIDDEN = 16
LAYERS = 8


def _layer_fn(lp, x):
    return jnp.tanh(x @ lp["w"] + lp["b"])


def _init_layers(key, layers=LAYERS, hidden=HIDDEN):
    ks = jax.random.split(key, layers)
    return {
        "w": jnp.stack([jax.random.normal(k, (hidden, hidden)) * 0.5 for k in ks]),
        "b": jnp.zeros((layers, hidden)),
    }


def _reference_forward(layer_params, x):
    def body(h, lp):
        return _layer_fn(lp, h), None
    out, _ = jax.lax.scan(body, x, layer_params)
    return out


def test_partition_layers():
    assert partition_layers(8, 4) == 2
    with pytest.raises(ValueError):
        partition_layers(7, 4)


def test_restack():
    params = _init_layers(jax.random.PRNGKey(0))
    stacked = restack_for_pipeline(params, 4)
    assert stacked["w"].shape == (4, 2, HIDDEN, HIDDEN)


def test_pipeline_forward_matches_plain():
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(0))
    stacked = restack_for_pipeline(params, 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    M, mb = 8, 4
    x = jnp.asarray(np.random.default_rng(0).normal(size=(M, mb, HIDDEN)).astype(np.float32))
    out = jax.jit(lambda p, v: pipe(p, v))(stacked, x)
    expected = jax.vmap(lambda v: _reference_forward(params, v))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-6)


def test_pipeline_backward_matches_plain():
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(1))
    stacked = restack_for_pipeline(params, 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 2, HIDDEN)).astype(np.float32))

    def loss_pipe(p):
        return jnp.mean(pipe(p, x)**2)

    def loss_plain(p):
        flat = jax.tree_util.tree_map(lambda l: l.reshape(-1, *l.shape[2:]), p)
        return jnp.mean(jax.vmap(lambda v: _reference_forward(flat, v))(x)**2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_plain = jax.jit(jax.grad(loss_plain))(stacked)
    np.testing.assert_allclose(np.asarray(g_pipe["w"]), np.asarray(g_plain["w"]), rtol=1e-4, atol=1e-6)


def test_pipeline_single_stage_degenerates():
    topo = MeshTopology.from_axis_dict({"data": 8})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(0))
    stacked = restack_for_pipeline(params, 1)
    pipe = PipelineModule(_layer_fn, num_stages=1, topo=topo)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4, HIDDEN)).astype(np.float32))
    out = pipe(stacked, x)
    expected = jax.vmap(lambda v: _reference_forward(params, v))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5)


def test_pipeline_requires_enough_microbatches():
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    stacked = restack_for_pipeline(_init_layers(jax.random.PRNGKey(0)), 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    x = jnp.zeros((2, 2, HIDDEN))  # only 2 micro-batches for 4 stages
    with pytest.raises(ValueError):
        pipe(stacked, x)


def test_pipeline_training_with_engine():
    """Pipelined model trains through the full engine (pipe x data mesh,
    pipe-sharded params via pipe_rules)."""
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)

    params = {"pipe_layers": restack_for_pipeline(_init_layers(jax.random.PRNGKey(0)), 4),
              "head": jnp.zeros((HIDDEN, HIDDEN))}

    def loss_fn(p, batch, rng):
        x = batch["x"]
        xm = x.reshape(4, x.shape[0] // 4, HIDDEN)  # [M, mb, H] pipeline micro-batches
        out = pipe(p["pipe_layers"], xm).reshape(x.shape)
        pred = out @ p["head"].astype(out.dtype)
        return jnp.mean((pred - batch["y"].astype(pred.dtype))**2).astype(jnp.float32)

    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=loss_fn,
        model_parameters=params,
        topology=topo,
        tp_rules=pipe_rules,
        config={
            "train_micro_batch_size_per_gpu": 8,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": False},
        })
    # params sharded over pipe on dim0
    w = engine.state.params["pipe_layers"]["w"]
    assert "pipe" in str(w.sharding.spec), w.sharding.spec

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(HIDDEN, HIDDEN)).astype(np.float32) * 0.2

    losses = []
    for s in range(6):
        x = rng.normal(size=(engine.train_batch_size, HIDDEN)).astype(np.float32)
        y = np.tanh(x @ w_true)
        m = engine.train_batch({"x": x, "y": y})
        losses.append(float(m.loss))
    assert losses[-1] < losses[0], losses


# ------------------------------------------------------------- 1F1B schedule
def test_train_schedule_completes_all_passes():
    from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                     TrainSchedule)
    M, S = 6, 4
    for s in range(S):
        cmds = [c for tick in TrainSchedule(M, S, s).steps() for c in tick]
        assert sum(isinstance(c, ForwardPass) for c in cmds) == M
        assert sum(isinstance(c, BackwardPass) for c in cmds) == M


def test_train_schedule_is_1f1b():
    """In-flight forwards (fwd issued minus bwd retired) never exceed the
    stage's pipe-buffer count — the 1F1B property that GPipe lacks."""
    from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                     TrainSchedule)
    M, S = 8, 4
    for s in range(S):
        sched = TrainSchedule(M, S, s)
        live = peak = 0
        for tick in sched.steps():
            for c in tick:
                if isinstance(c, ForwardPass):
                    live += 1
                elif isinstance(c, BackwardPass):
                    live -= 1
                peak = max(peak, live)
        assert peak <= sched.num_pipe_buffers()
        # last stage alternates F,B strictly: at most 1 in flight (buffers >= 2)
        if s == S - 1:
            assert peak == 1


def test_train_schedule_send_recv_pairing():
    """Every RecvActivation has a SendActivation one tick earlier upstream;
    every RecvGrad a SendGrad one tick earlier downstream."""
    from deepspeed_tpu.runtime.pipe.schedule import (RecvActivation, RecvGrad,
                                                     SendActivation, SendGrad,
                                                     TrainSchedule)
    M, S = 5, 3
    streams = [list(TrainSchedule(M, S, s).steps()) for s in range(S)]
    for s in range(S):
        for t, cmds in enumerate(streams[s]):
            for c in cmds:
                if isinstance(c, RecvActivation):
                    assert any(isinstance(p, SendActivation)
                               for p in streams[s - 1][t - 1])
                if isinstance(c, RecvGrad):
                    assert any(isinstance(p, SendGrad)
                               for p in streams[s + 1][t - 1])


def test_inference_schedule_forward_only():
    from deepspeed_tpu.runtime.pipe.schedule import (BackwardPass, ForwardPass,
                                                     InferenceSchedule)
    cmds = [c for tick in InferenceSchedule(4, 2, 0).steps() for c in tick]
    assert sum(isinstance(c, ForwardPass) for c in cmds) == 4
    assert not any(isinstance(c, BackwardPass) for c in cmds)


# ------------------------------------------------------- balanced partitioning
def test_partition_balanced_uniform():
    from deepspeed_tpu.runtime.pipe.module import partition_balanced
    assert partition_balanced([1.0] * 8, 4) == [0, 2, 4, 6, 8]


def test_partition_balanced_skewed():
    from deepspeed_tpu.runtime.pipe.module import partition_balanced
    # one huge layer: it gets its own stage, the rest spread out
    w = [10.0, 1, 1, 1, 1, 1]
    bounds = partition_balanced(w, 3)
    assert bounds[0] == 0 and bounds[-1] == 6
    loads = [sum(w[bounds[i]:bounds[i + 1]]) for i in range(3)]
    assert max(loads) == 10.0  # optimal bottleneck
    # every stage nonempty
    assert all(bounds[i + 1] > bounds[i] for i in range(3))


def test_partition_balanced_exact_stages():
    from deepspeed_tpu.runtime.pipe.module import partition_balanced
    bounds = partition_balanced([1.0, 1.0, 1.0], 3)
    assert bounds == [0, 1, 2, 3]
    with pytest.raises(ValueError):
        partition_balanced([1.0, 1.0], 3)


# --------------------------------------------------------------- layer specs
def test_layer_specs_tied_materialize_once():
    from deepspeed_tpu.runtime.pipe.module import LayerSpec, TiedLayerSpec, build_layer_specs

    def init(key, dim):
        return {"w": jax.random.normal(key, (dim, dim))}

    specs = [TiedLayerSpec("embed", init, dim=4), LayerSpec(init, dim=4),
             TiedLayerSpec("embed", init, dim=4)]
    layers, tied = build_layer_specs(specs, jax.random.PRNGKey(0))
    assert set(tied) == {"embed"}
    assert layers[0] == ("tied", "embed") and layers[2] == ("tied", "embed")
    assert layers[1][0] == "own"


# ------------------------------------------------------------ 1F1B engine
def _mk_stage_fns(S):
    def stage_fn(p, tied, x):
        return jnp.tanh(x @ p["w"] + p["b"])
    return [stage_fn] * S


def _mk_stage_params(key, S, hidden=HIDDEN):
    ks = jax.random.split(key, S)
    return [{"w": jax.random.normal(k, (hidden, hidden)) * 0.5,
             "b": jnp.zeros((hidden,))} for k in ks]


def test_1f1b_engine_matches_direct_grad():
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine1F1B
    S, M, mb = 3, 5, 4
    params = _mk_stage_params(jax.random.PRNGKey(1), S)
    rng = np.random.default_rng(2)
    mbs = [jnp.asarray(rng.normal(size=(mb, HIDDEN)).astype(np.float32)) for _ in range(M)]
    labels = [jnp.asarray(rng.normal(size=(mb, HIDDEN)).astype(np.float32)) for _ in range(M)]

    def loss_fn(out, label):
        return jnp.mean((out - label) ** 2)

    eng = PipelineEngine1F1B(_mk_stage_fns(S), loss_fn)
    loss, grads, tied_grads = eng.train_batch(params, mbs, labels)

    def direct(params):
        total = 0.0
        for x, y in zip(mbs, labels):
            for p in params:
                x = jnp.tanh(x @ p["w"] + p["b"])
            total = total + loss_fn(x, y)
        return total / M

    ref_loss = direct(params)
    ref_grads = jax.grad(direct)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for g, rg in zip(grads, ref_grads):
        for k in g:
            np.testing.assert_allclose(np.asarray(g[k]), np.asarray(rg[k]),
                                       rtol=1e-4, atol=1e-5)
    assert tied_grads is None
    # the 1F1B bound actually bit: stage 0 held <= S buffers, last stage 1
    assert eng.max_live_buffers[0] <= S
    assert eng.max_live_buffers[-1] == 1


def test_1f1b_engine_tied_weight_grads():
    """Tied embedding used by first and last stage: gradient is the sum of
    both uses (reference allreduce_tied_weight_gradients, pipe/module.py:423)."""
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine1F1B
    S, M, mb, H = 2, 4, 2, HIDDEN
    rng = np.random.default_rng(3)
    tied = {"embed": jnp.asarray(rng.normal(size=(H, H)).astype(np.float32) * 0.3)}
    params = _mk_stage_params(jax.random.PRNGKey(4), S)

    def stage0(p, t, x):
        return jnp.tanh((x @ t["embed"]) @ p["w"] + p["b"])

    def stage1(p, t, x):
        h = jnp.tanh(x @ p["w"] + p["b"])
        return h @ t["embed"].T  # tied unembed

    mbs = [jnp.asarray(rng.normal(size=(mb, H)).astype(np.float32)) for _ in range(M)]
    labels = [jnp.asarray(rng.normal(size=(mb, H)).astype(np.float32)) for _ in range(M)]

    def loss_fn(out, label):
        return jnp.mean((out - label) ** 2)

    eng = PipelineEngine1F1B([stage0, stage1], loss_fn)
    loss, grads, tied_grads = eng.train_batch(params, mbs, labels, tied_params=tied)

    def direct(params, tied):
        total = 0.0
        for x, y in zip(mbs, labels):
            h = stage0(params[0], tied, x)
            out = stage1(params[1], tied, h)
            total = total + loss_fn(out, y)
        return total / M

    ref_tied = jax.grad(direct, argnums=1)(params, tied)
    np.testing.assert_allclose(np.asarray(tied_grads["embed"]),
                               np.asarray(ref_tied["embed"]), rtol=1e-4, atol=1e-5)


def test_1f1b_eval_batch():
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine1F1B
    S = 2
    params = _mk_stage_params(jax.random.PRNGKey(5), S)
    eng = PipelineEngine1F1B(_mk_stage_fns(S), lambda o, l: jnp.mean(o))
    mbs = [jnp.ones((2, HIDDEN)) for _ in range(3)]
    outs = eng.eval_batch(params, mbs)
    assert len(outs) == 3 and outs[0].shape == (2, HIDDEN)


@pytest.mark.slow
def test_compiled_pipeline_tied_embedding_grads():
    """Tied embed/unembed AROUND the compiled pipeline: one differentiable
    program, so the tied gradient sums both uses with no explicit allreduce."""
    topo = MeshTopology.from_axis_dict({"pipe": 4, "data": 2})
    set_topology(topo)
    params = _init_layers(jax.random.PRNGKey(6))
    stacked = restack_for_pipeline(params, 4)
    pipe = PipelineModule(_layer_fn, num_stages=4, topo=topo)
    M, mb = 4, 2
    rng = np.random.default_rng(7)
    tokens = jnp.asarray(rng.integers(0, HIDDEN, size=(M, mb)))
    embed = jnp.asarray(rng.normal(size=(HIDDEN, HIDDEN)).astype(np.float32) * 0.3)

    def loss(embed, stacked):
        x = embed[tokens]                      # tied use 1
        h = pipe(stacked, x)
        logits = h @ embed.T                   # tied use 2
        return jnp.mean(logits ** 2)

    def loss_plain(embed, params):
        x = embed[tokens]
        h = jax.vmap(lambda v: _reference_forward(params, v))(x)
        logits = h @ embed.T
        return jnp.mean(logits ** 2)

    g = jax.grad(loss)(embed, stacked)
    g_ref = jax.grad(loss_plain)(embed, params)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=1e-4, atol=1e-5)


def test_1f1b_epilogue_hooks_run_once():
    """grad_reduce_fn applies once per stage tree and optimizer_step_fn once
    per batch — not once per stage stream."""
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine1F1B
    S, M = 3, 4
    params = _mk_stage_params(jax.random.PRNGKey(8), S)
    mbs = [jnp.ones((2, HIDDEN)) for _ in range(M)]
    labels = [jnp.zeros((2, HIDDEN)) for _ in range(M)]
    calls = {"reduce": 0, "step": 0}

    def reduce_fn(g):
        calls["reduce"] += 1
        return g

    def step_fn(grads, tied):
        calls["step"] += 1

    eng = PipelineEngine1F1B(_mk_stage_fns(S), lambda o, l: jnp.mean((o - l) ** 2),
                             grad_reduce_fn=reduce_fn, optimizer_step_fn=step_fn)
    eng.train_batch(params, mbs, labels)
    assert calls["reduce"] == S  # once per stage gradient tree
    assert calls["step"] == 1


def test_pipeline_composes_with_tensor_parallel():
    """PP x TP: pipe-sharded layer stacks with tensor-sharded inner dims
    train through the engine on a pipe=2 x tensor=2 x data=2 mesh."""
    topo = MeshTopology.from_axis_dict({"pipe": 2, "tensor": 2, "data": 2})
    set_topology(topo)
    pipe = PipelineModule(_layer_fn, num_stages=2, topo=topo)
    params = {"pipe_layers": restack_for_pipeline(_init_layers(jax.random.PRNGKey(2)), 2),
              "head": jnp.zeros((HIDDEN, HIDDEN))}

    def rules(path, shape):
        if "pipe_layers" in path:
            return (0, "pipe")
        if path.endswith("head"):
            return (1, "tensor")
        return None

    def loss_fn(p, batch, rng):
        x = batch["x"]
        xm = x.reshape(2, x.shape[0] // 2, HIDDEN)
        out = pipe(p["pipe_layers"], xm).reshape(x.shape)
        pred = out @ p["head"].astype(out.dtype)
        return jnp.mean((pred - batch["y"].astype(pred.dtype)) ** 2).astype(jnp.float32)

    import deepspeed_tpu
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=loss_fn, model_parameters=params, topology=topo, tp_rules=rules,
        config={"train_micro_batch_size_per_gpu": 4, "gradient_accumulation_steps": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
                "zero_optimization": {"stage": 1}, "bf16": {"enabled": False}})
    assert "pipe" in str(engine.state.params["pipe_layers"]["w"].sharding.spec)
    assert "tensor" in str(engine.state.params["head"].sharding.spec)
    rng = np.random.default_rng(1)
    w_true = rng.normal(size=(HIDDEN, HIDDEN)).astype(np.float32) * 0.2
    x = rng.normal(size=(engine.train_batch_size, HIDDEN)).astype(np.float32)
    batch = {"x": x, "y": np.tanh(x @ w_true)}
    losses = [float(engine.train_batch(batch).loss) for _ in range(5)]
    assert losses[-1] < losses[0], losses
