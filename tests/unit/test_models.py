"""Model family tests: shape/grad sanity, HF parity for llama/gpt2 where the
baked-in transformers lib provides reference implementations (the reference's
inference tests compare against HF outputs, tests/unit/inference/test_inference.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import bert, gpt2, llama
from deepspeed_tpu.models.transformer import cross_entropy_loss, sdpa


def test_llama_forward_shapes():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16))
    logits = llama.forward(cfg, params, jnp.asarray(ids))
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 16))
    l1 = np.asarray(llama.forward(cfg, params, jnp.asarray(ids)))
    ids2 = ids.copy()
    ids2[0, 10] = (ids2[0, 10] + 1) % cfg.vocab_size
    l2 = np.asarray(llama.forward(cfg, params, jnp.asarray(ids2)))
    np.testing.assert_allclose(l1[0, :10], l2[0, :10], atol=1e-5)
    assert not np.allclose(l1[0, 10:], l2[0, 10:])


def test_llama_gqa_heads():
    cfg = llama.LlamaConfig.tiny(heads=4, kv_heads=2)
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    assert params["layers"]["attn"]["wk"].shape[-1] == 2 * (cfg.hidden_size // 4)
    ids = np.zeros((1, 8), np.int32)
    logits = llama.forward(cfg, params, jnp.asarray(ids))
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_hf_parity():
    """Logit parity against transformers' LlamaForCausalLM with copied weights."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig as HFConfig
    from transformers.models.llama.modeling_llama import LlamaForCausalLM

    cfg = llama.LlamaConfig.tiny(vocab=128, hidden=32, layers=2, heads=4, kv_heads=4, seq=32)
    hf_cfg = HFConfig(vocab_size=128, hidden_size=32, intermediate_size=64, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=4, max_position_embeddings=32,
                      rms_norm_eps=cfg.rms_eps, attention_bias=False, tie_word_embeddings=False,
                      rope_theta=cfg.rope_theta)
    hf = LlamaForCausalLM(hf_cfg).eval()

    # copy HF weights into our pytree
    sd = hf.state_dict()
    L, D = 2, 32

    def t2j(t):
        return jnp.asarray(t.detach().numpy())

    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    params["embed"] = t2j(sd["model.embed_tokens.weight"])
    params["lm_head"] = t2j(sd["lm_head.weight"]).T
    params["final_norm"] = t2j(sd["model.norm.weight"])
    for field, hf_name in [("wq", "self_attn.q_proj"), ("wk", "self_attn.k_proj"),
                           ("wv", "self_attn.v_proj"), ("wo", "self_attn.o_proj")]:
        params["layers"]["attn"][field] = jnp.stack(
            [t2j(sd[f"model.layers.{i}.{hf_name}.weight"]).T for i in range(L)])
    for field, hf_name in [("w_gate", "mlp.gate_proj"), ("w_up", "mlp.up_proj"), ("w_down", "mlp.down_proj")]:
        params["layers"]["mlp"][field] = jnp.stack(
            [t2j(sd[f"model.layers.{i}.{hf_name}.weight"]).T for i in range(L)])
    params["layers"]["attn_norm"] = jnp.stack([t2j(sd[f"model.layers.{i}.input_layernorm.weight"]) for i in range(L)])
    params["layers"]["mlp_norm"] = jnp.stack(
        [t2j(sd[f"model.layers.{i}.post_attention_layernorm.weight"]) for i in range(L)])

    ids = np.random.default_rng(0).integers(0, 128, (2, 16))
    ours = np.asarray(llama.forward(cfg, params, jnp.asarray(ids)))
    with torch.no_grad():
        theirs = hf(torch.tensor(ids)).logits.numpy()
    # HF applies rotary with interleaved vs half-split convention matching ours (half-split)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_gpt2_trains_with_engine():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=gpt2.make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "bf16": {"enabled": False},
        })
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (engine.train_batch_size, 32))
    batch = llama.causal_lm_batch(ids)
    losses = [float(engine.train_batch(batch).loss) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_bert_mlm_forward_and_mask():
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (2, 16))
    mask = np.ones((2, 16), np.int32)
    mask[1, 8:] = 0  # padded tail
    logits = bert.forward(cfg, params, jnp.asarray(ids), attention_mask=jnp.asarray(mask))
    assert logits.shape == (2, 16, cfg.vocab_size)
    # padded positions must not influence unpadded outputs
    ids2 = ids.copy()
    ids2[1, 12] = (ids2[1, 12] + 7) % cfg.vocab_size
    l2 = bert.forward(cfg, params, jnp.asarray(ids2), attention_mask=jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(logits[1, :8]), np.asarray(l2[1, :8]), atol=1e-5)


@pytest.mark.slow
def test_bert_trains_zero1():
    cfg = bert.BertConfig.tiny()
    params = bert.init_params(cfg, jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=bert.make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "bf16": {"enabled": False},
        })
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (engine.train_batch_size, 16))
    labels = np.full_like(ids, -100)
    labels[:, ::4] = ids[:, ::4]  # predict every 4th token
    losses = [float(engine.train_batch({"input_ids": ids, "labels": labels}).loss) for _ in range(6)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_llama_trains_zero3_bf16():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=llama.make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
            "zero_optimization": {"stage": 3, "param_persistence_threshold": 0},
        })
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (engine.train_batch_size, 32))
    batch = llama.causal_lm_batch(ids)
    losses = [float(engine.train_batch(batch).loss) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_cross_entropy_ignore_index():
    logits = jnp.zeros((1, 4, 8))
    labels = jnp.array([[1, -100, 2, -100]])
    loss = cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(8), rtol=1e-5)
