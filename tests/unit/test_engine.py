"""Engine end-to-end tests — analog of tests/unit/runtime/zero/test_zero.py's
core pattern: train sharded vs an unsharded single-device baseline and assert
numeric parity across ZeRO stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel import MeshTopology

from .simple_model import init_mlp_params, mlp_loss_fn, random_batch, random_dataset

HIDDEN = 16


def make_engine(stage=0, hidden=HIDDEN, fp16=False, gas=1, micro=2, extra_cfg=None, dtype_fp32=True):
    params = init_mlp_params(jax.random.PRNGKey(0), hidden=hidden)
    cfg = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 100,
    }
    if fp16:
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    elif dtype_fp32:
        cfg["bf16"] = {"enabled": False}  # full fp32 for exact parity checks
    if extra_cfg:
        cfg.update(extra_cfg)
    engine, opt, _, sched = deepspeed_tpu.initialize(loss_fn=mlp_loss_fn, model_parameters=params, config=cfg)
    return engine


def train_losses(engine, steps=8, seed=1):
    losses = []
    for s in range(steps):
        batch = random_batch(engine.train_batch_size, hidden=HIDDEN, seed=seed + s)
        m = engine.train_batch(batch)
        losses.append(float(m.loss))
    return losses


def test_training_reduces_loss():
    engine = make_engine(stage=0)
    losses = train_losses(engine, steps=10)
    assert losses[-1] < losses[0] * 0.9, f"loss did not decrease: {losses}"


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_parity_with_baseline(stage):
    """Sharded training must match the stage-0 (pure DP) result bit-for-bit-ish."""
    base = make_engine(stage=0)
    test = make_engine(stage=stage)
    base_losses = train_losses(base, steps=5)
    test_losses = train_losses(test, steps=5)
    np.testing.assert_allclose(base_losses, test_losses, rtol=2e-5, atol=1e-6)
    p0 = base.get_fp32_params()
    p1 = test.get_fp32_params()
    for k in p0:
        np.testing.assert_allclose(p0[k]["w"], p1[k]["w"], rtol=2e-5, atol=1e-6)


def test_zero_state_is_actually_sharded(mesh8):
    engine = make_engine(stage=1)
    # optimizer moment leaves must be partitioned over the data axis
    m_leaf = engine.state.opt_state.exp_avg["layer_0"]["w"]
    assert len(m_leaf.sharding.device_set) == 8
    spec = m_leaf.sharding.spec
    assert any(s is not None for s in spec), f"opt state not sharded: {spec}"


def test_zero3_params_sharded():
    engine = make_engine(stage=3, extra_cfg={"zero_optimization": {"stage": 3, "param_persistence_threshold": 0}})
    w = engine.state.params["layer_0"]["w"]
    assert any(s is not None for s in w.sharding.spec), f"params not sharded: {w.sharding.spec}"


def test_gradient_accumulation_equivalence():
    """gas=4 with micro=2 must match gas=1 with micro=8 (same global batch)."""
    e1 = make_engine(gas=1, micro=8)
    e2 = make_engine(gas=4, micro=2)
    l1 = train_losses(e1, steps=4)
    l2 = train_losses(e2, steps=4)
    np.testing.assert_allclose(l1, l2, rtol=2e-5, atol=1e-6)


def test_forward_backward_step_shim():
    engine = make_engine(gas=2, micro=2)
    for s in range(2):
        for g in range(2):
            mb = random_batch(engine.train_batch_size // 2, hidden=HIDDEN, seed=10 * s + g)
            engine.forward(mb)
            engine.backward()
        m = engine.step()
    assert engine.global_steps == 2
    with pytest.raises(RuntimeError):
        engine.step()  # no accumulated micro-batches


def test_fp16_dynamic_loss_scale_recovers():
    engine = make_engine(fp16=True, dtype_fp32=False)
    initial_scale = float(engine.state.loss_scale.cur_scale)
    assert initial_scale == 2.0**8
    losses = train_losses(engine, steps=6)
    assert np.isfinite(losses).all()


def test_fp16_overflow_skips_step():
    engine = make_engine(fp16=True, dtype_fp32=False)
    # poison a batch to produce inf loss -> overflow -> step skipped, scale halved
    batch = random_batch(engine.train_batch_size, hidden=HIDDEN, seed=0)
    batch["x"][0, 0] = 1e30
    scale_before = float(engine.state.loss_scale.cur_scale)
    step_before = int(engine.state.step)
    m = engine.train_batch(batch)
    assert bool(m.skipped)
    assert int(engine.state.step) == step_before
    assert float(engine.state.loss_scale.cur_scale) <= scale_before


def test_gradient_clipping():
    engine = make_engine(extra_cfg={"gradient_clipping": 0.1})
    batch = random_batch(engine.train_batch_size, hidden=HIDDEN, seed=0)
    batch["y"] = batch["y"] * 1000.0  # huge loss -> huge grads
    m = engine.train_batch(batch)
    assert np.isfinite(float(m.loss))


def test_checkpoint_roundtrip(tmp_path):
    engine = make_engine(stage=1)
    train_losses(engine, steps=3)
    tag = engine.save_checkpoint(str(tmp_path))
    p_before = engine.get_fp32_params()
    step_before = int(engine.state.step)

    engine2 = make_engine(stage=1)
    engine2.load_checkpoint(str(tmp_path))
    assert int(engine2.state.step) == step_before
    assert engine2.global_steps == engine.global_steps
    p_after = engine2.get_fp32_params()
    for k in p_before:
        np.testing.assert_array_equal(p_before[k]["w"], p_after[k]["w"])
    # continued training matches
    l1 = train_losses(engine, steps=2, seed=99)
    l2 = train_losses(engine2, steps=2, seed=99)
    np.testing.assert_allclose(l1, l2, rtol=1e-6)


def test_checkpoint_elastic_zero_stage_change(tmp_path):
    """Save at stage 1, resume at stage 3 — reshape-on-load (the reference needs
    universal checkpoints for this; native here)."""
    e1 = make_engine(stage=1)
    train_losses(e1, steps=3)
    e1.save_checkpoint(str(tmp_path))
    e3 = make_engine(stage=3)
    e3.load_checkpoint(str(tmp_path))
    p1 = e1.get_fp32_params()
    p3 = e3.get_fp32_params()
    np.testing.assert_allclose(p1["layer_0"]["w"], p3["layer_0"]["w"], rtol=1e-6)


def test_dataloader_integration():
    ds = random_dataset(n=64, hidden=HIDDEN)
    params = init_mlp_params(jax.random.PRNGKey(0), hidden=HIDDEN)
    engine, _, loader, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn,
        model_parameters=params,
        training_data=ds,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "bf16": {"enabled": False},
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        })
    assert loader is not None
    n = 0
    for batch in loader:
        engine.train_batch(batch)
        n += 1
    assert n == len(loader) == 64 // engine.train_batch_size


def test_eval_batch():
    engine = make_engine()
    batch = random_batch(8, hidden=HIDDEN, seed=0)
    loss = engine.eval_batch(batch)
    assert np.isfinite(float(loss))


def test_save_16bit_model(tmp_path, mesh8):
    import deepspeed_tpu
    from .simple_model import init_mlp_params, mlp_loss_fn, random_batch
    params = init_mlp_params(jax.random.PRNGKey(0), hidden=32, nlayers=2)
    eng, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, model_parameters=params, topology=mesh8,
        config={"train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3}})
    eng.train_batch(random_batch(eng.train_batch_size, 32, seed=0))
    path = eng.save_16bit_model(str(tmp_path))
    from safetensors.numpy import load_file
    loaded = load_file(path)
    assert "layer_0.w" in loaded
    w = loaded["layer_0.w"]
    assert w.shape == (32, 32)
    # compute dtype (bf16 default) round-trips through safetensors
    assert w.dtype == np.asarray(jnp.zeros((), eng.compute_dtype)).dtype
    # values match the live fp32 master within cast tolerance
    master = eng.get_fp32_params()["layer_0"]["w"]
    np.testing.assert_allclose(np.asarray(w, np.float32), master, atol=2e-2, rtol=2e-2)


def test_monitor_events_beyond_loss_lr(tmp_path):
    """_maybe_report must emit grad_norm / throughput / telemetry-derived
    events, not just train_loss + lr (ISSUE 1: engine self-reporting)."""

    class SpyMonitor:
        def __init__(self):
            self.events = []

        def write_events(self, events):
            self.events.extend(events)

    engine = make_engine(extra_cfg={
        "steps_per_print": 1,
        "telemetry": {"jsonl_path": str(tmp_path / "t.jsonl"),
                      "peak_flops_per_chip": 1e12},
    })
    spy = SpyMonitor()
    engine.monitor = spy
    engine.telemetry.monitor = spy
    train_losses(engine, steps=4)
    tags = {t for t, _, _ in spy.events}
    assert "Train/Samples/train_loss" in tags and "Train/Samples/lr" in tags
    for expected in ("Train/Samples/grad_norm", "Train/Samples/step_time_ms",
                     "Train/Samples/samples_per_sec", "Train/Samples/tokens_per_sec",
                     "Train/Samples/mfu"):
        assert expected in tags, f"missing monitor event {expected}: {sorted(tags)}"
    # events carry the sample count as the step axis (reference Train/Samples/*)
    loss_events = [(v, s) for t, v, s in spy.events if t == "Train/Samples/train_loss"]
    assert [s for _, s in loss_events] == [engine.train_batch_size * (i + 1) for i in range(4)]


def test_wall_clock_breakdown_logs(mesh8):
    import deepspeed_tpu
    from .simple_model import init_mlp_params, mlp_loss_fn, random_batch
    params = init_mlp_params(jax.random.PRNGKey(0), hidden=16, nlayers=1)
    eng, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=mlp_loss_fn, model_parameters=params, topology=mesh8,
        config={"train_micro_batch_size_per_gpu": 1, "steps_per_print": 2,
                "wall_clock_breakdown": True,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}})
    import io
    import logging
    from deepspeed_tpu.utils.logging import logger as ds_logger
    buf = io.StringIO()
    h = logging.StreamHandler(buf)
    ds_logger.addHandler(h)
    try:
        for i in range(2):
            eng.train_batch(random_batch(eng.train_batch_size, 16, seed=i))
    finally:
        ds_logger.removeHandler(h)
    assert "wall clock breakdown" in buf.getvalue()
