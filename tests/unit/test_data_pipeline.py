"""Indexed dataset + data analyzer tests (reference analog:
tests/unit/runtime/test_data.py + data_analyzer usage in data_sampling)."""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (DataAnalyzer, MMapIndexedDataset,
                                                 MMapIndexedDatasetBuilder,
                                                 best_fitting_dtype, dataset_exists)


def _build_corpus(prefix, samples, dtype=np.int32, docs_at=()):
    b = MMapIndexedDatasetBuilder(str(prefix), dtype=dtype)
    for i, s in enumerate(samples):
        b.add_item(s)
        if i in docs_at:
            b.end_document()
    b.end_document()
    b.finalize()


def test_roundtrip_and_zero_copy(tmp_path):
    samples = [np.arange(n, dtype=np.int32) for n in (3, 7, 1, 12)]
    _build_corpus(tmp_path / "corpus", samples)
    ds = MMapIndexedDataset(str(tmp_path / "corpus"))
    assert len(ds) == 4
    for got, want in zip(ds[:], samples):
        np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(ds.sizes, [3, 7, 1, 12])
    assert ds.num_tokens(3) == 12
    # windowed read
    np.testing.assert_array_equal(ds.get(3, offset=2, length=4), [2, 3, 4, 5])
    with pytest.raises(IndexError):
        ds.get(0, offset=2, length=5)
    with pytest.raises(IndexError):
        ds[4]
    assert dataset_exists(str(tmp_path / "corpus"))


def test_format_header_fields(tmp_path):
    """The idx header is byte-compatible MMIDIDX v1 (interop with corpora
    produced by Megatron/DeepSpeed tooling)."""
    _build_corpus(tmp_path / "c", [np.array([1, 2], np.uint16)], dtype=np.uint16)
    raw = open(str(tmp_path / "c.idx"), "rb").read()
    assert raw[:9] == b"MMIDIDX\x00\x00"
    import struct
    assert struct.unpack("<Q", raw[9:17])[0] == 1      # version
    assert struct.unpack("<B", raw[17:18])[0] == 8     # uint16 code
    assert struct.unpack("<Q", raw[18:26])[0] == 1     # num sequences


def test_doc_idx_boundaries(tmp_path):
    _build_corpus(tmp_path / "d", [np.ones(2, np.int32)] * 5, docs_at=(1, 3))
    ds = MMapIndexedDataset(str(tmp_path / "d"))
    np.testing.assert_array_equal(ds.doc_idx, [0, 2, 4, 5])


def test_merge_file(tmp_path):
    _build_corpus(tmp_path / "a", [np.array([1, 2], np.int32)])
    _build_corpus(tmp_path / "b", [np.array([3], np.int32), np.array([4, 5, 6], np.int32)])
    m = MMapIndexedDatasetBuilder(str(tmp_path / "merged"), dtype=np.int32)
    m.merge_file_(str(tmp_path / "a"))
    m.merge_file_(str(tmp_path / "b"))
    m.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "merged"))
    assert len(ds) == 3
    np.testing.assert_array_equal(ds[0], [1, 2])
    np.testing.assert_array_equal(ds[2], [4, 5, 6])


def test_best_fitting_dtype():
    assert best_fitting_dtype(30000) == np.uint16
    assert best_fitting_dtype(100000) == np.int32
    assert best_fitting_dtype(None) == np.int32


def test_data_analyzer_map_reduce(tmp_path):
    """Two-worker map + reduce: seqlen metric indexes every sample; sum
    metric accumulates corpus-wide."""
    samples = [np.arange(n, dtype=np.int32) for n in (5, 3, 5, 8, 3, 5)]
    _build_corpus(tmp_path / "corpus", samples)
    ds = MMapIndexedDataset(str(tmp_path / "corpus"))
    save = str(tmp_path / "analysis")

    def make(worker_id):
        return DataAnalyzer(ds, ["seqlen", "total_tokens"],
                            [len, len], ["single_value_per_sample",
                                         "accumulate_value_over_samples"],
                            save_path=save, num_workers=2, worker_id=worker_id)

    make(0).run_map()
    make(1).run_map()
    make(0).run_reduce()

    s2m = DataAnalyzer.load_sample_to_metric(save, "seqlen")
    np.testing.assert_array_equal(s2m, [5, 3, 5, 8, 3, 5])
    m2s = DataAnalyzer.load_metric_to_sample(save, "seqlen")
    np.testing.assert_array_equal(m2s[5], [0, 2, 5])
    np.testing.assert_array_equal(m2s[3], [1, 4])
    import json
    total = json.load(open(save + "/total_tokens_sum.json"))["sum"]
    assert total == sum(len(s) for s in samples)
    pct = DataAnalyzer.get_metric_percentiles(save, "seqlen", [50.0, 100.0])
    assert pct[100.0] == 8.0


def test_analyzer_feeds_curriculum(tmp_path):
    """The analyzer's difficulty index drives a curriculum bucket selection —
    the end-to-end data-efficiency flow."""
    samples = [np.zeros(n, np.int32) for n in (2, 4, 6, 8)]
    _build_corpus(tmp_path / "c", samples)
    ds = MMapIndexedDataset(str(tmp_path / "c"))
    save = str(tmp_path / "an")
    an = DataAnalyzer(ds, ["seqlen"], [len], ["single_value_per_sample"], save_path=save)
    an.run_map()
    an.run_reduce()
    m2s = DataAnalyzer.load_metric_to_sample(save, "seqlen")
    # curriculum at difficulty <= 6: only samples with seqlen <= 6 eligible
    eligible = sorted(i for v, idxs in m2s.items() if v <= 6 for i in idxs)
    assert eligible == [0, 1, 2]


def test_empty_dataset_and_idle_worker(tmp_path):
    """A zero-sample dataset opens; an idle analyzer worker's empty shard
    doesn't break the reduce."""
    b = MMapIndexedDatasetBuilder(str(tmp_path / "empty"), dtype=np.int32)
    b.end_document()
    b.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "empty"))
    assert len(ds) == 0
    # 3 samples over 4 workers: worker 3 gets an empty range
    samples = [np.zeros(2, np.int32)] * 3
    _build_corpus(tmp_path / "c3", samples)
    full = MMapIndexedDataset(str(tmp_path / "c3"))
    save = str(tmp_path / "an4")
    for w in range(4):
        DataAnalyzer(full, ["seqlen"], [len], ["single_value_per_sample"],
                     save_path=save, num_workers=4, worker_id=w).run_map()
    DataAnalyzer(full, ["seqlen"], [len], ["single_value_per_sample"],
                 save_path=save, num_workers=4, worker_id=0).run_reduce()
    np.testing.assert_array_equal(DataAnalyzer.load_sample_to_metric(save, "seqlen"),
                                  [2, 2, 2])


def test_float_metric_rejected(tmp_path):
    _build_corpus(tmp_path / "f", [np.zeros(3, np.int32)])
    ds = MMapIndexedDataset(str(tmp_path / "f"))
    an = DataAnalyzer(ds, ["rarity"], [lambda s: 0.5], ["single_value_per_sample"],
                      save_path=str(tmp_path / "anx"))
    with pytest.raises(ValueError, match="non-integral"):
        an.run_map()


def test_get_rejects_bad_offset(tmp_path):
    _build_corpus(tmp_path / "g", [np.arange(3, dtype=np.int32)])
    ds = MMapIndexedDataset(str(tmp_path / "g"))
    with pytest.raises(IndexError):
        ds.get(0, offset=10)  # offset past sample must not leak neighbors


# -------------------------------------------------- random-LTD engine wiring
def test_random_ltd_token_counts_follow_schedule():
    """The scoped LTD state really drops tokens: with keep=K configured, each
    MIDDLE layer's attention sees exactly K query tokens while the first and
    last layers see the full sequence (reference random-LTD keeps outer
    layers intact, data_routing/basic_layer.py)."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models import llama
    from deepspeed_tpu.models.transformer import scoped_random_ltd, sdpa

    S, K, L = 32, 8, 4
    cfg = llama.LlamaConfig.tiny(vocab=64, hidden=32, layers=L, heads=4, kv_heads=4, seq=S)
    cfg = type(cfg)(**{**cfg.__dict__, "remat": False})
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    seen = []

    def spy_attention(q, k, v, causal=True, mask=None, **kw):
        seen.append(q.shape[1])
        return sdpa(q, k, v, causal=causal, mask=mask, **kw)

    loss_fn = scoped_random_ltd(llama.make_loss_fn(cfg, attention_fn=spy_attention),
                                {"keep": K})
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, S))
    loss = loss_fn(params, llama.causal_lm_batch(ids), jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    # the L-2 middle layers share one traced scan body, so the spy records one
    # full-seq call (first layer), one K-token call (the scanned middles), and
    # one full-seq call (last layer)
    assert seen == [S, K, S], seen


@pytest.mark.slow
def test_random_ltd_reaches_engine_from_config():
    """data_efficiency.data_routing alone engages token dropping through
    initialize() (reference convert_to_random_ltd from config,
    data_routing/helper.py:11), and the kept-token budget ramps on the
    schedule with the engine re-jitting at each budget step."""
    import deepspeed_tpu
    import jax
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.models import transformer as tr
    from deepspeed_tpu.parallel import MeshTopology, reset_topology

    reset_topology()
    S = 32
    cfg = llama.LlamaConfig.tiny(vocab=64, hidden=32, layers=3, heads=4, kv_heads=4, seq=S)
    topo = MeshTopology.from_axis_dict({"data": 1}, devices=jax.devices()[:1])
    tr._CONFIGURED_LTD["engaged"] = False
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=llama.make_loss_fn(cfg),
        model_parameters=llama.init_params(cfg, jax.random.PRNGKey(0)),
        topology=topo,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "data_efficiency": {
                "enabled": True,
                "data_routing": {
                    "enabled": True,
                    "random_ltd": {"random_ltd_schedule": {
                        "min_value": 8, "max_value": 16,
                        "schedule_config": {"seq_per_step": 4, "require_steps": 4}}},
                },
            },
        })
    assert engine._ltd_state is not None and engine._ltd_state["keep"] == 8
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, S))
    batch = llama.causal_lm_batch(ids)
    keeps = []
    for _ in range(5):
        m = engine.train_batch(batch)
        assert np.isfinite(float(m.loss))
        keeps.append(engine._ltd_state["keep"])
    assert tr.configured_ltd_engaged()  # the forward actually routed through LTD
    # linear ramp 8 -> 16 over 4 steps, quantized to seq_per_step=4
    assert keeps == [8, 8, 12, 12, 16], keeps


def test_random_ltd_eval_is_rng_independent():
    """ADVICE r5 (medium): eval must measure the FULL model.  The engine's
    empty LTD pin is authoritative over the train wrapper initialize()
    installed, so eval loss is rng-independent and equals the no-LTD loss."""
    import deepspeed_tpu
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.models import transformer as tr
    from deepspeed_tpu.parallel import MeshTopology

    S = 32
    cfg = llama.LlamaConfig.tiny(vocab=64, hidden=32, layers=3, heads=4, kv_heads=4, seq=S)
    topo = MeshTopology.from_axis_dict({"data": 1}, devices=jax.devices()[:1])
    engine, _, _, _ = deepspeed_tpu.initialize(
        loss_fn=llama.make_loss_fn(cfg),
        model_parameters=llama.init_params(cfg, jax.random.PRNGKey(0)),
        topology=topo,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": False},
            "data_efficiency": {
                "enabled": True,
                "data_routing": {
                    "enabled": True,
                    "random_ltd": {"random_ltd_schedule": {
                        "min_value": 8, "max_value": 16,
                        "schedule_config": {"seq_per_step": 4, "require_steps": 4}}},
                },
            },
        })
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, S))
    batch = llama.causal_lm_batch(ids)
    engine.train_batch(batch)  # train path engages token dropping
    assert tr.configured_ltd_engaged()
    l1 = float(engine.eval_batch(batch, rng=jax.random.PRNGKey(1)))
    l2 = float(engine.eval_batch(batch, rng=jax.random.PRNGKey(2)))
    assert l1 == l2, f"eval loss depends on rng (LTD leaked into eval): {l1} vs {l2}"
    # and it matches the unwrapped full-model loss on the same params
    plain = llama.make_loss_fn(cfg)
    p32 = jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), engine.state.params)
    ref = float(plain(p32, batch, jax.random.PRNGKey(7)))
    np.testing.assert_allclose(l1, ref, rtol=1e-5)
